"""Load balancer: stdlib threading HTTP reverse proxy.

Reference parity: sky/serve/load_balancer.py (SkyServeLoadBalancer:22 —
proxy + retries across replicas + QPS reporting) and
load_balancing_policies.py (RoundRobinPolicy:89, LeastLoadPolicy:115).
Replica set comes from the serve DB (probed by the controller); QPS is
recorded there for the autoscaler.
"""

from __future__ import annotations

import argparse
import http.server
import itertools
import os
import socketserver
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from skypilot_tpu.serve import serve_state

_HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "host",
                "proxy-authenticate", "proxy-authorization", "te",
                "trailers", "upgrade"}


class Policy:
    def select(self, urls: List[str]) -> Optional[str]:
        raise NotImplementedError

    def done(self, url: str) -> None:
        pass


class RoundRobinPolicy(Policy):
    def __init__(self):
        self._counter = itertools.count()

    def select(self, urls):
        if not urls:
            return None
        return urls[next(self._counter) % len(urls)]


class LeastLoadPolicy(Policy):
    """Pick the replica with the fewest in-flight requests; break ties
    round-robin so sequential (zero-concurrency) traffic still spreads."""

    def __init__(self):
        self._load: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()

    def select(self, urls):
        if not urls:
            return None
        with self._lock:
            lowest = min(self._load.get(u, 0) for u in urls)
            tied = [u for u in urls if self._load.get(u, 0) == lowest]
            url = tied[next(self._rr) % len(tied)]
            self._load[url] = self._load.get(url, 0) + 1
        return url

    def done(self, url):
        with self._lock:
            self._load[url] = max(0, self._load.get(url, 1) - 1)


POLICIES = {"round_robin": RoundRobinPolicy, "least_load": LeastLoadPolicy}


def make_handler(service: str, policy: Policy, max_retries: int = 3):
    class ProxyHandler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _proxy(self):
            serve_state.record_request(service)
            body = None
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                body = self.rfile.read(length)
            urls = serve_state.ready_urls(service)
            tried = []
            self._response_started = False
            for _ in range(min(max_retries, max(len(urls), 1))):
                url = policy.select([u for u in urls if u not in tried])
                if url is None:
                    break
                tried.append(url)
                try:
                    self._forward(url, body)
                    policy.done(url)
                    return
                except Exception:  # noqa: BLE001 — try next replica
                    policy.done(url)
                    if self._response_started:
                        # Bytes already reached the client: a retry
                        # would corrupt the stream. Drop the connection
                        # so the client sees a clean truncation.
                        self.close_connection = True
                        return
            self.send_response(503)
            msg = b"no ready replicas"
            self.send_header("Content-Length", str(len(msg)))
            self.end_headers()
            self.wfile.write(msg)

        def _forward(self, base_url: str, body: Optional[bytes]):
            """Streaming reverse proxy: chunks reach the client AS the
            replica produces them (first streamed token is one prefill
            away, not one full generation — the TTFT that the serve
            bench measures goes through this path). Reference parity:
            sky/serve/load_balancer.py:174 StreamingResponse proxy.

            Retries happen only before the first forwarded byte; a 4xx
            from the replica is forwarded as-is (deterministic client
            error), while connect errors and 5xx raise to the retry
            loop in _proxy.
            """
            url = base_url + self.path
            headers = {k: v for k, v in self.headers.items()
                       if k.lower() not in _HOP_HEADERS}
            req = urllib.request.Request(url, data=body, headers=headers,
                                         method=self.command)
            try:
                resp = urllib.request.urlopen(req, timeout=120)
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500:
                    resp = e      # forward the replica's client error
                else:
                    raise
            with resp:
                self._response_started = True
                self.send_response(resp.status)
                length = resp.headers.get("Content-Length")
                for k, v in resp.headers.items():
                    if k.lower() not in _HOP_HEADERS | {"content-length"}:
                        self.send_header(k, v)
                chunked = length is None
                if chunked:
                    self.send_header("Transfer-Encoding", "chunked")
                else:
                    self.send_header("Content-Length", length)
                self.end_headers()
                # read1: return as soon as ANY data is available
                # (urllib decodes the upstream chunking; we re-frame
                # for our client). A full read() would buffer the
                # entire generation and destroy streaming TTFT.
                # (HTTPError bodies may lack read1 — tiny, read whole.)
                read1 = getattr(resp, "read1", None)
                while True:
                    chunk = read1(65536) if read1 else resp.read()
                    if not chunk:
                        break
                    if chunked:
                        self.wfile.write(f"{len(chunk):x}\r\n".encode())
                        self.wfile.write(chunk + b"\r\n")
                    else:
                        self.wfile.write(chunk)
                    self.wfile.flush()
                if chunked:
                    self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

        do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _proxy

        def log_message(self, *args):
            pass

    return ProxyHandler


class _ThreadingServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # Request bursts overflow the default listen backlog of 5 ->
    # connection resets before the handler ever runs.
    request_queue_size = 128
    # TLS context (None = plaintext). Sockets are wrapped PER
    # CONNECTION with a deferred handshake: wrapping the listening
    # socket would run the handshake inside accept() on the single
    # serve_forever thread, letting one silent client block the whole
    # LB (a one-connection DoS).
    ssl_context = None

    def get_request(self):
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False)
        return sock, addr

    def handle_error(self, request, client_address):
        import ssl
        import sys as _sys
        e = _sys.exc_info()[1]
        if isinstance(e, (ssl.SSLError, ConnectionError, TimeoutError)):
            return  # failed handshake / dropped client: not our bug
        super().handle_error(request, client_address)


def serve(service: str, port: int, policy_name: str = "least_load",
          certfile: Optional[str] = None, keyfile: Optional[str] = None):
    if bool(certfile) != bool(keyfile):
        raise ValueError("TLS needs BOTH certfile and keyfile")
    policy = POLICIES[policy_name]()
    httpd = _ThreadingServer(("0.0.0.0", port),
                             make_handler(service, policy))
    if certfile:
        # TLS terminates here; LB -> replica stays plaintext on the
        # cluster-internal network (reference: sky/serve TLS fields).
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(os.path.expanduser(certfile),
                            os.path.expanduser(keyfile))
        httpd.ssl_context = ctx
    httpd.serve_forever()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--service", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--policy", default="least_load",
                    choices=sorted(POLICIES))
    ap.add_argument("--tls-certfile", default=None)
    ap.add_argument("--tls-keyfile", default=None)
    args = ap.parse_args()
    serve(args.service, args.port, args.policy,
          certfile=args.tls_certfile, keyfile=args.tls_keyfile)


if __name__ == "__main__":
    main()
