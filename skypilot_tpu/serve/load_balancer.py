"""Load balancer: stdlib threading HTTP reverse proxy.

Reference parity: sky/serve/load_balancer.py (SkyServeLoadBalancer:22 —
proxy + retries across replicas + QPS reporting) and
load_balancing_policies.py (RoundRobinPolicy:89, LeastLoadPolicy:115).
Replica set comes from the serve DB (probed by the controller); QPS is
recorded there for the autoscaler.
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import http.server
import itertools
import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from skypilot_tpu import chaos
from skypilot_tpu.infer import adapters as adapters_lib
from skypilot_tpu.infer import qos as qos_lib
from skypilot_tpu.observability import health as health_lib
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.serve import serve_state

_HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "host",
                "proxy-authenticate", "proxy-authorization", "te",
                "trailers", "upgrade"}

# The LB's own telemetry (it used to be the one fleet hop with none):
# per-backend proxied counts by response code, and failed forward
# attempts that cost a retry/failover. Exposed on the LB's own
# `GET /metrics` (reserved path — proxied traffic never collides with
# it because replicas are addressed by the federation tier directly).
LB_PROXIED = metrics.counter(
    "skytpu_lb_proxied_total",
    "Requests proxied by the load balancer, by backend replica URL and "
    'response code (backend="none", code="503" when no replica '
    "answered)", labelnames=("backend", "code"))
LB_RETRIES = metrics.counter(
    "skytpu_lb_retries_total",
    "Forward attempts that failed and triggered failover to another "
    "replica (or the terminal 503), by backend",
    labelnames=("backend",))
LB_FAILOVERS = metrics.counter(
    "skytpu_lb_failovers_total",
    "Streaming /generate upstreams lost and failed over to a "
    'surviving replica, by phase ("connect" = died before any token '
    'streamed, "mid_stream" = died with tokens already committed; '
    "the resume replays prompt + committed tokens with the budget "
    "reduced, so the client sees one gapless sequence)",
    labelnames=("phase",))
LB_HANDOFFS = metrics.counter(
    "skytpu_lb_handoffs_total",
    "Disaggregated prefill->decode handoffs by outcome: ok (a decode-"
    "tier replica accepted the KV transfer), retry (a decode replica "
    "died mid-transfer and the export — held in LB memory — retried "
    "on a survivor), fallback (the two-tier flow was skipped: a tier "
    "was dark, the prefill replica answered a typed 409 "
    "handoff_ineligible, or the prefill tier was exhausted — the "
    "request served single-tier instead), failed (every decode "
    "replica refused; the client saw a typed 503)",
    labelnames=("result",))
HANDOFF_SECONDS = metrics.histogram(
    "skytpu_handoff_seconds",
    "Wall time of the LB->decode-tier handoff hop: POST /handoff to "
    "first upstream evidence (the first streamed chunk — the decode "
    "replica streams the committed tokens as soon as the import "
    "admits — or the full response for blocking requests)",
    buckets=metrics.latency_buckets())
LB_TIER_REQUESTS = metrics.counter(
    "skytpu_lb_tier_requests_total",
    'Requests the LB routed per disaggregation tier ("prefill" and '
    '"decode" tick once each for a completed two-tier request; '
    '"single" counts requests on a disaggregated service that fell '
    "back to the single-tier path)", labelnames=("tier",))


class _UpstreamPool:
    """Keep-alive sockets to replicas. A fresh TCP connect per proxied
    request costs a handshake on the TTFT path and a TIME_WAIT per
    request; streaming replicas speak HTTP/1.1 keep-alive, so sockets
    whose previous response was fully consumed are reusable."""

    def __init__(self):
        self._idle: Dict[Tuple[str, int], List[socket.socket]] = {}
        self._lock = threading.Lock()

    def get(self, addr: Tuple[str, int]) -> Tuple[socket.socket, bool]:
        """Returns (socket, reused). A reused socket may have gone
        stale (replica closed it while idle) — callers retry once on a
        fresh connect before failing over."""
        with self._lock:
            conns = self._idle.get(addr)
            if conns:
                return conns.pop(), True
        return socket.create_connection(addr, timeout=120), False

    def put(self, addr: Tuple[str, int], sock: socket.socket) -> None:
        with self._lock:
            self._idle.setdefault(addr, []).append(sock)


_POOL = _UpstreamPool()


class _ChunkedTracker:
    """Incremental chunked-framing parser over RAW spliced bytes: finds
    where the body ENDS (so the upstream socket can go back to the
    keep-alive pool) without ever copying or re-framing the payload."""

    def __init__(self):
        self._line = b""      # partial size/trailer line across reads
        self._data = 0        # bytes of current chunk (+CRLF) still due
        self._last = False    # saw the zero-size chunk
        self.done = False

    def feed(self, piece: bytes) -> None:
        i, n = 0, len(piece)
        while i < n and not self.done:
            if self._data:
                take = min(self._data, n - i)
                self._data -= take
                i += take
                continue
            j = piece.find(b"\n", i)
            if j < 0:
                self._line += piece[i:]
                return
            line = (self._line + piece[i:j]).strip()
            self._line = b""
            i = j + 1
            if self._last:
                # Trailer section: a blank line ends the body.
                if line == b"":
                    self.done = True
                continue
            if line == b"":
                continue          # CRLF between chunks
            size = int(line.split(b";")[0], 16)
            if size == 0:
                self._last = True
            else:
                self._data = size + 2   # chunk data + trailing CRLF


class _UpstreamError(Exception):
    """Deterministic non-200 answer on the failover stream path (a
    validation 4xx or a typed shed): carried out of the generator so
    the caller can forward it verbatim when nothing has streamed yet."""

    def __init__(self, status: int, headers, body: bytes):
        super().__init__(f"upstream {status}")
        self.status = status
        self.headers = headers
        self.body = body


class _ClientGone(Exception):
    """The DOWNSTREAM client hung up mid-stream: abort without burning
    failover attempts on replicas that cannot help."""


def _upstream_ndjson(base_url: str, path: str, payload: bytes,
                     req_headers):
    """POST a streaming ``/generate`` to one replica and yield parsed
    NDJSON objects as they arrive. ``http.client`` decodes the chunked
    framing here — the splice path's raw tracker never extracts payload
    bytes, and failover needs the token VALUES, not just the framing.
    Raises ``ConnectionError`` on connect failure, 5xx, or a connection
    that dies before the terminal chunk (a replica SIGKILL mid-stream
    surfaces as exactly that); :class:`_UpstreamError` carries any
    other non-200 so the caller can forward it. Sockets are not
    pooled on this path: a streaming generation holds its connection
    for the whole decode, so the handshake is noise next to it."""
    parts = urlsplit(base_url)
    conn = http.client.HTTPConnection(parts.hostname or "",
                                      parts.port or 80, timeout=120)
    try:
        hdrs = {k: v for k, v in req_headers.items()
                if k.lower() not in _HOP_HEADERS | {"content-length"}}
        try:
            conn.request("POST", path, body=payload, headers=hdrs)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            raise ConnectionError(
                f"upstream connect failed: {e}") from e
        if resp.status >= 500:
            raise ConnectionError(f"upstream {resp.status}")
        if resp.status != 200:
            raise _UpstreamError(resp.status, resp.getheaders(),
                                 resp.read())
        while True:
            try:
                line = resp.readline()
            except (OSError, http.client.HTTPException) as e:
                # Premature close before the terminal chunk: the
                # replica died mid-generation (IncompleteRead).
                raise ConnectionError(
                    f"upstream died mid-stream: {e}") from e
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue
    finally:
        conn.close()


def _post_json(base_url: str, path: str, payload: bytes,
               headers: Dict[str, str]):
    """One blocking JSON POST to a replica (the two-tier flow's
    ``/prefill`` and ``/handoff`` hops). Returns (status, headers,
    body); raises ``ConnectionError`` on connect failure or 5xx so the
    caller's retry loop walks to the next replica. Unpooled on
    purpose: a handoff payload is a one-shot multi-MB KV transfer,
    not TTFT-critical keep-alive traffic."""
    parts = urlsplit(base_url)
    conn = http.client.HTTPConnection(parts.hostname or "",
                                      parts.port or 80, timeout=120)
    try:
        try:
            conn.request("POST", path, body=payload, headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            raise ConnectionError(
                f"upstream connect failed: {e}") from e
        if resp.status >= 500:
            raise ConnectionError(f"upstream {resp.status}")
        return resp.status, resp.getheaders(), resp.read()
    finally:
        conn.close()


# Adapter-catalog routing (docs/serving.md §Adapter catalog): the
# service's published fine-tune names come from its spec (`service.
# adapters`), read off the serve DB with a short TTL so the proxy hot
# path pays one DB hit per window, not per request. None = the service
# publishes no catalog — names pass through untouched (the replica
# tier still 404s unknowns).
_ADAPTER_TTL_S = 5.0
_adapter_cache: Dict[str, Tuple[float, Optional[frozenset]]] = {}


def _service_adapters(service: str) -> Optional[frozenset]:
    now = time.monotonic()
    hit = _adapter_cache.get(service)
    if hit is not None and now - hit[0] < _ADAPTER_TTL_S:
        return hit[1]
    names = None
    rec = serve_state.get_service(service)
    if rec is not None:
        ads = (rec.get("spec") or {}).get("adapters")
        if isinstance(ads, dict) and ads:
            names = frozenset(str(k) for k in ads)
    _adapter_cache[service] = (now, names)
    return names


# Disaggregated prefill/decode serving (docs/serving.md §Disaggregated
# serving): the service's tier split comes from its spec, read off the
# serve DB with the same short TTL as the adapter catalog. None = the
# service is single-tier and /generate proxies as always.
_disagg_cache: Dict[str, Tuple[float, Optional[Dict[str, int]]]] = {}


def _service_disagg(service: str) -> Optional[Dict[str, int]]:
    now = time.monotonic()
    hit = _disagg_cache.get(service)
    if hit is not None and now - hit[0] < _ADAPTER_TTL_S:
        return hit[1]
    d = None
    rec = serve_state.get_service(service)
    if rec is not None:
        raw = (rec.get("spec") or {}).get("disaggregation")
        if isinstance(raw, dict) and raw:
            try:
                d = {str(k): int(v) for k, v in raw.items()}
            except (TypeError, ValueError):
                d = None
    _disagg_cache[service] = (now, d)
    return d


# Prefix-affinity routing: the LB computes the SAME chunk-aligned
# prefix digest the engine's PrefixIndex keys its resident KV prefixes
# by (infer/engine.py PrefixIndex._digest — blake2b-128 over
# salt + int64 token bytes), and rendezvous-hashes it onto a replica.
# Requests sharing a prompt-prefix family land on the replica that
# already holds that family's KV blocks, so the fleet-wide prefix hit
# rate approaches a single replica's instead of decaying ~1/N under
# load-spread routing. The chunk must match the replicas' prefill
# chunk for the digest to name a boundary the engine actually caches
# at — SKYTPU_PREFILL_CHUNK configures the fleet-wide value.
DEFAULT_PREFIX_CHUNK = 512


def prefix_affinity_key(tokens, chunk: Optional[int] = None,
                        salt: bytes = b"") -> Optional[bytes]:
    """Digest of the LONGEST chunk-aligned proper prefix of
    ``tokens`` — byte-for-byte the engine PrefixIndex digest of the
    same prefix under the same ``salt``. None = ineligible (prompt no
    longer than one chunk), mirroring ``PrefixIndex.eligible``.
    ``salt`` namespaces by adapter identity, exactly like the engine:
    two fine-tunes sharing a prompt must not share a routing family
    (their cached KV rows differ)."""
    if chunk is None:
        try:
            chunk = int(os.environ.get("SKYTPU_PREFILL_CHUNK",
                                       str(DEFAULT_PREFIX_CHUNK)))
        except ValueError:
            chunk = DEFAULT_PREFIX_CHUNK
    if chunk <= 0 or len(tokens) <= chunk:
        return None
    n = ((len(tokens) - 1) // chunk) * chunk
    import numpy as np
    return hashlib.blake2b(
        salt + np.asarray([int(t) for t in tokens[:n]],
                          np.int64).tobytes(),
        digest_size=16).digest()


def _ranked_urls(key: str, urls: List[str]) -> List[str]:
    """Rendezvous (highest-random-weight) order for ``key``: a stable
    per-key replica ranking that only reshuffles the keys owned by a
    replica that joins or leaves."""
    return sorted(urls, key=lambda u: hashlib.blake2b(
        (key + "|" + u).encode(), digest_size=8).digest(), reverse=True)


def _affinity_url(model_name: str, urls: List[str]) -> str:
    """Rendezvous pick: one fine-tune's traffic lands on the same
    replica while it is up — its device adapter pool stays warm — and
    fails over deterministically to the next-highest weight when it
    dies."""
    return _ranked_urls(model_name, urls)[0]


def _spill_margin() -> int:
    try:
        return max(0, int(os.environ.get("SKYTPU_LB_SPILL", "4")))
    except ValueError:
        return 4


def _affinity_pick(key: str, urls: List[str], policy: "Policy") -> str:
    """Rendezvous pick with LOAD SPILL: affinity pins a key's traffic
    to one replica, which under a hot key means one replica melts
    while its neighbours idle. Walk the rendezvous ranking and take
    the first replica whose live in-flight count is within
    ``SKYTPU_LB_SPILL`` of the least-loaded candidate — the pinned
    replica wins while healthy, and a hot spot spills to the NEXT
    deterministic choice (so spilled traffic still concentrates,
    keeping its cache-warmth second-best rather than random). Shared
    by adapter affinity and prefix affinity."""
    ranked = _ranked_urls(key, urls)
    floor = min(policy.load(u) for u in ranked)
    margin = _spill_margin()
    for u in ranked:
        if policy.load(u) <= floor + margin:
            return u
    return ranked[0]


class Policy:
    """Backend selection + live in-flight accounting. The load map
    lives on the BASE class because every pick path — policy select,
    adapter affinity, prefix affinity — must see one truth: the proxy
    ``acquire``s every pick and ``done``s it when the request
    finishes, and the affinity spill rule reads ``load`` to decide
    when a pinned replica is hot enough to spill past."""

    def __init__(self):
        self._load: Dict[str, int] = {}
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        raise NotImplementedError

    def acquire(self, url: str) -> None:
        with self._lock:
            self._load[url] = self._load.get(url, 0) + 1

    def done(self, url: str) -> None:
        with self._lock:
            self._load[url] = max(0, self._load.get(url, 1) - 1)

    def load(self, url: str) -> int:
        with self._lock:
            return self._load.get(url, 0)


class RoundRobinPolicy(Policy):
    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select(self, urls):
        if not urls:
            return None
        return urls[next(self._counter) % len(urls)]


class LeastLoadPolicy(Policy):
    """Pick the replica with the fewest in-flight requests; break ties
    round-robin so sequential (zero-concurrency) traffic still
    spreads. ``select`` only READS the load map — the proxy acquires
    after any pick (policy or affinity), so both paths count."""

    def __init__(self):
        super().__init__()
        self._rr = itertools.count()

    def select(self, urls):
        if not urls:
            return None
        with self._lock:
            lowest = min(self._load.get(u, 0) for u in urls)
            tied = [u for u in urls if self._load.get(u, 0) == lowest]
            return tied[next(self._rr) % len(tied)]


POLICIES = {"round_robin": RoundRobinPolicy, "least_load": LeastLoadPolicy}


def make_handler(service: str, policy: Policy, max_retries: int = 3,
                 qos: Optional[qos_lib.AdmissionController] = None):
    # Body-tenant extraction only pays when tenant identity can change
    # the admission outcome: with no per-tenant rates configured every
    # tenant is unlimited, so the proxy hot path must not JSON-decode a
    # hundreds-of-KB token body just to pick a metric label the model
    # server will attribute anyway (chaos plans still force the parse —
    # injected sheds match on the body tenant in tests).
    qos_rates_body_tenant = qos is not None and (
        bool(qos.cfg.tenants) or qos.cfg.default_rate > 0)
    # Mid-stream failover: streaming /generate requests leave the
    # raw-splice path (which can never retry once bytes have moved)
    # for a decoded NDJSON proxy that can resume a died-mid-stream
    # generation on a surviving replica. Greedy-only semantics — the
    # resume replays prompt + committed tokens, which is bit-identical
    # only under greedy decoding. On by default; SKYTPU_LB_FAILOVER=0
    # restores pure splice for streams.
    failover_on = os.environ.get("SKYTPU_LB_FAILOVER", "1") != "0"

    class ProxyHandler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _typed_reject(self, code: int, typed: dict,
                          retry_after_s: Optional[float] = 1.0) -> None:
            """A typed load-shed/overload/unknown-adapter response
            minted AT the LB (never forwarded): JSON body (+
            Retry-After for retryable sheds), counted under
            backend="none" so fleet dashboards see LB-minted rejects
            next to replica answers."""
            if code in (429, 503) and retry_after_s is None:
                # Every retryable LB-minted shed carries Retry-After:
                # a 429/503 without it strands well-behaved clients on
                # their slowest default backoff.
                retry_after_s = 1.0
            LB_PROXIED.labels(backend="none", code=str(code)).inc()
            body = json.dumps({"error": typed}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if retry_after_s is not None:
                self.send_header(
                    "Retry-After",
                    qos_lib.retry_after_header(retry_after_s))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _proxy(self):
            # The LB's own observability surface rides reserved paths
            # on the proxy port (plain GETs, never forwarded): the
            # federation tier scrapes /metrics, the health model
            # probes /healthz.
            route = self.path.split("?", 1)[0]
            if self.command == "GET" and route == "/metrics":
                return metrics.write_exposition(self)
            if self.command == "GET" and route == "/healthz":
                n_ready = len(serve_state.ready_urls(service))
                if n_ready:
                    return health_lib.write_healthz(
                        self, health_lib.HEALTHY,
                        reason=f"{n_ready} ready replicas")
                return health_lib.write_healthz(
                    self, health_lib.DEGRADED, reason="no ready replicas")
            # Chunked request bodies have no Content-Length; reading
            # them is unimplemented, and NOT reading them would leave
            # unread bytes on the keep-alive connection — the next
            # request would parse the stale body as its request line.
            if "chunked" in (self.headers.get("Transfer-Encoding")
                             or "").lower():
                self.close_connection = True
                return self._typed_reject(411, {
                    "type": "length_required",
                    "message": "chunked request bodies are not "
                               "supported; send Content-Length",
                }, retry_after_s=None)
            body = None
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                body = self.rfile.read(length)

            # The body parses AT MOST once per request, and only when
            # something needs a field out of it (tenant identity or an
            # adapter name) — never on the plain proxy hot path.
            parsed = {"fields": None, "done": False}

            def _body_json():
                if not parsed["done"]:
                    parsed["done"] = True
                    if body:
                        try:
                            parsed["fields"] = json.loads(body)
                        except (ValueError, UnicodeDecodeError):
                            parsed["fields"] = None
                return parsed["fields"]

            tenant = qos_lib.DEFAULT_TENANT
            if qos is not None and self.command == "POST":
                # Fleet-edge admission control: the same per-tenant
                # token buckets the model server runs, one hop
                # earlier — a hot tenant is shed HERE before it costs
                # a proxied connection and a replica inbox slot.
                # POST-only so the two tiers' buckets drain in step:
                # the model server admission-checks only POST
                # /generate, and a tenant's GET polls (dashboards,
                # /debug/flight) must not burn the quota its real
                # generation requests need. The body is read first so
                # the SDK path (tenant in the JSON body, no header)
                # lands in its own bucket, not a shared 'default' one
                # — and so a shed response leaves no unread body bytes
                # on the keep-alive connection.
                body_fields = None
                if (body and not self.headers.get(qos_lib.tenant_header())
                        and (qos_rates_body_tenant or chaos.active())):
                    body_fields = _body_json()
                tenant, _ = qos_lib.request_identity(
                    self.headers, body=body_fields, cfg=qos.cfg)
                try:
                    qos.admit(tenant)
                except qos_lib.ShedError as e:
                    return self._typed_reject(
                        e.http_status, e.typed_error,
                        retry_after_s=e.retry_after_s)
            # Adapter-catalog routing: the named fine-tune (header
            # first — the cheap proxy path — else the body's ``model``
            # field, parsed only when the service publishes a
            # catalog). Unknown names reject typed HERE, one hop
            # before they cost a proxied connection; known names get
            # replica AFFINITY below so each fine-tune's device pool
            # stays warm on one replica.
            model_name = None
            if self.command == "POST":
                model_name = self.headers.get(adapters_lib.MODEL_HEADER)
                known = _service_adapters(service)
                if model_name is None and known is not None and body:
                    fields = _body_json()
                    if isinstance(fields, dict):
                        model_name = fields.get("model")
                if model_name:
                    model_name = str(model_name).strip()[:128] or None
                if model_name and known is not None \
                        and model_name not in known:
                    return self._typed_reject(
                        404, {
                            "type": "unknown_adapter",
                            "adapter": model_name,
                            "service": service,
                            "message": f"unknown adapter "
                                       f"{model_name!r}",
                        }, retry_after_s=None)
            serve_state.record_request(service)
            urls = serve_state.ready_urls(service)
            # Prefix-affinity routing key (adapter-salted, like the
            # engine's index): parsed only for POST /generate bodies
            # with a token list — the family digest is worth one JSON
            # decode because landing on the warm replica saves the
            # whole prefill recompute.
            prefix_key = None
            if (self.command == "POST" and route == "/generate"
                    and body and os.environ.get(
                        "SKYTPU_LB_PREFIX_AFFINITY", "1") != "0"):
                fields = _body_json()
                if (isinstance(fields, dict)
                        and isinstance(fields.get("tokens"), list)):
                    try:
                        prefix_key = prefix_affinity_key(
                            fields["tokens"],
                            salt=(model_name or "").encode())
                    except (TypeError, ValueError):
                        prefix_key = None
            # Disaggregated two-tier flow: prefill tier computes the
            # prompt to one committed token, the KV export hops to a
            # decode-tier replica, the client sees one ordinary
            # /generate answer. Any ineligibility falls back to the
            # single-tier path below (preferring the decode tier).
            if (_service_disagg(service) is not None
                    and self.command == "POST"
                    and route == "/generate" and body):
                fields = _body_json()
                if (isinstance(fields, dict)
                        and isinstance(fields.get("tokens"), list)):
                    handled, fallback = self._proxy_disagg(
                        fields, model_name, prefix_key)
                    if handled:
                        return
                    LB_TIER_REQUESTS.labels(tier="single").inc()
                    if fallback:
                        urls = fallback
            if (failover_on and self.command == "POST"
                    and route == "/generate" and body
                    and b'"stream"' in body):
                # Cheap byte scan first (the hot path must not JSON-
                # decode every body), then a real parse to confirm.
                fields = _body_json()
                if (isinstance(fields, dict) and fields.get("stream")
                        and isinstance(fields.get("tokens"), list)):
                    return self._proxy_stream(urls, fields, model_name,
                                              tenant, prefix_key)
            tried = []
            self._response_started = False
            for _ in range(min(max_retries, max(len(urls), 1))):
                cand = [u for u in urls if u not in tried]
                url = self._pick_backend(cand, model_name, prefix_key)
                if url is None:
                    break
                tried.append(url)
                try:
                    code = self._forward(url, body)
                    policy.done(url)
                    LB_PROXIED.labels(backend=url, code=str(code)).inc()
                    return
                except Exception:  # noqa: BLE001 — try next replica
                    policy.done(url)
                    LB_RETRIES.labels(backend=url).inc()
                    if self._response_started:
                        # Bytes already reached the client: a retry
                        # would corrupt the stream. Drop the connection
                        # so the client sees a clean truncation.
                        self.close_connection = True
                        return
            # Typed overload body (the model server's 503 shape): a
            # client distinguishes "back off and retry" from a replica
            # 5xx without parsing prose. With QoS on, the no-replica
            # bounce IS a shed — the qos-shed-rate SLO rule and the
            # `skytpu top` shed column must see the lb tier go dark,
            # not just its token-bucket rejects.
            if qos is not None:
                qos_lib.QOS_SHED.labels(
                    tenant=qos_lib.tenant_label(tenant, qos.cfg),
                    reason="overloaded", where="lb").inc()
            self._typed_reject(503, {
                "type": "overloaded",
                "message": "no ready replicas",
                "service": service,
            })

        def _pick_backend(self, cand: List[str],
                          model_name: Optional[str],
                          prefix_key: Optional[bytes]) -> Optional[str]:
            """One pick for every routing flavor. Precedence: prefix
            affinity (a warm KV family is the costliest thing to
            recompute) > adapter affinity > the configured policy;
            both affinities share the load-spill walk. Every
            successful pick is ``acquire``d — the caller owns the
            matching ``policy.done(url)``."""
            if not cand:
                return None
            if prefix_key is not None and len(cand) > 1:
                url = _affinity_pick(prefix_key.hex(), cand, policy)
            elif model_name and len(cand) > 1:
                url = _affinity_pick(model_name, cand, policy)
            else:
                url = policy.select(cand)
            if url is not None:
                policy.acquire(url)
            return url

        def _send_raw(self, status: int, headers, body: bytes) -> None:
            """Forward an upstream's buffered answer verbatim (minus
            hop headers; Content-Length recomputed)."""
            self.send_response(status)
            for k, v in headers:
                if (k.lower() not in _HOP_HEADERS
                        and k.lower() != "content-length"):
                    self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _proxy_stream(self, urls: List[str], fields: dict,
                          model_name: Optional[str], tenant: str,
                          prefix_key: Optional[bytes] = None) -> None:
            """Streaming ``/generate`` with MID-STREAM failover.

            The splice path drops the connection when a replica dies
            after the first forwarded byte — the client eats a
            truncated stream. Here the LB decodes the NDJSON lines,
            tracks every token that reached the client (``committed``),
            and when the upstream dies it replays ``prompt + committed``
            on a surviving replica with ``max_new_tokens`` reduced by
            what already streamed. Greedy decoding makes the resumed
            suffix bit-identical to what the dead replica would have
            produced, so the client sees ONE gapless, duplicate-free
            token sequence; the done line is patched to the stitched
            total and carries the failover count.
            """
            try:
                prompt = [int(t) for t in fields["tokens"]]
                budget = int(fields.get("max_new_tokens", 64))
            except (ValueError, TypeError):
                # The replica tier owns request validation: let one
                # replica mint the 400 (non-stream framing is fine —
                # a malformed body never streams).
                prompt, budget = [], 0
            committed: List[int] = []
            tried: List[str] = []
            failovers = 0
            headers_sent = False
            self._response_started = False

            def emit(obj: dict) -> None:
                nonlocal headers_sent
                data = json.dumps(obj).encode() + b"\n"
                try:
                    if not headers_sent:
                        headers_sent = True
                        self._response_started = True
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(data),
                                                        data))
                except ConnectionError as e:
                    raise _ClientGone() from e

            def finish(obj: dict, url: Optional[str]) -> None:
                obj["n_tokens"] = len(committed)
                obj["failovers"] = failovers
                emit(obj)
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except ConnectionError:
                    pass
                LB_PROXIED.labels(backend=url or "none",
                                  code="200").inc()

            try:
                for _ in range(min(max_retries, max(len(urls), 1))):
                    cand = [u for u in urls if u not in tried]
                    url = self._pick_backend(cand, model_name,
                                             prefix_key)
                    if url is None:
                        break
                    tried.append(url)
                    replay = dict(fields)
                    if committed:
                        replay["tokens"] = prompt + committed
                        replay["max_new_tokens"] = (budget
                                                    - len(committed))
                    try:
                        # Same literal point as the splice path: one
                        # chaos rule faults both forward flavors.
                        chaos.point("serve.lb.forward", backend=url)
                        for obj in _upstream_ndjson(
                                url, self.path,
                                json.dumps(replay).encode(),
                                self.headers):
                            if "done" in obj or "error" in obj:
                                policy.done(url)
                                return finish(obj, url)
                            toks = obj.get("tokens")
                            if toks:
                                committed.extend(int(t) for t in toks)
                            emit(obj)
                        # Stream ended with no done line and no
                        # exception: the replica still died on us.
                        raise ConnectionError(
                            "upstream ended without done line")
                    except _UpstreamError as e:
                        policy.done(url)
                        if not headers_sent:
                            # Deterministic non-stream answer (4xx
                            # validation / typed shed): forward
                            # verbatim, same contract as the splice
                            # path.
                            LB_PROXIED.labels(
                                backend=url, code=str(e.status)).inc()
                            self._send_raw(e.status, e.headers, e.body)
                            return
                        # A 4xx on the REPLAY (started stream): this
                        # candidate cannot resume us — treat it as
                        # lost and walk on.
                        LB_RETRIES.labels(backend=url).inc()
                        LB_FAILOVERS.labels(phase="mid_stream").inc()
                        failovers += 1
                    except ConnectionError:
                        policy.done(url)
                        LB_RETRIES.labels(backend=url).inc()
                        LB_FAILOVERS.labels(
                            phase=("mid_stream" if committed
                                   or headers_sent else
                                   "connect")).inc()
                        failovers += 1
                        if committed and len(committed) >= budget:
                            # The dead replica delivered the full
                            # budget but lost its done line: mint the
                            # trailer here rather than replaying a
                            # zero-budget generation.
                            return finish({"done": True,
                                           "lb_minted": True}, None)
            except _ClientGone:
                # Downstream hung up: nothing a surviving replica can
                # do. 499 mirrors the replica-side accounting.
                LB_PROXIED.labels(backend="none", code="499").inc()
                self.close_connection = True
                return
            if headers_sent:
                # Candidates exhausted mid-stream: end the chunked
                # body CLEANLY with an in-stream typed error line, so
                # the client sees a parseable failure, not a
                # truncation it must infer from framing.
                try:
                    emit({"error": {
                        "type": "upstream_lost",
                        "message": "replica lost mid-stream; no "
                                   "surviving replica could resume",
                        "n_streamed": len(committed),
                        "failovers": failovers}})
                    self.wfile.write(b"0\r\n\r\n")
                except (_ClientGone, ConnectionError):
                    pass
                LB_PROXIED.labels(backend="none", code="200").inc()
                return
            if qos is not None:
                qos_lib.QOS_SHED.labels(
                    tenant=qos_lib.tenant_label(tenant, qos.cfg),
                    reason="overloaded", where="lb").inc()
            self._typed_reject(503, {
                "type": "overloaded",
                "message": "no ready replicas",
                "service": service,
            })

        def _proxy_disagg(self, fields: dict,
                          model_name: Optional[str],
                          prefix_key: Optional[bytes]):
            """Two-tier disaggregated ``/generate``: POST ``/prefill``
            to a prefill-tier replica (chunked admission to ONE
            committed token + a paged-KV export), then hand the export
            to a decode-tier replica's ``/handoff``, which imports the
            blocks and resumes through the ordinary prefix-resume path
            — greedy output is bit-identical to single-tier. Both hops
            carry the same traceparent (minted here if the client sent
            none) so ``skytpu trace`` stitches one tree across tiers.

            Returns ``(handled, fallback_urls)``: handled=True means a
            response (or typed reject) went out; handled=False means
            the caller should run the single-tier path — over
            ``fallback_urls`` (the decode tier) when non-empty, so a
            fallback still avoids loading the prefill tier with
            decode work."""
            prefill_urls = serve_state.ready_urls(service,
                                                 tier="prefill")
            decode_urls = serve_state.ready_urls(service, tier="decode")
            single = decode_urls or None
            try:
                prompt = [int(t) for t in fields["tokens"]]
                budget = int(fields.get("max_new_tokens", 64))
            except (TypeError, ValueError):
                # The replica tier owns request validation.
                return False, single
            if not prefill_urls or not decode_urls:
                LB_HANDOFFS.labels(result="fallback").inc()
                return False, single
            if budget <= 1 or prefix_affinity_key(prompt) is None:
                # Cheap mirror of the replica's handoff_eligible
                # (prompt must exceed one chunk; >1 token budget) —
                # the replica's typed 409 stays authoritative for
                # what this can't see (paged pool off, index sizing).
                return False, single
            # One trace across both tiers: reuse the client's
            # traceparent, mint one otherwise, and send the SAME
            # header on the prefill and handoff hops.
            tp = self.headers.get("traceparent")
            if tracing.parse_traceparent(tp or "") is None:
                tp = tracing.format_traceparent(tracing.SpanContext(
                    tracing.new_trace_id(), tracing.new_span_id()))
            hdrs = {"Content-Type": "application/json",
                    "traceparent": tp}
            tenant_hdr = self.headers.get(qos_lib.tenant_header())
            if tenant_hdr:
                hdrs[qos_lib.tenant_header()] = tenant_hdr

            pre_payload = {"tokens": prompt, "max_new_tokens": budget}
            if model_name:
                pre_payload["model"] = model_name
            pre_body = json.dumps(pre_payload).encode()
            pre = None
            tried: List[str] = []
            for _ in range(min(max_retries, len(prefill_urls))):
                cand = [u for u in prefill_urls if u not in tried]
                url = self._pick_backend(cand, model_name, prefix_key)
                if url is None:
                    break
                tried.append(url)
                try:
                    chaos.point("serve.lb.forward", backend=url)
                    status, rhdrs, rbody = _post_json(
                        url, "/prefill", pre_body, hdrs)
                except (ConnectionError, chaos.ChaosError):
                    policy.done(url)
                    LB_RETRIES.labels(backend=url).inc()
                    continue
                policy.done(url)
                if status == 409:
                    # Typed handoff_ineligible (short prompt for the
                    # replica's chunk, prefix evicted before export,
                    # no paged pool): single-tier fallback.
                    LB_HANDOFFS.labels(result="fallback").inc()
                    return False, single
                if status != 200:
                    # Deterministic 4xx / typed shed: forward verbatim.
                    LB_PROXIED.labels(backend=url,
                                      code=str(status)).inc()
                    self._send_raw(status, rhdrs, rbody)
                    return True, None
                try:
                    pre = json.loads(rbody)
                except ValueError:
                    LB_RETRIES.labels(backend=url).inc()
                    continue
                break
            if not isinstance(pre, dict) or pre.get("export") is None:
                LB_HANDOFFS.labels(result="fallback").inc()
                return False, single
            LB_TIER_REQUESTS.labels(tier="prefill").inc()
            committed = [int(t) for t in pre.get("committed") or []]
            hbody = {"tokens": prompt, "committed": committed,
                     "export": pre["export"],
                     "max_new_tokens": budget}
            if model_name:
                hbody["model"] = model_name
            if fields.get("stream"):
                hbody["stream"] = True
                self._handoff_stream(decode_urls, hbody, model_name,
                                     prefix_key, hdrs)
                return True, None
            tried = []
            for _ in range(min(max_retries, len(decode_urls))):
                cand = [u for u in decode_urls if u not in tried]
                url = self._pick_backend(cand, model_name, prefix_key)
                if url is None:
                    break
                tried.append(url)
                t_hand = time.monotonic()
                try:
                    # The transfer itself is a chaos point: plans kill
                    # the decode replica mid-handoff and the export —
                    # held HERE in LB memory — retries on a survivor;
                    # the prefill tier keeps its refcounted copy
                    # either way (zero loss, zero leak).
                    chaos.point("handoff.transfer", backend=url)
                    status, rhdrs, rbody = _post_json(
                        url, "/handoff",
                        json.dumps(hbody).encode(), hdrs)
                except (ConnectionError, chaos.ChaosError):
                    policy.done(url)
                    LB_RETRIES.labels(backend=url).inc()
                    LB_HANDOFFS.labels(result="retry").inc()
                    continue
                policy.done(url)
                HANDOFF_SECONDS.observe(time.monotonic() - t_hand)
                LB_HANDOFFS.labels(result="ok").inc()
                LB_TIER_REQUESTS.labels(tier="decode").inc()
                LB_PROXIED.labels(backend=url, code=str(status)).inc()
                self._send_raw(status, rhdrs, rbody)
                return True, None
            LB_HANDOFFS.labels(result="failed").inc()
            self._typed_reject(503, {
                "type": "overloaded",
                "message": "no decode-tier replica accepted the "
                           "handoff",
                "service": service,
            })
            return True, None

        def _handoff_stream(self, decode_urls: List[str], hbody: dict,
                            model_name: Optional[str],
                            prefix_key: Optional[bytes],
                            hdrs: Dict[str, str]) -> None:
            """Streaming decode leg of the two-tier flow, with the
            same mid-stream failover contract as ``_proxy_stream`` —
            except the resume replays ``/handoff`` (the export payload
            lives HERE), with ``committed`` grown to every token the
            client has seen and the survivor's re-streamed seed tokens
            suppressed, so the client sees ONE duplicate-free
            sequence. The decode replica streams committed tokens from
            cursor zero, which is why the client's TTFT is the prefill
            tier's."""
            budget = int(hbody.get("max_new_tokens", 64))
            committed0 = [int(t) for t in hbody.get("committed") or []]
            received: List[int] = []   # tokens the client has
            tried: List[str] = []
            failovers = 0
            headers_sent = False
            obs_done = False
            self._response_started = False

            def emit(obj: dict) -> None:
                nonlocal headers_sent
                data = json.dumps(obj).encode() + b"\n"
                try:
                    if not headers_sent:
                        headers_sent = True
                        self._response_started = True
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding",
                                         "chunked")
                        self.end_headers()
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(data),
                                                        data))
                except ConnectionError as e:
                    raise _ClientGone() from e

            def finish(obj: dict, url: Optional[str]) -> None:
                obj = dict(obj)
                obj["n_tokens"] = len(received)
                obj["failovers"] = failovers
                emit(obj)
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except ConnectionError:
                    pass
                LB_PROXIED.labels(backend=url or "none",
                                  code="200").inc()

            try:
                for _ in range(min(max_retries, len(decode_urls))):
                    cand = [u for u in decode_urls if u not in tried]
                    url = self._pick_backend(cand, model_name,
                                             prefix_key)
                    if url is None:
                        break
                    tried.append(url)
                    replay = dict(hbody)
                    replay["committed"] = (list(received) if received
                                           else list(committed0))
                    # The survivor streams its committed seeds from
                    # cursor zero; the client already has these.
                    skip = len(received)
                    t_hand = time.monotonic()
                    try:
                        chaos.point("handoff.transfer", backend=url)
                        for obj in _upstream_ndjson(
                                url, "/handoff",
                                json.dumps(replay).encode(), hdrs):
                            if not obs_done:
                                obs_done = True
                                HANDOFF_SECONDS.observe(
                                    time.monotonic() - t_hand)
                                LB_HANDOFFS.labels(result="ok").inc()
                                LB_TIER_REQUESTS.labels(
                                    tier="decode").inc()
                            if "done" in obj or "error" in obj:
                                policy.done(url)
                                return finish(obj, url)
                            toks = [int(t) for t in
                                    obj.get("tokens") or []]
                            if skip and toks:
                                drop = min(skip, len(toks))
                                skip -= drop
                                toks = toks[drop:]
                            if not toks:
                                continue
                            received.extend(toks)
                            out = dict(obj)
                            out["tokens"] = toks
                            emit(out)
                        raise ConnectionError(
                            "upstream ended without done line")
                    except _UpstreamError as e:
                        policy.done(url)
                        if not headers_sent:
                            LB_PROXIED.labels(
                                backend=url, code=str(e.status)).inc()
                            self._send_raw(e.status, e.headers, e.body)
                            return
                        LB_RETRIES.labels(backend=url).inc()
                        LB_FAILOVERS.labels(phase="mid_stream").inc()
                        LB_HANDOFFS.labels(result="retry").inc()
                        failovers += 1
                    except (ConnectionError, chaos.ChaosError):
                        policy.done(url)
                        LB_RETRIES.labels(backend=url).inc()
                        LB_FAILOVERS.labels(
                            phase=("mid_stream" if headers_sent
                                   else "connect")).inc()
                        LB_HANDOFFS.labels(result="retry").inc()
                        failovers += 1
                        if received and len(received) >= budget:
                            # Full budget delivered, done line lost:
                            # mint the trailer rather than replaying
                            # a zero-budget generation.
                            return finish({"done": True,
                                           "lb_minted": True}, None)
            except _ClientGone:
                LB_PROXIED.labels(backend="none", code="499").inc()
                self.close_connection = True
                return
            LB_HANDOFFS.labels(result="failed").inc()
            if headers_sent:
                try:
                    emit({"error": {
                        "type": "upstream_lost",
                        "message": "decode replica lost mid-stream; "
                                   "no surviving replica could resume "
                                   "the handoff",
                        "n_streamed": len(received),
                        "failovers": failovers}})
                    self.wfile.write(b"0\r\n\r\n")
                except (_ClientGone, ConnectionError):
                    pass
                LB_PROXIED.labels(backend="none", code="200").inc()
                return
            self._typed_reject(503, {
                "type": "overloaded",
                "message": "no decode-tier replica accepted the "
                           "handoff",
                "service": service,
            })

        def _forward(self, base_url: str, body: Optional[bytes]):
            """Streaming reverse proxy, raw-splice edition: replica
            response bytes are forwarded VERBATIM — the chunked framing
            is tracked (to find the body's end for keep-alive reuse)
            but never decoded and re-encoded, so each upstream read
            costs exactly one downstream write. Upstream sockets are
            pooled keep-alive (no TCP handshake on the TTFT path).
            Reference parity: sky/serve/load_balancer.py:174
            StreamingResponse proxy.

            Retries happen only before the first forwarded byte; a 4xx
            from the replica is forwarded as-is (deterministic client
            error), while connect errors and 5xx raise to the retry
            loop in _proxy.
            """
            # Before the first byte moves: an injected fault lands in
            # _proxy's retry loop and triggers clean replica failover.
            chaos.point("serve.lb.forward", backend=base_url)
            parts = urlsplit(base_url)
            addr = (parts.hostname or "", parts.port or 80)
            hdrs = [f"{self.command} {self.path} HTTP/1.1",
                    f"Host: {parts.netloc}",
                    f"Content-Length: {len(body) if body else 0}"]
            hdrs += [f"{k}: {v}" for k, v in self.headers.items()
                     if k.lower() not in _HOP_HEADERS | {"content-length"}]
            payload = ("\r\n".join(hdrs) + "\r\n\r\n").encode() + (body
                                                                   or b"")
            # A pooled socket can be stale (replica closed it while
            # idle): retry exactly once on a FRESH connect — a failure
            # there is a real replica failure and raises to _proxy.
            # The retry attempt bypasses the pool entirely: after a
            # replica restart EVERY pooled socket is stale, and popping
            # another one would burn the retry without ever dialing.
            buf = b""
            for attempt in (0, 1):
                if attempt == 0:
                    sock, reused = _POOL.get(addr)
                else:
                    sock = socket.create_connection(addr, timeout=120)
                    reused = False
                try:
                    sock.sendall(payload)
                    while b"\r\n\r\n" not in buf:
                        piece = sock.recv(65536)
                        if not piece:
                            raise ConnectionError("upstream closed early")
                        buf += piece
                    break
                except OSError:
                    sock.close()
                    buf = b""
                    if not (reused and attempt == 0):
                        raise
            hdr_end = buf.index(b"\r\n\r\n") + 4
            head, rest = buf[:hdr_end - 4], buf[hdr_end:]
            status_line, *lines = head.split(b"\r\n")
            code = int(status_line.split()[1])
            if code >= 500:
                sock.close()
                raise ConnectionError(f"upstream {code}")
            resp_headers = []
            clen = None
            chunked = False
            upstream_close = False
            for ln in lines:
                k, _, v = ln.partition(b":")
                kl, v = k.strip().lower(), v.strip()
                if kl == b"content-length":
                    clen = int(v)
                elif kl == b"transfer-encoding":
                    chunked = b"chunked" in v.lower()
                elif kl == b"connection":
                    upstream_close = b"close" in v.lower()
                    continue
                elif kl in (b"keep-alive", b"proxy-authenticate", b"te",
                            b"trailers", b"upgrade"):
                    continue
                resp_headers.append(ln)
            # ONE write for status+headers+whatever body bytes arrived
            # with them (the replica's first token often rides the same
            # segment as the headers).
            self._response_started = True
            out = b"\r\n".join([status_line] + resp_headers)
            self.wfile.write(out + b"\r\n\r\n" + rest)
            if self.command == "HEAD" or code in (204, 304):
                remaining, tracker = 0, None
            elif chunked:
                remaining, tracker = None, _ChunkedTracker()
                tracker.feed(rest)
            elif clen is not None:
                remaining, tracker = clen - len(rest), None
            else:               # EOF-framed: splice to close, no reuse
                remaining, tracker = -1, None
                self.close_connection = True
            while ((tracker is not None and not tracker.done)
                   or (tracker is None and (remaining is None
                                            or remaining > 0
                                            or remaining == -1))):
                if tracker is None and remaining == 0:
                    break
                piece = sock.recv(65536)
                if not piece:
                    if remaining == -1:
                        break       # EOF IS the framing
                    raise ConnectionError("upstream closed mid-body")
                self.wfile.write(piece)
                if tracker is not None:
                    tracker.feed(piece)
                elif remaining > 0:
                    remaining -= len(piece)
            if upstream_close or remaining == -1:
                sock.close()
            else:
                _POOL.put(addr, sock)
            return code

        do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _proxy

        def log_message(self, *args):
            pass

    return ProxyHandler


class _ThreadingServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # Request bursts overflow the default listen backlog of 5 ->
    # connection resets before the handler ever runs.
    request_queue_size = 128
    # TLS context (None = plaintext). Sockets are wrapped PER
    # CONNECTION with a deferred handshake: wrapping the listening
    # socket would run the handshake inside accept() on the single
    # serve_forever thread, letting one silent client block the whole
    # LB (a one-connection DoS).
    ssl_context = None

    def get_request(self):
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False)
        return sock, addr

    def handle_error(self, request, client_address):
        import ssl
        import sys as _sys
        e = _sys.exc_info()[1]
        if isinstance(e, (ssl.SSLError, ConnectionError, TimeoutError)):
            return  # failed handshake / dropped client: not our bug
        super().handle_error(request, client_address)


def serve(service: str, port: int, policy_name: str = "least_load",
          certfile: Optional[str] = None, keyfile: Optional[str] = None):
    if bool(certfile) != bool(keyfile):
        raise ValueError("TLS needs BOTH certfile and keyfile")
    policy = POLICIES[policy_name]()
    httpd = _ThreadingServer(
        ("0.0.0.0", port),
        make_handler(service, policy,
                     qos=qos_lib.admission_from_env("lb")))
    if certfile:
        # TLS terminates here; LB -> replica stays plaintext on the
        # cluster-internal network (reference: sky/serve TLS fields).
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(os.path.expanduser(certfile),
                            os.path.expanduser(keyfile))
        httpd.ssl_context = ctx
    httpd.serve_forever()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--service", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--policy", default="least_load",
                    choices=sorted(POLICIES))
    ap.add_argument("--tls-certfile", default=None)
    ap.add_argument("--tls-keyfile", default=None)
    args = ap.parse_args()
    serve(args.service, args.port, args.policy,
          certfile=args.tls_certfile, keyfile=args.tls_keyfile)


if __name__ == "__main__":
    main()
