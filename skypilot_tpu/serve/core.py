"""SkyServe client ops: up/down/status.

Controller-as-task (reference: sky/serve/server/core.py — the client
launches a sky-serve-controller cluster and the controller + load
balancer run there, service.py:_start_service): the serve controller
cluster is provisioned through the framework's own launch path, the
controller and LB processes run on its head, and the service endpoint
is the HEAD's address on the LB port — publicly reachable wherever the
head is, never a client loopback.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import controller_utils, exceptions
from skypilot_tpu.backend import ClusterHandle
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.task import Task


def _controller_handle(create_for: Optional[Task] = None) -> ClusterHandle:
    return controller_utils.get_or_create_controller(
        controller_utils.SERVE_CONTROLLER_CLUSTER, "serve",
        exceptions.ServeError, create_for)


def _rpc(handle: ClusterHandle):
    return controller_utils.controller_rpc(handle)


def up(task: Task, service_name: str,
       lb_port: Optional[int] = None) -> Dict[str, Any]:
    if task.service is None:
        raise exceptions.ServeError(
            "task has no `service:` section; add one to serve it")
    handle = _controller_handle(create_for=task)
    task = controller_utils.translate_local_file_mounts(task, handle)
    spec_dict = task.service.to_yaml_config()
    result = _rpc(handle).call(
        "serve_up", service_name=service_name, spec=spec_dict,
        task_config=task.to_yaml_config(), lb_port=lb_port)
    host = controller_utils.controller_endpoint_host(handle)
    scheme = "https" if task.service.tls_certfile else "http"
    return {"name": service_name,
            "endpoint": f"{scheme}://{host}:{result['lb_port']}",
            "lb_port": result["lb_port"]}


def update(task: Task, service_name: str) -> Dict[str, Any]:
    """Rolling update: new-version replicas come up first; old ones are
    drained only once the new version is READY (zero downtime;
    reference: sky/serve/serve_utils.py version machinery)."""
    if task.service is None:
        raise exceptions.ServeError(
            "task has no `service:` section; add one to serve it")
    handle = _controller_handle()
    task = controller_utils.translate_local_file_mounts(task, handle)
    r = _rpc(handle).call(
        "serve_update", service_name=service_name,
        spec=task.service.to_yaml_config(),
        task_config=task.to_yaml_config())
    return {"name": service_name, "version": r["version"]}


def down(service_name: str, purge: bool = False) -> None:
    handle = _controller_handle()
    rpc = _rpc(handle)
    r = rpc.call("serve_down", service_name=service_name)
    if r.get("missing"):
        if purge:
            return
        raise exceptions.ServeError(f"no service {service_name!r}")
    # The controller notices SHUTTING_DOWN and tears replicas down;
    # wait for it (or its death), then reap the record.
    deadline = time.time() + 120
    while time.time() < deadline:
        rows = rpc.call("serve_status", service_name=service_name)
        if not rows:
            break
        status = ServiceStatus(rows[0]["status"])
        if status in (ServiceStatus.SHUTDOWN, ServiceStatus.FAILED):
            break
        # Re-probe liveness each pass: a controller that dies mid-
        # teardown must not make us wait out the full deadline.
        if not rows[0].get("controller_alive", True):
            break
        time.sleep(0.3 if handle.provider == "local" else 2.0)
    rpc.call("serve_remove", service_name=service_name)


def status(service_name: Optional[str] = None) -> List[Dict[str, Any]]:
    try:
        handle = _controller_handle()
    except exceptions.ServeError:
        return []
    rows = _rpc(handle).call("serve_status", service_name=service_name)
    out = []
    for s in rows:
        s = dict(s)
        s["status"] = ServiceStatus(s["status"])
        s["replicas"] = [
            dict(r, status=ReplicaStatus(r["status"]))
            for r in s.get("replicas", [])]
        out.append(s)
    return out


def wait_ready(service_name: str, timeout: float = 120,
               poll: Optional[float] = None) -> None:
    handle = _controller_handle()
    rpc = _rpc(handle)
    if poll is None:
        poll = 0.3 if handle.provider == "local" else 3.0
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = rpc.call("serve_status", service_name=service_name)
        if rows:
            st = ServiceStatus(rows[0]["status"])
            if st == ServiceStatus.READY:
                return
            if st.is_terminal():
                raise exceptions.ServeError(
                    f"service entered {st.value}")
        time.sleep(poll)
    raise TimeoutError(f"service {service_name} not READY in {timeout}s")
