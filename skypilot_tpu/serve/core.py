"""SkyServe client ops: up/down/status.

Reference parity: sky/serve/server/core.py.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.task import Task
from skypilot_tpu.utils import paths


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def up(task: Task, service_name: str,
       lb_port: Optional[int] = None) -> Dict[str, Any]:
    if task.service is None:
        raise exceptions.ServeError(
            "task has no `service:` section; add one to serve it")
    if serve_state.get_service(service_name) is not None:
        raise exceptions.ServeError(
            f"service {service_name!r} already exists")
    lb_port = lb_port or _free_port()
    spec_dict = {k: v for k, v in vars(task.service).items()}
    serve_state.add_service(service_name, spec_dict, task.to_yaml_config(),
                            lb_port)
    log = os.path.join(paths.logs_dir(),
                       f"serve-controller-{service_name}.log")
    with open(log, "ab") as f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.serve.controller",
             "--service", service_name],
            stdout=f, stderr=subprocess.STDOUT, start_new_session=True,
            env={**os.environ, "SKYPILOT_TPU_HOME": paths.home()})
    serve_state.set_controller_pid(service_name, proc.pid)
    return {"name": service_name, "endpoint": f"http://127.0.0.1:{lb_port}",
            "lb_port": lb_port}


def down(service_name: str, purge: bool = False) -> None:
    rec = serve_state.get_service(service_name)
    if rec is None:
        if purge:
            return
        raise exceptions.ServeError(f"no service {service_name!r}")
    serve_state.set_service_status(service_name, ServiceStatus.SHUTTING_DOWN)
    # Controller notices and tears everything down; wait briefly, then
    # reap the record.
    deadline = time.time() + 120
    pid = rec["controller_pid"]
    while time.time() < deadline:
        cur = serve_state.get_service(service_name)
        if cur is None or cur["status"] in (ServiceStatus.SHUTDOWN,
                                            ServiceStatus.FAILED):
            break
        if pid is not None:
            try:
                os.kill(pid, 0)
            except OSError:
                break  # controller is gone
        time.sleep(0.3)
    serve_state.remove_service(service_name)


def status(service_name: Optional[str] = None) -> List[Dict[str, Any]]:
    services = ([serve_state.get_service(service_name)]
                if service_name else serve_state.list_services())
    out = []
    for s in services:
        if s is None:
            continue
        out.append(dict(s, replicas=serve_state.list_replicas(s["name"])))
    return out


def wait_ready(service_name: str, timeout: float = 120) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        rec = serve_state.get_service(service_name)
        if rec and rec["status"] == ServiceStatus.READY:
            return
        if rec and rec["status"].is_terminal():
            raise exceptions.ServeError(
                f"service entered {rec['status'].value}")
        time.sleep(0.3)
    raise TimeoutError(f"service {service_name} not READY in {timeout}s")
