"""Request worker: executes one API request in its own process.

Reference parity: sky/server/requests/executor.py
(_request_execution_wrapper :222 — forked process per request, output
redirected to the per-request log).
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import traceback
from typing import Any, Dict

from skypilot_tpu.observability import tracing
from skypilot_tpu.server import requests_db
from skypilot_tpu.server.requests_db import RequestStatus


def _execute(name: str, payload: Dict[str, Any]) -> Any:
    import skypilot_tpu as sky
    from skypilot_tpu.task import Task

    if name == "launch":
        task = Task.from_yaml_config(payload["task"])
        job_id, handle = sky.launch(
            task, cluster_name=payload.get("cluster_name"),
            retry_until_up=payload.get("retry_until_up", False),
            idle_minutes_to_autostop=payload.get("idle_minutes_to_autostop"),
            down=payload.get("down", False))
        return {"job_id": job_id,
                "cluster_name": handle.cluster_name if handle else None}
    if name == "exec":
        task = Task.from_yaml_config(payload["task"])
        job_id, handle = sky.exec(task, cluster_name=payload["cluster_name"])
        return {"job_id": job_id, "cluster_name": handle.cluster_name}
    if name == "status":
        records = sky.status(payload.get("cluster_names"),
                             refresh=payload.get("refresh", False))
        return [{**r, "status": r["status"].value} for r in records]
    if name == "queue":
        jobs = sky.queue(payload["cluster_name"])
        return [{**j, "status": j["status"].value} for j in jobs]
    if name in ("stop", "start", "down"):
        getattr(sky, name)(payload["cluster_name"])
        return {"ok": True}
    if name == "autostop":
        sky.autostop(payload["cluster_name"], payload["idle_minutes"],
                     payload.get("down", False))
        return {"ok": True}
    if name == "cancel":
        sky.cancel(payload["cluster_name"], payload["job_id"])
        return {"ok": True}
    if name == "cost_report":
        return sky.cost_report()
    if name == "jobs.launch":
        from skypilot_tpu.jobs import core as jobs_core
        task = Task.from_yaml_config(payload["task"])
        return {"job_id": jobs_core.launch(task, name=payload.get("name"))}
    if name == "jobs.queue":
        from skypilot_tpu.jobs import core as jobs_core
        return [{**r, "status": r["status"].value}
                for r in jobs_core.queue()]
    if name == "jobs.cancel":
        from skypilot_tpu.jobs import core as jobs_core
        jobs_core.cancel(payload["job_id"])
        return {"ok": True}
    if name == "serve.up":
        from skypilot_tpu.serve import core as serve_core
        task = Task.from_yaml_config(payload["task"])
        return serve_core.up(task, payload["service_name"],
                             lb_port=payload.get("lb_port"))
    if name == "serve.status":
        from skypilot_tpu.serve import core as serve_core
        out = []
        for s in serve_core.status(payload.get("service_name")):
            out.append({**s, "status": s["status"].value,
                        "replicas": [{**r, "status": r["status"].value}
                                     for r in s["replicas"]]})
        return out
    if name == "serve.down":
        from skypilot_tpu.serve import core as serve_core
        serve_core.down(payload["service_name"],
                        purge=payload.get("purge", False))
        return {"ok": True}
    raise ValueError(f"unknown request name {name!r}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--request-id", required=True)
    args = ap.parse_args()
    tracing.set_process_name("worker")
    rec = requests_db.get(args.request_id)
    if rec is None:
        sys.exit(1)
    log = requests_db.log_path(args.request_id)
    with open(log, "a", buffering=1) as f, \
            contextlib.redirect_stdout(f), contextlib.redirect_stderr(f):
        try:
            # Child of the request span the server injected via
            # SKYTPU_TRACEPARENT; every RPC span below nests in here.
            with tracing.start_span(
                    f"worker.execute:{rec['name']}",
                    attrs={"request_id": args.request_id}):
                result = _execute(rec["name"], rec["payload"])
            requests_db.finish(args.request_id, RequestStatus.SUCCEEDED,
                               result=result)
        except Exception as e:  # noqa: BLE001 — report to the client
            tracing.add_event(
                "worker.error",
                attrs={"request_id": args.request_id,
                       "error_type": type(e).__name__,
                       "message": str(e)[:500]},
                echo=True)
            traceback.print_exc()
            requests_db.finish(args.request_id, RequestStatus.FAILED,
                               error=f"{type(e).__name__}: {e}")
            sys.exit(1)


if __name__ == "__main__":
    main()
