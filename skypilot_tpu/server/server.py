"""The API server: REST over stdlib HTTP with an async request executor.

Reference parity: sky/server/server.py (FastAPI app :145; every mutating
endpoint creates a request record and returns its id) + the executor
model of sky/server/requests/executor.py (few long-request workers, many
short ones — here: a bounded dispatcher that runs each request as its
own worker subprocess, so a crashing request never takes the server
down).

Endpoints:
  POST /launch /exec /status /queue /stop /start /down /autostop /cancel
       /cost_report /jobs/launch /jobs/queue /jobs/cancel
       /serve/up /serve/status /serve/down     -> {"request_id": ...}
  GET  /api/get?request_id=X                   -> request record (result)
  GET  /api/stream?request_id=X                -> request log (text)
  GET  /api/status                             -> recent requests
  POST /api/cancel                             -> cancel a request
  GET  /api/health                             -> {"status": "healthy"}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer
from socketserver import ThreadingMixIn
from typing import Any, Dict, Optional

from skypilot_tpu.observability import aggregate, health, metrics, slo, \
    tracing
from skypilot_tpu.server import requests_db
from skypilot_tpu.server.requests_db import RequestStatus
from skypilot_tpu.utils import paths

API_REQUESTS = metrics.counter(
    "skytpu_api_requests_total",
    "API-server requests accepted for async execution, by endpoint",
    labelnames=("endpoint",))
API_REQUESTS_FINISHED = metrics.counter(
    "skytpu_api_requests_finished_total",
    "Async API requests reaped by the executor, by final status",
    labelnames=("status",))
API_WORKERS_BUSY = metrics.gauge(
    "skytpu_api_workers_busy",
    "Worker subprocesses currently executing API requests")
API_REQUEST_SECONDS = metrics.histogram(
    "skytpu_api_request_seconds",
    "Async API request wall time, dispatch to worker exit, by endpoint",
    labelnames=("endpoint",),
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             300.0, 1800.0))

MAX_CONCURRENT_REQUESTS = int(os.environ.get("SKYTPU_API_WORKERS", "8"))
# Terminal requests older than this are garbage-collected (logs too).
REQUEST_TTL_S = float(os.environ.get("SKYTPU_API_REQUEST_TTL_HOURS",
                                     "168")) * 3600
_GC_INTERVAL_S = 600
# SLO watchdog cadence (seconds between fleet snapshots).
SLO_INTERVAL_S = float(os.environ.get("SKYTPU_SLO_INTERVAL", "15"))
# Per-endpoint scrape timeout for /metrics/fleet federation passes.
FLEET_SCRAPE_TIMEOUT_S = float(
    os.environ.get("SKYTPU_FLEET_SCRAPE_TIMEOUT", "2"))

# The fleet-facing singletons, installed by serve() (and by the test
# fixtures that assemble Executor/_Server by hand): the handler checks
# executor liveness for its own healthz and reads the watchdog's
# active alerts for /api/fleet/health.
_EXECUTOR: Optional["Executor"] = None
_WATCHDOG: Optional[slo.Watchdog] = None


def fleet_snapshot() -> aggregate.FleetSnapshot:
    """One federation pass over everything this server can see — its
    own registry plus every discovered fleet endpoint."""
    return aggregate.federate(
        aggregate.discover_endpoints(self_text=metrics.render),
        timeout=FLEET_SCRAPE_TIMEOUT_S)


def _api_self_health() -> Dict[str, Any]:
    ok = _EXECUTOR is not None and _EXECUTOR.is_alive()
    return health.component(
        "api-server", "self",
        health.HEALTHY if ok else health.DEGRADED,
        reason="" if ok else "request executor thread not running")


def fleet_health() -> Dict[str, Any]:
    """The /api/fleet/health payload: component table + rollup +
    whatever alerts the watchdog currently holds."""
    components = health.fleet_health(api_self=_api_self_health())
    return {"status": health.worst(components),
            "components": components,
            "alerts": (_WATCHDOG.active_alerts()
                       if _WATCHDOG is not None else [])}


def start_watchdog(interval_s: float = SLO_INTERVAL_S,
                   rules: Optional[list] = None) -> slo.Watchdog:
    """Install (or return) the server's SLO watchdog: federated
    snapshot + health model every interval, typed slo.breach /
    slo.recovered events on transitions."""
    global _WATCHDOG
    if _WATCHDOG is None:
        _WATCHDOG = slo.Watchdog(
            rules=rules, interval_s=interval_s,
            snapshot_fn=lambda: (
                fleet_snapshot().families,
                health.fleet_health(api_self=_api_self_health())))
    _WATCHDOG.start()
    return _WATCHDOG

_ENDPOINTS = {
    "/launch": "launch", "/exec": "exec", "/status": "status",
    "/queue": "queue", "/stop": "stop", "/start": "start", "/down": "down",
    "/autostop": "autostop", "/cancel": "cancel",
    "/cost_report": "cost_report",
    "/jobs/launch": "jobs.launch", "/jobs/queue": "jobs.queue",
    "/jobs/cancel": "jobs.cancel",
    "/serve/up": "serve.up", "/serve/status": "serve.status",
    "/serve/down": "serve.down",
}


class Executor(threading.Thread):
    """Dispatches NEW requests to worker subprocesses, bounded."""

    def __init__(self):
        super().__init__(daemon=True)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._spawned_at: Dict[str, tuple] = {}   # rid -> (name, t0)
        self._stop = threading.Event()
        self._last_gc = 0.0
        # Healthz wiring: the newest executor is the one /healthz
        # liveness-checks (tests construct Executor + handler by hand,
        # so registration can't live only in serve()).
        global _EXECUTOR
        _EXECUTOR = self

    def run(self) -> None:
        while not self._stop.is_set():
            self._reap()
            try:
                # Heartbeat flush: any buffered span/event (request
                # spans recorded at reap, GC events) becomes durable
                # within ~5s without paying a whole-buffer rewrite per
                # reaped request — the throttle skips clean/fresh
                # buffers at the cost of two compares.
                tracing.flush_periodic(min_new_records=256,
                                       max_age_s=5.0)
            except OSError:
                pass   # unwritable events dir must not kill the loop
            if time.time() - self._last_gc > _GC_INTERVAL_S:
                self._last_gc = time.time()
                try:
                    n = requests_db.gc(REQUEST_TTL_S)
                    n_logs = tracing.gc_event_logs()
                    if n or n_logs:
                        tracing.add_event(
                            "server.request_gc",
                            attrs={"removed_requests": n,
                                   "removed_event_logs": n_logs},
                            echo=True)
                except Exception as e:  # noqa: BLE001 — GC never fatal
                    tracing.add_event(
                        "server.request_gc_failed",
                        attrs={"error_type": type(e).__name__,
                               "message": str(e)[:500]},
                        echo=True)
            if len(self._procs) < MAX_CONCURRENT_REQUESTS:
                rec = requests_db.next_new()
                if rec is not None:
                    self._spawn(rec)
                    continue
            time.sleep(0.05)

    def _spawn(self, rec: Dict[str, Any]) -> None:
        env = {**os.environ, "SKYPILOT_TPU_HOME": paths.home()}
        # The worker runs AS the submitting client: ownership checks in
        # core/backend resolve get_user_identity() to the identity the
        # client sent, not the server's own UNIX user.
        user = rec.get("user") or {}
        if user.get("id"):
            env["SKYPILOT_TPU_USER_ID"] = user["id"]
            env["SKYPILOT_TPU_USER_NAME"] = user.get("name", user["id"])
        # Trace context crosses the process boundary as env: every span
        # the worker (and anything the worker spawns) opens becomes a
        # child of this request's span.
        trace = rec.get("trace") or {}
        if trace.get("tp"):
            env[tracing.ENV_VAR] = trace["tp"]
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.server.worker",
             "--request-id", rec["request_id"]], env=env)
        requests_db.set_pid(rec["request_id"], proc.pid)
        self._procs[rec["request_id"]] = proc
        self._spawned_at[rec["request_id"]] = (rec["name"], time.time())
        API_WORKERS_BUSY.set(len(self._procs))

    def _reap(self) -> None:
        for rid, proc in list(self._procs.items()):
            if proc.poll() is not None:
                del self._procs[rid]
                API_WORKERS_BUSY.set(len(self._procs))
                # Worker died before recording a result? Mark failed.
                rec = requests_db.get(rid)
                if rec and not rec["status"].is_terminal():
                    requests_db.finish(
                        rid, RequestStatus.FAILED,
                        error=f"worker exited with {proc.returncode} "
                              f"without recording a result")
                    rec = requests_db.get(rid)
                name, t0 = self._spawned_at.pop(rid, (None, None))
                if name is not None:
                    API_REQUEST_SECONDS.labels(endpoint=name).observe(
                        time.time() - t0)
                if rec:
                    API_REQUESTS_FINISHED.labels(
                        status=rec["status"].value).inc()
                    self._record_request_span(rec)

    def _record_request_span(self, rec: Dict[str, Any]) -> None:
        """Close the request-lifecycle span (created-at to finished-at,
        in the server process) under the identity persisted at accept
        time. Durability is deferred to the executor loop's throttled
        heartbeat flush (~5s bound) — see run(). Restart-proof:
        everything needed lives in the request record."""
        trace = rec.get("trace") or {}
        ctx = tracing.parse_traceparent(trace.get("tp"))
        if ctx is None:
            return
        status = rec["status"]
        tracing.record_span(
            f"api.request:{rec['name']}",
            rec["created_at"], rec["finished_at"] or time.time(),
            ctx=ctx, parent_id=trace.get("parent"),
            attrs={"request_id": rec["request_id"],
                   "status": status.value},
            status="ok" if status == RequestStatus.SUCCEEDED else "error",
            error_type=None if status == RequestStatus.SUCCEEDED
            else status.value)
        # Durability rides the executor loop's throttled heartbeat
        # flush (see run()) — an eager whole-buffer flush per reaped
        # request would redo O(ring) serialization on a busy server.

    def stop(self) -> None:
        self._stop.set()


def make_handler(auth_token: Optional[str] = None):
    class ApiHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _authorized(self) -> bool:
            """Bearer-token check (skipped for /api/health so probes
            and `api info` work unauthenticated). Browsers cannot set
            an Authorization header on a plain link, so GETs also
            accept ?token=<token> — that is how the dashboard URL
            printed by `api start --auth` carries the credential (the
            dashboard JS forwards it to its own /api fetches).
            Constant-time comparisons: the token is the whole
            credential."""
            if auth_token is None:
                return True
            parsed = urllib.parse.urlparse(self.path)
            if parsed.path == "/api/health":
                return True
            import hmac
            got = self.headers.get("Authorization", "")
            want = f"Bearer {auth_token}"
            if hmac.compare_digest(got.encode(), want.encode()):
                return True
            qtok = (urllib.parse.parse_qs(parsed.query).get("token")
                    or [""])[0]
            return hmac.compare_digest(qtok.encode(),
                                       auth_token.encode())

        def _reject_unauthorized(self) -> None:
            # HTTP/1.1 keep-alive: the unread POST body would otherwise
            # be parsed as the NEXT request line on this connection.
            self.close_connection = True
            self._json(401, {"error": "unauthorized"})

        def _client_identity(self) -> Optional[Dict[str, str]]:
            uid = self.headers.get("X-SkyTPU-User-Id")
            if not uid:
                return None
            return {"id": uid,
                    "name": self.headers.get("X-SkyTPU-User-Name", uid)}

        # -- helpers -------------------------------------------------------
        def _json(self, code: int, obj: Any) -> None:
            body = json.dumps(obj, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> Dict[str, Any]:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        # -- routes --------------------------------------------------------
        def do_POST(self):
            if not self._authorized():
                return self._reject_unauthorized()
            path = urllib.parse.urlparse(self.path).path
            if path == "/api/cancel":
                body = self._body()
                rid = body.get("request_id")
                rec = requests_db.get(rid) if rid else None
                if rec is None:
                    return self._json(404, {"error": "unknown request"})
                if not rec["status"].is_terminal():
                    if rec["pid"]:
                        try:
                            os.kill(rec["pid"], signal.SIGTERM)
                        except OSError:
                            pass
                    requests_db.finish(rid, RequestStatus.CANCELLED)
                return self._json(200, {"ok": True})
            name = _ENDPOINTS.get(path)
            if name is None:
                return self._json(404, {"error": f"no endpoint {path}"})
            # Adopt the client's trace (malformed/absent header starts
            # a fresh one) and mint the request's own span id NOW; the
            # span itself is recorded when the executor reaps the
            # worker, from this persisted identity.
            client_ctx = tracing.parse_traceparent(
                self.headers.get("traceparent"))
            req_ctx = tracing.SpanContext(
                client_ctx.trace_id if client_ctx
                else tracing.new_trace_id(),
                tracing.new_span_id())
            trace = {"tp": tracing.format_traceparent(req_ctx),
                     "parent": client_ctx.span_id if client_ctx
                     else None}
            rid = requests_db.create(name, self._body(),
                                     user=self._client_identity(),
                                     trace=trace)
            API_REQUESTS.labels(endpoint=name).inc()
            return self._json(200, {"request_id": rid})

        def do_GET(self):
            if not self._authorized():
                return self._reject_unauthorized()
            parsed = urllib.parse.urlparse(self.path)
            qs = urllib.parse.parse_qs(parsed.query)
            if parsed.path == "/api/health":
                return self._json(200, {"status": "healthy",
                                        "version": _version()})
            if parsed.path == "/metrics":
                # This process's registry: executor gauges, request
                # counters, and anything library code running in the
                # server process recorded (GETs may auth via ?token=,
                # which is how a Prometheus scrape_config's params
                # carry the credential).
                metrics.write_exposition(self)
                return
            if parsed.path == "/metrics/fleet":
                # Federated exposition: one scrape target covering the
                # fleet (this registry + LBs + model-server replicas +
                # controller/skylet exposition files), merged with
                # type-correct semantics (see observability/aggregate).
                body = fleet_snapshot().render().encode()
                self.send_response(200)
                self.send_header("Content-Type", metrics.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parsed.path == "/healthz":
                h = _api_self_health()
                health.write_healthz(self, h["status"], h["reason"])
                return
            if parsed.path == "/api/fleet/health":
                return self._json(200, fleet_health())
            if parsed.path == "/api/clusters":
                from skypilot_tpu import state as gstate
                rows = []
                for r in gstate.list_clusters():
                    h = r.get("handle") or {}
                    res = h.get("resources") or {}
                    rows.append({
                        "name": r["name"],
                        "status": r["status"].value,
                        "resources": f"{h.get('provider', '?')}:"
                                     f"{res.get('accelerators', '?')}",
                        "autostop": r.get("autostop_minutes"),
                    })
                return self._json(200, rows)
            if parsed.path == "/api/jobs":
                from skypilot_tpu.jobs import state as jobs_state
                return self._json(200, [
                    {**j, "status": j["status"].value}
                    for j in jobs_state.list_jobs()])
            if parsed.path in ("/dashboard", "/"):
                from skypilot_tpu.server import html as html_mod
                body = html_mod.DASHBOARD_HTML.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parsed.path == "/api/status":
                return self._json(200, [
                    {**r, "status": r["status"].value}
                    for r in requests_db.list_requests()])
            if parsed.path == "/api/get":
                rid = (qs.get("request_id") or [None])[0]
                rec = requests_db.get(rid) if rid else None
                if rec is None:
                    return self._json(404, {"error": "unknown request"})
                return self._json(200, {**rec, "status": rec["status"].value})
            if parsed.path == "/api/stream":
                rid = (qs.get("request_id") or [None])[0]
                log = requests_db.log_path(rid) if rid else None
                content = ""
                if log and os.path.exists(log):
                    content = open(log).read()
                body = content.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            return self._json(404, {"error": f"no endpoint {parsed.path}"})

        def log_message(self, *args):
            pass

    return ApiHandler


def _version() -> str:
    import skypilot_tpu
    return skypilot_tpu.__version__


class _Server(ThreadingMixIn, HTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def serve(host: str = "127.0.0.1", port: int = 46580,
          auth_token: Optional[str] = None) -> None:
    tracing.set_process_name("api-server")
    executor = Executor()
    executor.start()
    watchdog = start_watchdog()
    httpd = _Server((host, port), make_handler(auth_token))
    try:
        httpd.serve_forever()
    finally:
        watchdog.stop()
        executor.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address; 0.0.0.0 for a shared server "
                         "(use --auth-token-file then!)")
    ap.add_argument("--port", type=int, default=46580)
    ap.add_argument("--auth-token-file", default=None,
                    help="require `Authorization: Bearer <token>` on "
                         "every endpoint except /api/health; the token "
                         "is the file's stripped contents")
    args = ap.parse_args()
    token = None
    if args.auth_token_file:
        with open(os.path.expanduser(args.auth_token_file)) as f:
            token = f.read().strip()
        if not token:
            raise SystemExit(f"{args.auth_token_file} is empty")
    serve(args.host, args.port, auth_token=token)


if __name__ == "__main__":
    main()
