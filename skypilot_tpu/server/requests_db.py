"""Async-request DB for the API server.

Reference parity: sky/server/requests/requests.py (sqlite request
records NEW->RUNNING->SUCCEEDED/FAILED/CANCELLED, per-request logs).
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import db, paths


class RequestStatus(enum.Enum):
    NEW = "NEW"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS requests (
    request_id TEXT PRIMARY KEY,
    name TEXT,
    status TEXT,
    payload TEXT,
    result TEXT,
    error TEXT,
    pid INTEGER,
    created_at REAL,
    finished_at REAL
);
"""


@contextlib.contextmanager
def _db():
    conn = db.connect(paths.requests_db(), timeout=10)
    conn.executescript(_SCHEMA)
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def create(name: str, payload: Dict[str, Any]) -> str:
    request_id = uuid.uuid4().hex[:16]
    with _db() as c:
        c.execute(
            "INSERT INTO requests (request_id, name, status, payload,"
            " created_at) VALUES (?,?,?,?,?)",
            (request_id, name, RequestStatus.NEW.value,
             json.dumps(payload), time.time()))
    return request_id


def next_new() -> Optional[Dict[str, Any]]:
    """Claim the oldest NEW request (atomic via status flip)."""
    with _db() as c:
        row = c.execute(
            "SELECT request_id FROM requests WHERE status=?"
            " ORDER BY created_at LIMIT 1",
            (RequestStatus.NEW.value,)).fetchone()
        if row is None:
            return None
        n = c.execute(
            "UPDATE requests SET status=? WHERE request_id=? AND status=?",
            (RequestStatus.RUNNING.value, row[0],
             RequestStatus.NEW.value)).rowcount
        if n == 0:
            return None
    return get(row[0])


def set_pid(request_id: str, pid: int) -> None:
    with _db() as c:
        c.execute("UPDATE requests SET pid=? WHERE request_id=?",
                  (pid, request_id))


def finish(request_id: str, status: RequestStatus,
           result: Any = None, error: Optional[str] = None) -> None:
    with _db() as c:
        c.execute(
            "UPDATE requests SET status=?, result=?, error=?, finished_at=?"
            " WHERE request_id=?",
            (status.value, json.dumps(result), error, time.time(),
             request_id))


def get(request_id: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(
            "SELECT request_id, name, status, payload, result, error, pid,"
            " created_at, finished_at FROM requests WHERE request_id=?",
            (request_id,)).fetchone()
    if row is None:
        return None
    return {
        "request_id": row[0], "name": row[1],
        "status": RequestStatus(row[2]),
        "payload": json.loads(row[3] or "{}"),
        "result": json.loads(row[4]) if row[4] else None,
        "error": row[5], "pid": row[6],
        "created_at": row[7], "finished_at": row[8],
    }


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT request_id FROM requests ORDER BY created_at DESC"
            " LIMIT ?", (limit,)).fetchall()
    return [r for rid, in rows if (r := get(rid)) is not None]


def log_path(request_id: str) -> str:
    d = os.path.join(paths.home(), "request_logs")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{request_id}.log")
