"""Async-request DB for the API server.

Reference parity: sky/server/requests/requests.py (sqlite request
records NEW->RUNNING->SUCCEEDED/FAILED/CANCELLED, per-request logs).
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import db, paths


class RequestStatus(enum.Enum):
    NEW = "NEW"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


# Schema history (PRAGMA user_version):
#   v1: requests table
#   v2: + requests.user (JSON {"id","name"} of the submitting client —
#       the API server stamps it from the request headers and injects
#       it into the worker so ops run AS that identity)
#   v3: + requests.trace (JSON {"tp": traceparent of the request's own
#       span, "parent": the client's span id or null} — the trace
#       context the executor injects into the worker and records the
#       request span under; see observability/tracing.py), and an index
#       covering the status-filtered scans (next_new() runs every
#       executor tick and gc() every few minutes; both full-scanned an
#       unindexed status column)
SCHEMA_VERSION = 3

_SCHEMA = """
CREATE TABLE IF NOT EXISTS requests (
    request_id TEXT PRIMARY KEY,
    name TEXT,
    status TEXT,
    payload TEXT,
    result TEXT,
    error TEXT,
    pid INTEGER,
    created_at REAL,
    finished_at REAL,
    user TEXT,
    trace TEXT
);
CREATE INDEX IF NOT EXISTS idx_requests_status
    ON requests (status, created_at);
"""

_MIGRATIONS = {
    2: "ALTER TABLE requests ADD COLUMN user TEXT;",
    3: ("ALTER TABLE requests ADD COLUMN trace TEXT;"
        "CREATE INDEX IF NOT EXISTS idx_requests_status"
        " ON requests (status, created_at);"),
}


@contextlib.contextmanager
def _db():
    conn = db.open_versioned(paths.requests_db(), _SCHEMA, SCHEMA_VERSION,
                             _MIGRATIONS, timeout=10)
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def create(name: str, payload: Dict[str, Any],
           user: Optional[Dict[str, str]] = None,
           trace: Optional[Dict[str, Any]] = None) -> str:
    request_id = uuid.uuid4().hex[:16]
    with _db() as c:
        c.execute(
            "INSERT INTO requests (request_id, name, status, payload,"
            " created_at, user, trace) VALUES (?,?,?,?,?,?,?)",
            (request_id, name, RequestStatus.NEW.value,
             json.dumps(payload), time.time(),
             json.dumps(user) if user else None,
             json.dumps(trace) if trace else None))
    return request_id


def next_new() -> Optional[Dict[str, Any]]:
    """Claim the oldest NEW request (atomic via status flip)."""
    with _db() as c:
        row = c.execute(
            "SELECT request_id FROM requests WHERE status=?"
            " ORDER BY created_at LIMIT 1",
            (RequestStatus.NEW.value,)).fetchone()
        if row is None:
            return None
        n = c.execute(
            "UPDATE requests SET status=? WHERE request_id=? AND status=?",
            (RequestStatus.RUNNING.value, row[0],
             RequestStatus.NEW.value)).rowcount
        if n == 0:
            return None
    return get(row[0])


def set_pid(request_id: str, pid: int) -> None:
    with _db() as c:
        c.execute("UPDATE requests SET pid=? WHERE request_id=?",
                  (pid, request_id))


def finish(request_id: str, status: RequestStatus,
           result: Any = None, error: Optional[str] = None) -> None:
    with _db() as c:
        c.execute(
            "UPDATE requests SET status=?, result=?, error=?, finished_at=?"
            " WHERE request_id=?",
            (status.value, json.dumps(result), error, time.time(),
             request_id))


def get(request_id: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(
            "SELECT request_id, name, status, payload, result, error, pid,"
            " created_at, finished_at, user, trace FROM requests"
            " WHERE request_id=?",
            (request_id,)).fetchone()
    if row is None:
        return None
    return {
        "request_id": row[0], "name": row[1],
        "status": RequestStatus(row[2]),
        "payload": json.loads(row[3] or "{}"),
        "result": json.loads(row[4]) if row[4] else None,
        "error": row[5], "pid": row[6],
        "created_at": row[7], "finished_at": row[8],
        "user": json.loads(row[9]) if row[9] else None,
        "trace": json.loads(row[10]) if row[10] else None,
    }


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT request_id FROM requests ORDER BY created_at DESC"
            " LIMIT ?", (limit,)).fetchall()
    return [r for rid, in rows if (r := get(rid)) is not None]


def log_path(request_id: str) -> str:
    d = os.path.join(paths.home(), "request_logs")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{request_id}.log")


def gc(max_age_s: float, keep_last: int = 50) -> int:
    """Delete terminal requests older than ``max_age_s`` (and their log
    files), always keeping the ``keep_last`` most recent records so
    `api status` history survives an aggressive TTL. Returns the number
    of records removed. (Reference analog: the API server's request GC;
    an unbounded requests DB on a shared server grows without limit.)"""
    cutoff = time.time() - max_age_s
    terminal = tuple(s.value for s in RequestStatus if s.is_terminal())
    with _db() as c:
        keep = {r[0] for r in c.execute(
            "SELECT request_id FROM requests ORDER BY created_at DESC"
            " LIMIT ?", (keep_last,)).fetchall()}
        rows = c.execute(
            f"SELECT request_id FROM requests WHERE status IN"
            f" ({','.join('?' * len(terminal))}) AND finished_at < ?",
            terminal + (cutoff,)).fetchall()
        doomed = [r[0] for r in rows if r[0] not in keep]
        for rid in doomed:
            c.execute("DELETE FROM requests WHERE request_id=?", (rid,))
    for rid in doomed:
        try:
            os.remove(log_path(rid))
        except OSError:
            pass
    return len(doomed)
