"""Dashboard HTML (stdlib-served, zero deps).

Reference parity: sky/jobs/dashboard/ (Flask+HTML jobs table) and
sky/server/html/log.html (browser log viewer) — one page here covering
clusters, managed jobs, and API requests, auto-refreshing from the
JSON endpoints.
"""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>skypilot-tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 2rem; color: #1a1a2e; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 1.8rem; }
  table { border-collapse: collapse; width: 100%; font-size: 0.88rem; }
  th, td { text-align: left; padding: 6px 12px;
           border-bottom: 1px solid #e5e5ef; }
  th { color: #555; font-weight: 600; }
  .ok { color: #0a7d33; } .bad { color: #b3261e; } .dim { color: #888; }
  #updated { color: #888; font-size: 0.8rem; }
</style>
</head>
<body>
<h1>skypilot-tpu <span id="updated"></span></h1>
<h2>Clusters</h2>
<table id="clusters"><thead><tr>
  <th>Name</th><th>Status</th><th>Resources</th><th>Autostop</th>
</tr></thead><tbody></tbody></table>
<h2>Managed jobs</h2>
<table id="jobs"><thead><tr>
  <th>ID</th><th>Name</th><th>Status</th><th>Task</th>
  <th>Recoveries</th><th>Cluster</th>
</tr></thead><tbody></tbody></table>
<h2>API requests</h2>
<table id="requests"><thead><tr>
  <th>ID</th><th>Op</th><th>Status</th>
</tr></thead><tbody></tbody></table>
<script>
function cls(s) {
  if (["UP","SUCCEEDED","RUNNING"].includes(s)) return "ok";
  if (s.startsWith("FAILED") || s === "CANCELLED") return "bad";
  return "dim";
}
function fill(id, rows, cols) {
  const tb = document.querySelector(`#${id} tbody`);
  tb.innerHTML = "";
  for (const r of rows) {
    const tr = document.createElement("tr");
    for (const c of cols) {
      const td = document.createElement("td");
      const v = r[c] ?? "-";
      td.textContent = v;
      if (c === "status") td.className = cls(String(v));
      tr.appendChild(td);
    }
    tb.appendChild(tr);
  }
}
async function refresh() {
  try {
    const [cs, js, rs] = await Promise.all([
      fetch("/api/clusters" + window.location.search).then(r => r.json()),
      fetch("/api/jobs" + window.location.search).then(r => r.json()),
      fetch("/api/status" + window.location.search).then(r => r.json()),
    ]);
    fill("clusters", cs, ["name", "status", "resources", "autostop"]);
    fill("jobs", js, ["job_id", "name", "status", "task",
                      "recovery_count", "cluster_name"]);
    fill("requests", rs.slice(-30).reverse(),
         ["request_id", "name", "status"]);
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed";
  }
}
refresh();
setInterval(refresh, 5000);
</script>
</body>
</html>
"""
