"""Pipeline parallelism: GPipe-schedule microbatching over the ``pp`` axis.

TPU-first design notes
----------------------
* No per-stage processes, no send/recv: pipelining is expressed as a
  *dense, sharded program*. Layer weights are stacked on a leading
  ``stage`` dim sharded over the ``pp`` mesh axis; a ring buffer of
  per-stage activations carries one microbatch per stage; each tick
  (a) shifts the buffer one stage forward — a concatenate on a
  pp-sharded dim that XLA lowers to neighbor collective-permutes over
  ICI — and (b) applies every stage's layers to its slot in parallel
  (``vmap`` over the stage dim). After ``n_micro + n_stages - 1`` ticks
  all microbatches have flowed through all stages.
* Classic GPipe bubble: stages idle ``(n_stages-1)/(n_micro+n_stages-1)``
  of the time; raise ``n_microbatches`` to amortize.
* Backward is plain autodiff through the tick ``lax.scan`` — XLA
  reverses the permutes, yielding the mirrored backward schedule. Each
  tick is rematerialized (``jax.checkpoint``) so live activation memory
  is one microbatch per stage, not the whole schedule.
* Composes with the other axes: batch dims still shard over (dp, fsdp),
  per-layer weights over fsdp/tp within each stage.

This module is a *model adapter*: it exposes the same
``init_params / param_logical_axes / loss_fn`` surface the trainer
expects, wrapping ``models.llama`` with stage-stacked parameters.

Reference parity: the reference has no pipeline parallelism anywhere —
it delegates to DeepSpeed/SGLang inside workload recipes (reference:
examples/deepspeed-multinode/sky.yaml; SURVEY.md §2.11). Here it is a
first-class mesh axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models import llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class PipelineConfig(llama.LlamaConfig):
    """Llama config with a pipelined decoder stack.

    ``schedule``:
      * ``"gpipe"`` — all microbatch outputs are buffered ([M, b, S, D])
        and the loss runs over the re-assembled batch. Activation
        memory grows with n_microbatches.
      * ``"1f1b"``  — the loss for each microbatch is computed IN the
        tick that drains it and only scalar accumulators survive; the
        O(M) output buffer disappears, so activation memory is O(
        n_stages) regardless of microbatch count. Autodiff through the
        tick scan then replays ticks newest-first, each immediately
        followed by its own head/loss backward — the classic
        one-forward-one-backward interleave emerges from the reversed
        program rather than from hand-scheduled send/recvs.

    On bubbles (measured in examples/pp_schedule_bench.py): a
    synchronous flat pipeline has bubble (S-1)/(M+S-1) under EITHER
    schedule — 1F1B's textbook win over GPipe is activation MEMORY,
    not steady-state bubble. The bubble payoff is indirect and real:
    O(stages) memory lets n_microbatches rise at fixed HBM, and the
    bubble fraction falls with M.
    """

    n_stages: int = 2
    n_microbatches: int = 4
    schedule: str = "gpipe"

    def __post_init__(self):
        if self.n_layers % self.n_stages:
            raise ValueError(f"n_layers={self.n_layers} not divisible by "
                             f"n_stages={self.n_stages}")
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule "
                             f"{self.schedule!r} (gpipe | 1f1b)")

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages


CONFIGS: Dict[str, PipelineConfig] = {
    "pp-tiny": PipelineConfig(vocab_size=512, d_model=128, n_layers=4,
                              n_heads=4, n_kv_heads=2, d_ff=256,
                              max_seq_len=256, n_stages=2,
                              n_microbatches=4),
}


# ---------------------------------------------------------------------------
# Stage-stacked parameters
# ---------------------------------------------------------------------------

def _to_stages(blocks: Params, n_stages: int) -> Params:
    """[L, ...] stacked per-layer weights -> [n_stages, L/stages, ...]."""
    return jax.tree.map(
        lambda x: x.reshape(n_stages, x.shape[0] // n_stages, *x.shape[1:]),
        blocks)


def init_params(rng: jax.Array, cfg: PipelineConfig) -> Params:
    params = llama.init_params(rng, cfg)
    params["blocks"] = _to_stages(params["blocks"], cfg.n_stages)
    return params


def param_logical_axes(cfg: PipelineConfig) -> Params:
    axes = llama.param_logical_axes(cfg)
    axes["blocks"] = jax.tree.map(
        lambda t: ("stage",) + t,
        axes["blocks"], is_leaf=lambda x: isinstance(x, tuple))
    return axes


# ---------------------------------------------------------------------------
# The pipelined forward
# ---------------------------------------------------------------------------

def _stage_apply(cfg: PipelineConfig, stage_blocks: Params, x: jax.Array,
                 cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Run one stage's layers_per_stage decoder layers. x: [b, S, D]."""

    def body(carry, layer):
        return llama.decoder_layer(cfg, carry, layer, cos, sin), None

    x, _ = lax.scan(body, x, stage_blocks)
    return x


def forward_hidden(params: Params, tokens: jax.Array, cfg: PipelineConfig,
                   constrain=None, mesh=None, rules=None) -> jax.Array:
    """[B, S] ids -> final-norm hidden [B, S, D] via the pipelined stack.

    B must be divisible by n_microbatches. ``constrain/mesh/rules`` follow
    the models.llama signature; activation constraints are applied to the
    whole stage buffer (stage dim -> pp), per-layer internals are left to
    XLA's sharding propagation from the weight shardings.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    S_stages, M = cfg.n_stages, cfg.n_microbatches
    B, S = tokens.shape
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    b = B // M

    table = constrain(params["embed"].astype(cfg.dtype),
                      ("vocab", "embed"))
    x = table[tokens]                                    # [B, S, D]
    micro = x.reshape(M, b, S, x.shape[-1])
    micro = constrain(micro, ("micro", "batch", "seq", "embed"))
    positions = jnp.arange(S)
    cos, sin = llama.rope_frequencies(cfg, positions)

    apply_all = jax.vmap(
        lambda blocks, xs: _stage_apply(cfg, blocks, xs, cos, sin))

    def tick(carry, t):
        buf, out = carry
        # Feed microbatch t into stage 0 (past the end: recycle the last
        # one; its output lands outside the valid output range).
        inp = lax.dynamic_index_in_dim(micro, jnp.minimum(t, M - 1), axis=0,
                                       keepdims=False)
        # Shift one stage forward: on a pp-sharded dim this is a neighbor
        # collective-permute, the pipeline's only communication.
        buf = jnp.concatenate([inp[None], buf[:-1]], axis=0)
        buf = constrain(buf, ("stage", "batch", "seq", "embed"))
        buf = apply_all(params["blocks"], buf)
        buf = constrain(buf, ("stage", "batch", "seq", "embed"))
        # Stage (n_stages-1) just finished microbatch t-(n_stages-1).
        # Earlier ticks write warm-up garbage to slot 0; the true
        # microbatch-0 output overwrites it at t = n_stages-1 (ticks only
        # move forward, so every slot's final write is the real one).
        idx = jnp.maximum(t - (S_stages - 1), 0)
        out = lax.dynamic_update_index_in_dim(out, buf[-1], idx, axis=0)
        return (buf, out), None

    D = x.shape[-1]
    buf0 = jnp.zeros((S_stages, b, S, D), cfg.dtype)
    out0 = jnp.zeros((M, b, S, D), cfg.dtype)
    total_ticks = M + S_stages - 1
    tick_fn = jax.checkpoint(tick, policy=llama.remat_policy(cfg))
    (_, out), _ = lax.scan(tick_fn, (buf0, out0), jnp.arange(total_ticks))

    x = out.reshape(B, S, D)
    return llama.rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(params: Params, tokens: jax.Array, cfg: PipelineConfig,
            constrain=None, mesh=None, rules=None) -> jax.Array:
    """[B, S] ids -> logits [B, S, vocab] fp32 (pipelined stack)."""
    if constrain is None:
        constrain = lambda x, axes: x
    x = forward_hidden(params, tokens, cfg, constrain, mesh, rules)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: Dict[str, jax.Array],
            cfg: PipelineConfig, constrain=None, mesh=None,
            rules=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy through the pipelined forward.

    Honors ``cfg.xent_chunk`` via the shared llama.xent_metrics epilogue.
    ``cfg.schedule == "1f1b"`` streams the loss per drained microbatch
    (O(n_stages) activation memory); both schedules compute the same
    number (tested to tolerance in tests/test_pipeline.py).
    """
    if constrain is None:
        constrain = lambda x, axes: x
    if batch.get("segment_ids") is not None:
        raise ValueError(
            "packed sequences (segment_ids) are not supported by the "
            "pipeline adapter; use the llama or moe model")
    if cfg.schedule == "1f1b":
        return _loss_streaming(params, batch, cfg, constrain)
    tokens = batch["tokens"]
    h = forward_hidden(params, tokens, cfg, constrain, mesh, rules)
    loss, acc, denom = llama.xent_metrics(params, h, tokens,
                                          batch.get("mask"), cfg,
                                          constrain)
    return loss, {"loss": loss, "accuracy": acc, "tokens": denom}


def _loss_streaming(params: Params, batch: Dict[str, jax.Array],
                    cfg: PipelineConfig, constrain
                    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """The 1F1B-equivalent schedule: each microbatch's head+loss runs
    in the tick that drains it from the last stage, and only scalar
    (loss, accuracy, token-count) accumulators cross ticks — there is
    no [M, b, S, D] output buffer, so activation memory is flat in
    n_microbatches (the property that lets M grow until the bubble
    (S-1)/(M+S-1) is negligible). Autodiff replays ticks newest-first,
    interleaving each tick's stage backward with its loss backward —
    the 1F1B alternation, derived instead of hand-scheduled.

    Warm-up ticks drain a zeros buffer: rms_norm(0)=0 -> uniform
    logits -> a FINITE dummy loss, which the validity factor zeroes
    (finite-times-zero keeps gradients clean where NaN would poison
    them)."""
    tokens = batch["tokens"]
    mask = batch.get("mask")
    S_stages, M = cfg.n_stages, cfg.n_microbatches
    B, S = tokens.shape
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    b = B // M

    table = constrain(params["embed"].astype(cfg.dtype),
                      ("vocab", "embed"))
    x = table[tokens]
    micro = x.reshape(M, b, S, x.shape[-1])
    micro = constrain(micro, ("micro", "batch", "seq", "embed"))
    micro_tok = tokens.reshape(M, b, S)
    micro_mask = mask.reshape(M, b, S) if mask is not None else None
    positions = jnp.arange(S)
    cos, sin = llama.rope_frequencies(cfg, positions)

    apply_all = jax.vmap(
        lambda blocks, xs: _stage_apply(cfg, blocks, xs, cos, sin))

    def tick(carry, t):
        buf, lsum, asum, dsum = carry
        inp = lax.dynamic_index_in_dim(micro, jnp.minimum(t, M - 1),
                                       axis=0, keepdims=False)
        buf = jnp.concatenate([inp[None], buf[:-1]], axis=0)
        buf = constrain(buf, ("stage", "batch", "seq", "embed"))
        buf = apply_all(params["blocks"], buf)
        buf = constrain(buf, ("stage", "batch", "seq", "embed"))
        idx = t - (S_stages - 1)          # microbatch draining this tick
        safe = jnp.clip(idx, 0, M - 1)
        h = llama.rms_norm(buf[-1], params["final_norm"], cfg.norm_eps)
        tok = lax.dynamic_index_in_dim(micro_tok, safe, axis=0,
                                       keepdims=False)
        msk = (lax.dynamic_index_in_dim(micro_mask, safe, axis=0,
                                        keepdims=False)
               if micro_mask is not None else None)
        loss, acc, denom = llama.xent_metrics(params, h, tok, msk, cfg,
                                              constrain)
        valid = (idx >= 0).astype(jnp.float32)
        lsum = lsum + valid * loss * denom
        asum = asum + valid * acc * denom
        dsum = dsum + valid * denom
        return (buf, lsum, asum, dsum), None

    D = x.shape[-1]
    buf0 = jnp.zeros((S_stages, b, S, D), cfg.dtype)
    zero = jnp.zeros((), jnp.float32)
    total_ticks = M + S_stages - 1
    tick_fn = jax.checkpoint(tick, policy=llama.remat_policy(cfg))
    (_, lsum, asum, dsum), _ = lax.scan(
        tick_fn, (buf0, zero, zero, zero), jnp.arange(total_ticks))
    denom = jnp.maximum(dsum, 1.0)
    loss = lsum / denom
    return loss, {"loss": loss, "accuracy": asum / denom,
                  "tokens": dsum}
