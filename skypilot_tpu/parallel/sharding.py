"""Logical-axis -> mesh-axis sharding rules (MaxText-style).

Model code annotates tensors with *logical* axis names ("embed", "heads",
"batch", ...). A rule table maps each logical name to a mesh axis (or
None = replicated). Swapping the table reconfigures the whole model
between FSDP / TP / DP / hybrid without touching model code.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[str, Tuple[str, ...], None]
Rules = Dict[str, MeshAxis]

# Default hybrid FSDP x TP rules:
#  - params' "embed" dim sharded over fsdp (ZeRO-3 style),
#  - heads / mlp / vocab dims over tp (Megatron style),
#  - activations' batch dim over (dp, fsdp) jointly.
DEFAULT_RULES: Rules = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "vocab": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "head_dim": None,
    "layer": None,
    # MoE: the expert dim shards over ep; XLA turns the dispatch/combine
    # einsums into all-to-alls over the ep axis. Capacity stays local.
    "expert": "ep",
    "capacity": None,
    # Pipeline: the stage dim of stage-stacked weights / activation
    # buffers shards over pp; the tick shift compiles to collective
    # permutes between neighbor stages.
    "stage": "pp",
    "micro": None,
}

# Activation-side overrides: activations' "embed" stays unsharded (it is
# the contracting dim of every matmul); sharding it would force XLA into
# all-to-alls mid-layer.
ACT_RULES: Rules = dict(DEFAULT_RULES, embed=None, vocab="tp")


def _axis_size(mesh: Mesh, axis: MeshAxis) -> int:
    if axis is None:
        return 1
    if isinstance(axis, tuple):
        out = 1
        for a in axis:
            out *= mesh.shape[a]
        return out
    return mesh.shape[axis]


def spec_for(logical_axes: Sequence[Optional[str]], rules: Rules,
             mesh: Optional[Mesh] = None,
             shape: Optional[Sequence[int]] = None) -> P:
    """Resolve logical axis names to a PartitionSpec via the rule table.

    When ``mesh`` and ``shape`` are given, a dim that the mapped mesh axis
    does not divide evenly is left replicated instead of erroring — so the
    same rules work for any slice topology (a 3-way fsdp axis simply won't
    shard a 128-wide dim).
    """
    resolved = []
    for i, a in enumerate(logical_axes):
        axis = rules.get(a) if a is not None else None
        if (axis is not None and mesh is not None and shape is not None
                and shape[i] % _axis_size(mesh, axis) != 0):
            axis = None
        resolved.append(axis)
    return P(*resolved)


def logical_to_sharding(logical_tree, mesh: Mesh, rules: Rules = DEFAULT_RULES,
                        shapes=None):
    """Map a pytree of logical-axis tuples to NamedShardings.

    ``shapes``: optional matching pytree of array shapes (or objects with
    ``.shape``) enabling the divisibility guard in ``spec_for``.
    """
    is_leaf = lambda x: isinstance(x, tuple)
    if shapes is None:
        return jax.tree.map(
            lambda axes: NamedSharding(mesh, spec_for(axes, rules, mesh)),
            logical_tree, is_leaf=is_leaf)
    return jax.tree.map(
        lambda axes, s: NamedSharding(
            mesh, spec_for(axes, rules, mesh, getattr(s, "shape", s))),
        logical_tree, shapes, is_leaf=is_leaf)


def shard_tree_subset(tree, logical_tree, mesh: Mesh, rules: Rules):
    """device_put every array in ``tree`` per its axes in
    ``logical_tree``, walking by DICT KEY so ``tree`` may be a subset
    of the axes tree (e.g. w8a8 serving's slimmed params: embed + norms
    only — a plain tree.map would fail on the structure mismatch).
    Arrays without an axes entry are replicated."""
    replicated = NamedSharding(mesh, P())
    if isinstance(tree, dict):
        sub = logical_tree if isinstance(logical_tree, dict) else {}
        return {k: shard_tree_subset(v, sub.get(k), mesh, rules)
                for k, v in tree.items()}
    axes = logical_tree if isinstance(logical_tree, tuple) else None
    if axes is None:
        return jax.device_put(tree, replicated)
    return jax.device_put(
        tree, NamedSharding(mesh, spec_for(axes, rules, mesh,
                                           tree.shape)))


# Inference TP rules: Megatron-style heads/mlp/vocab over tp; no data/
# fsdp axes (serving replicates activations' batch). The "embed"
# logical axis has no rule, so NORMS replicate — but the embedding
# table and LM head shard their "vocab" dim (the token gather and the
# logits matmul run vocab-split, with XLA inserting the collectives).
INFER_TP_RULES: Rules = {"heads": "tp", "kv_heads": "tp",
                         "mlp": "tp", "vocab": "tp"}


def make_constrain(mesh: Optional[Mesh], rules: Rules = ACT_RULES):
    """Return fn(x, logical_axes) applying with_sharding_constraint.

    With mesh=None returns identity (single-device path compiles to the
    same HLO with zero overhead).
    """
    if mesh is None:
        return lambda x, axes: x

    def constrain(x, axes):
        if len(axes) != x.ndim:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec_for(axes, rules, mesh, x.shape)))

    return constrain
