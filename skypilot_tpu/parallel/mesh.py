"""Device-mesh construction for TPU slices.

The canonical mesh axes, outermost to innermost:

  ``pp``   pipeline parallel (decoder stages; p2p activation permutes)
  ``dp``   pure data parallel (gradients all-reduced; params replicated)
  ``fsdp`` fully-sharded data parallel (params/opt-state sharded on embed dim)
  ``ep``   expert parallel (MoE expert dim sharded; token all-to-alls)
  ``sp``   sequence/context parallel (ring attention; defaults to 1)
  ``tp``   tensor parallel (heads / mlp / vocab dims sharded) — innermost:
           per-layer all-reduces ride the fastest ICI wires; the sp ring's
           neighbor ppermutes sit just outside

Axis *order matters* on TPU: innermost axes map to the densest ICI links,
so tensor-parallel collectives (per-layer all-reduces) ride the fastest
wires while dp gradient reductions tolerate slower paths (DCN for
multi-slice). This mirrors the scaling-book recipe: pick a mesh, annotate
shardings, let XLA place the collectives.

Reference parity: the reference has no device-mesh concept at all — its
"parallelism" is node-level gang scheduling + env-var rank injection
(reference: sky/backends/cloud_vm_ray_backend.py:385-668). Here the mesh
is the first-class scaling object.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshShape:
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return (self.pp * self.dp * self.fsdp * self.ep * self.tp
                * self.sp)

    def as_dict(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "ep": self.ep, "tp": self.tp, "sp": self.sp}


def make_mesh(shape: Optional[MeshShape | Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 4-axis mesh. Defaults to all devices on ``fsdp``."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = MeshShape(fsdp=n)
    elif isinstance(shape, dict):
        shape = MeshShape(**{k: v for k, v in shape.items() if k in MESH_AXES})
    if shape.size != n:
        raise ValueError(
            f"mesh shape {shape.as_dict()} needs {shape.size} devices, "
            f"got {n}")
    dev_array = np.asarray(devices).reshape(shape.pp, shape.dp, shape.fsdp,
                                            shape.ep, shape.sp, shape.tp)
    return Mesh(dev_array, MESH_AXES)


def default_shape_for(n_devices: int, tp: int = 1, sp: int = 1,
                      dp: int = 1, ep: int = 1, pp: int = 1) -> MeshShape:
    """FSDP-dominant factorization: the remainder goes to fsdp."""
    denom = tp * sp * dp * ep * pp
    rest = n_devices // denom
    if rest * denom != n_devices:
        raise ValueError(f"{n_devices} devices not divisible by "
                         f"tp={tp} sp={sp} dp={dp} ep={ep} pp={pp}")
    return MeshShape(pp=pp, dp=dp, fsdp=rest, ep=ep, tp=tp, sp=sp)
