"""Device-mesh construction for TPU slices.

The canonical mesh axes, outermost to innermost:

  ``pp``   pipeline parallel (decoder stages; p2p activation permutes)
  ``dp``   pure data parallel (gradients all-reduced; params replicated)
  ``fsdp`` fully-sharded data parallel (params/opt-state sharded on embed dim)
  ``ep``   expert parallel (MoE expert dim sharded; token all-to-alls)
  ``sp``   sequence/context parallel (ring attention; defaults to 1)
  ``tp``   tensor parallel (heads / mlp / vocab dims sharded) — innermost:
           per-layer all-reduces ride the fastest ICI wires; the sp ring's
           neighbor ppermutes sit just outside

Axis *order matters* on TPU: innermost axes map to the densest ICI links,
so tensor-parallel collectives (per-layer all-reduces) ride the fastest
wires while dp gradient reductions tolerate slower paths (DCN for
multi-slice). This mirrors the scaling-book recipe: pick a mesh, annotate
shardings, let XLA place the collectives.

Reference parity: the reference has no device-mesh concept at all — its
"parallelism" is node-level gang scheduling + env-var rank injection
(reference: sky/backends/cloud_vm_ray_backend.py:385-668). Here the mesh
is the first-class scaling object.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshShape:
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return (self.pp * self.dp * self.fsdp * self.ep * self.tp
                * self.sp)

    def as_dict(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "ep": self.ep, "tp": self.tp, "sp": self.sp}


def make_mesh(shape: Optional[MeshShape | Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 4-axis mesh. Defaults to all devices on ``fsdp``."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = MeshShape(fsdp=n)
    elif isinstance(shape, dict):
        shape = MeshShape(**{k: v for k, v in shape.items() if k in MESH_AXES})
    if shape.size != n:
        raise ValueError(
            f"mesh shape {shape.as_dict()} needs {shape.size} devices, "
            f"got {n}")
    dev_array = np.asarray(devices).reshape(shape.pp, shape.dp, shape.fsdp,
                                            shape.ep, shape.sp, shape.tp)
    return Mesh(dev_array, MESH_AXES)


def default_shape_for(n_devices: int, tp: int = 1, sp: int = 1,
                      dp: int = 1, ep: int = 1, pp: int = 1) -> MeshShape:
    """FSDP-dominant factorization: the remainder goes to fsdp."""
    denom = tp * sp * dp * ep * pp
    rest = n_devices // denom
    if rest * denom != n_devices:
        raise ValueError(f"{n_devices} devices not divisible by "
                         f"tp={tp} sp={sp} dp={dp} ep={ep} pp={pp}")
    return MeshShape(pp=pp, dp=dp, fsdp=rest, ep=ep, tp=tp, sp=sp)


def make_multislice_mesh(shape: Optional[MeshShape | Dict[str, int]] = None,
                         devices: Optional[Sequence[jax.Device]] = None,
                         n_slices: Optional[int] = None) -> Mesh:
    """Mesh spanning multiple TPU slices (multislice / DCN).

    The ``dp`` axis indexes slices — gradient all-reduces ride DCN
    between slices while every other axis (fsdp/ep/sp/tp + any
    within-slice pp) stays on ICI inside a slice. This is the
    scaling-book multislice recipe: data-parallel across slices,
    model-parallel within.

    Devices are grouped by ``slice_index`` (libtpu sets it on real
    multislice); on single-slice/CPU backends pass ``n_slices`` to
    split the device list into equal virtual slices for testing.

    Reference parity: none — the reference has no multislice support at
    all (SURVEY.md §2.3: "No queued-resources / multi-slice API —
    north-star gap to build").
    """
    devices = list(devices if devices is not None else jax.devices())
    groups: Dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0) or 0,
                          []).append(d)
    if len(groups) == 1 and n_slices and n_slices > 1:
        per = len(devices) // n_slices
        if per * n_slices != len(devices):
            raise ValueError(f"{len(devices)} devices not divisible "
                             f"into {n_slices} slices")
        groups = {i: devices[i * per:(i + 1) * per]
                  for i in range(n_slices)}
    slices = [groups[k] for k in sorted(groups)]
    ns = len(slices)
    per_slice = len(slices[0])
    if any(len(s) != per_slice for s in slices):
        raise ValueError("slices are not equally sized")

    if shape is None:
        shape = MeshShape(dp=ns, fsdp=per_slice)
    elif isinstance(shape, dict):
        shape = MeshShape(**{k: v for k, v in shape.items()
                             if k in MESH_AXES})
    if shape.dp != ns:
        raise ValueError(
            f"multislice mesh needs dp == n_slices ({ns}), got "
            f"dp={shape.dp}")
    within = shape.pp * shape.fsdp * shape.ep * shape.sp * shape.tp
    if within != per_slice:
        raise ValueError(
            f"within-slice axes need {within} devices but each slice "
            f"has {per_slice}")
    # dev_array[pp, dp, fsdp, ep, sp, tp]: dp indexes the slice; all
    # other dims range over that slice's own devices.
    per_arrays = [
        np.asarray(s).reshape(shape.pp, shape.fsdp, shape.ep, shape.sp,
                              shape.tp)
        for s in slices]
    dev_array = np.stack(per_arrays, axis=1)
    return Mesh(dev_array, MESH_AXES)
