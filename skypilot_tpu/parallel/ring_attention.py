"""Context parallelism: ring attention + Ulysses all-to-all attention.

Long-sequence attention sharded over the ``sp`` mesh axis. Two schemes,
both expressed as shard_map'ed collectives so XLA schedules the ICI
traffic (and can overlap `ppermute` with the block matmuls):

* **Ring attention** (`ring_attention`): K/V blocks circulate around the
  ``sp`` ring via `lax.ppermute` while each device keeps its query shard;
  softmax is accumulated online (flash-style running max/sum), so no
  device ever materializes more than one remote K/V block. A custom VJP
  runs a second ring in the backward pass with dK/dV accumulators riding
  along with their K/V blocks — memory stays O(seq/sp) per device in both
  passes. GQA is native: the *unrepeated* K/V heads circulate (grouped
  einsums inside the ring body), so ICI volume and resident KV bytes are
  n_kv_heads-sized, not n_heads-sized.

* **Ulysses attention** (`ulysses_attention`): `lax.all_to_all` reshards
  [seq/sp, heads] -> [seq, heads/sp], runs ordinary (flash) attention on
  full sequences for a head subset, and reshards back. Cheaper in
  collective volume when heads >= sp; requires heads % sp == 0.

**Packed sequences** (`segment_ids` [B, S], 0 = padding) compose with
both schemes: in the ring, each block's segment ids circulate WITH its
K/V, and the ring body masks cross-segment pairs — so packed long-
context training runs under sequence parallelism (the flagship TPU
workload). Ulysses all-gathers the (tiny) id vector to mask the full
sequence locally.

Reference parity: the reference has NO sequence/context parallelism
anywhere (SURVEY.md §2.11 — long-context is delegated to workload
engines like vLLM/DeepSpeed). Here it is first-class, per the TPU-native
mandate: sequence parallelism shapes the core mesh design (the ``sp``
axis in parallel.mesh) rather than being an external recipe concern.

Causal note: the plain ring computes blocks entirely in the masked
future and zeroes them (uniform work per step keeps the collective
schedule static). `zigzag_ring_attention` removes that waste: the
zigzag chunk layout makes every step's needed work a single maskless
half-block einsum, balanced across devices — ~2x attention FLOPs saving
as sp grows. Models opt in via the activation-rule key
``seq_layout: zigzag`` (llama permutes once after the embedding).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

if hasattr(jax, "shard_map"):              # jax >= 0.5
    _shard_map_impl = jax.shard_map
else:                                      # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map across the 0.4 -> 0.5 rename: the replication
    check kwarg was ``check_rep`` before it became ``check_vma``."""
    try:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=check_vma)
    except TypeError:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma)

NEG_INF = -1e30


def _ring_perm(axis_size: int):
    return [(j, (j + 1) % axis_size) for j in range(axis_size)]


def _block_mask(my_idx, kv_idx, s_q: int, s_k: int):
    """Causal mask between global query/key positions of two ring blocks."""
    q_pos = my_idx * s_q + jnp.arange(s_q)
    k_pos = kv_idx * s_k + jnp.arange(s_k)
    return q_pos[:, None] >= k_pos[None, :]


def _allowed_mask(causal, has_seg, my_idx, kv_idx, s_q, s_k, q_seg, k_seg):
    """Combined causal+segment mask, broadcastable to [B,Hkv,G,Sq,Sk].
    None means everything is allowed."""
    allowed = None
    if causal:
        allowed = _block_mask(my_idx, kv_idx, s_q, s_k)  # [Sq, Sk]
    if has_seg:
        same = (q_seg[:, None, None, :, None]
                == k_seg[:, None, None, None, :])        # [B,1,1,Sq,Sk]
        allowed = same if allowed is None else (allowed & same)
    return allowed


def _group(q, n_kv: int):
    """[B, S, Hq, D] -> [B, S, Hkv, G, D] with G = Hq // Hkv."""
    B, S, Hq, D = q.shape
    return q.reshape(B, S, n_kv, Hq // n_kv, D)


# ---------------------------------------------------------------------------
# Forward ring
# ---------------------------------------------------------------------------

def _ring_fwd(axis_name: str, axis_size: int, causal: bool, has_seg: bool,
              q, k, v, seg):
    """Local q [B,S,Hq,D]; k/v [B,S,Hkv,D], Hq % Hkv == 0; seg [B,S].

    Returns (o [B,S,Hq,D], lse [B,Hkv,G,S]). Grouped (GQA) einsums: the
    circulating K/V stay at Hkv heads. With has_seg, the K/V block's
    segment ids ride the ring and cross-segment pairs are masked.
    """
    scale = q.shape[-1] ** -0.5
    my_idx = lax.axis_index(axis_name)
    B, S, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    perm = _ring_perm(axis_size)
    q5 = _group(q, Hkv)  # [B, S, Hkv, G, D]

    o0 = jnp.zeros((B, S, Hkv, Hq // Hkv, D), jnp.float32)
    m0 = jnp.full((B, Hkv, Hq // Hkv, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, Hq // Hkv, S), jnp.float32)

    def step(carry, i):
        o, m, l, k, v, kseg = carry
        kv_idx = (my_idx - i) % axis_size
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                       preferred_element_type=jnp.float32) * scale
        allowed = _allowed_mask(causal, has_seg, my_idx, kv_idx, S, Sk,
                                seg, kseg)
        if allowed is not None:
            s = jnp.where(allowed, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        l = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        o = o * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (o, m_new, l, k, v, kseg), None

    (o, m, l, k, v, _), _ = lax.scan(step, (o0, m0, l0, k, v, seg),
                                     jnp.arange(axis_size))
    # axis_size permutes = identity: k/v are home again (used by the bwd).
    l_safe = jnp.maximum(l, 1e-30)
    o = (o / l_safe.transpose(0, 3, 1, 2)[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return o.reshape(B, S, Hq, D), lse


# ---------------------------------------------------------------------------
# Backward ring: dK/dV accumulators travel with their K/V blocks.
# ---------------------------------------------------------------------------

def _ring_bwd(axis_name: str, axis_size: int, causal: bool, has_seg: bool,
              res, do):
    q, k, v, o, lse, seg = res
    scale = q.shape[-1] ** -0.5
    my_idx = lax.axis_index(axis_name)
    B, S, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    perm = _ring_perm(axis_size)
    q5 = _group(q, Hkv)
    do5 = _group(do, Hkv)

    # delta_i = sum_d do_i * o_i  (rowwise), [B, Hkv, G, S]
    delta = jnp.einsum("bqhgd,bqhgd->bhgq", do5.astype(jnp.float32),
                       _group(o, Hkv).astype(jnp.float32))

    dq0 = jnp.zeros(q5.shape, jnp.float32)
    dk0 = jnp.zeros_like(k, jnp.float32)
    dv0 = jnp.zeros_like(v, jnp.float32)

    def step(carry, i):
        dq, k, v, dk, dv, kseg = carry
        kv_idx = (my_idx - i) % axis_size
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[..., None])
        allowed = _allowed_mask(causal, has_seg, my_idx, kv_idx, S, Sk,
                                seg, kseg)
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        dv = dv + jnp.einsum("bhgqk,bqhgd->bkhd", p.astype(do.dtype), do5,
                             preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", do5, v,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        ds_c = ds.astype(q.dtype)
        dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", ds_c, k,
                             preferred_element_type=jnp.float32)
        dk = dk + jnp.einsum("bhgqk,bqhgd->bkhd", ds_c, q5,
                             preferred_element_type=jnp.float32)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (dq, k, v, dk, dv, kseg), None

    (dq, k, v, dk, dv, _), _ = lax.scan(step, (dq0, k, v, dk0, dv0, seg),
                                        jnp.arange(axis_size))
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    return (dq.reshape(B, S, Hq, D).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype), dseg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ring_attn(axis_name: str, axis_size: int, causal: bool, has_seg: bool,
               q, k, v, seg):
    o, _ = _ring_fwd(axis_name, axis_size, causal, has_seg, q, k, v, seg)
    return o


def _ring_attn_fwd(axis_name, axis_size, causal, has_seg, q, k, v, seg):
    o, lse = _ring_fwd(axis_name, axis_size, causal, has_seg, q, k, v, seg)
    return o, (q, k, v, o, lse, seg)


_ring_attn.defvjp(_ring_attn_fwd, _ring_bwd)


# ---------------------------------------------------------------------------
# Zigzag ring: load-balanced causal context parallelism
# ---------------------------------------------------------------------------
# The plain causal ring wastes work: at every step some device's whole
# K/V block is in its masked future, yet lockstep ppermutes mean nobody
# finishes early. The zigzag layout splits the sequence into 2n chunks
# and gives device i chunks (i, 2n-1-i). Then for any remote block from
# device j, exactly one of two MASKLESS half-einsums is needed:
#
#   i > j : ALL local queries attend the block's LOW chunk only
#           (q[2c] x k[:c]) — its high chunk is entirely future.
#   i < j : only the local HIGH-chunk queries attend, but to the whole
#           block (q[c:] x k[2c]) — low queries see only future.
#
# Both cases cost 2c^2 (vs the plain ring's 4c^2 per step), every
# device does the same amount at every step, and only the t=0 local
# block needs a mask at all. ~2x attention FLOPs saving as n grows.
# (This is the zigzag scheme from public ring-flash-attention work,
# expressed as lax.cond branches whose outputs share one accumulator
# pytree — XLA executes exactly one branch per step.)

def zigzag_indices(seq_len: int, n: int):
    """Global row order for the zigzag layout: shard i holds chunks
    (i, 2n-1-i). Returns (permute_idx, unpermute_idx)."""
    if seq_len % (2 * n) != 0:
        raise ValueError(f"seq {seq_len} not divisible by 2*{n}")
    c = seq_len // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * c, (i + 1) * c))
        order.extend(range((2 * n - 1 - i) * c, (2 * n - i) * c))
    perm = np.asarray(order, dtype=np.int32)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len, dtype=np.int32)
    return perm, inv


def zigzag_permute(x, n: int, axis: int = 1):
    perm, _ = zigzag_indices(x.shape[axis], n)
    return jnp.take(x, perm, axis=axis)


def zigzag_unpermute(x, n: int, axis: int = 1):
    _, inv = zigzag_indices(x.shape[axis], n)
    return jnp.take(x, inv, axis=axis)


def apply_zigzag_layout(x, positions, segment_ids, mesh, rules):
    """The model-side half of the zigzag layout contract, shared by
    every decoder model (llama, moe): decide whether the zigzag layout
    applies (rules ask for it, the sequence mesh-axis is > 1, and S
    divides 2*n), permute activations/positions/segment ids once, and
    strip the layout key on fallback so the attention dispatch always
    agrees with the actual layout.

    x: [B, S, D] post-embedding activations. Returns
    ``(x, positions, segment_ids, layer_rules, use_zigzag, n_sp)``;
    the caller runs its decoder stack under ``layer_rules`` and, when
    ``use_zigzag``, un-permutes the final hidden states with
    ``zigzag_unpermute(x, n_sp)``.
    """
    use_zigzag, n_sp = False, 1
    if mesh is not None and rules is not None \
            and rules.get("seq_layout") == "zigzag":
        S = x.shape[1]
        seq_axis = rules.get("seq")
        n_sp = (mesh.shape.get(seq_axis, 1)
                if isinstance(seq_axis, str) else 1)
        use_zigzag = n_sp > 1 and S % (2 * n_sp) == 0
        if use_zigzag:
            x = zigzag_permute(x, n_sp)
            positions = zigzag_permute(positions, n_sp,
                                       axis=positions.ndim - 1)
            if segment_ids is not None:
                segment_ids = zigzag_permute(segment_ids, n_sp)
    layer_rules = rules
    if rules is not None and rules.get("seq_layout") == "zigzag" \
            and not use_zigzag:
        # Divisibility fallback: drop the layout key so the attention
        # dispatch agrees with the (unpermuted) layout.
        layer_rules = {k: v for k, v in rules.items()
                       if k != "seq_layout"}
    return x, positions, segment_ids, layer_rules, use_zigzag, n_sp


def _zz_positions(my_idx, n: int, c: int):
    """Global positions of this device's 2c local rows."""
    lo = my_idx * c + jnp.arange(c)
    hi = (2 * n - 1 - my_idx) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


def _zz_seg_mask(q_seg, k_seg):
    """[B,1,1,Sq,Sk] same-segment mask (None when unsegmented)."""
    return (q_seg[:, None, None, :, None]
            == k_seg[:, None, None, None, :])


def _online_update(o, m, l, s, v5, allowed, v_dtype):
    """One online-softmax accumulation of scores s against values v5.
    o [B,S,Hkv,G,D] (S rows matching s's q dim), m/l [B,Hkv,G,S]."""
    if allowed is not None:
        s = jnp.where(allowed, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if allowed is not None:
        p = jnp.where(allowed, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v_dtype), v5,
                    preferred_element_type=jnp.float32)
    o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + pv
    return o_new, m_new, l_new


def _zigzag_fwd(axis_name: str, axis_size: int, has_seg: bool,
                q, k, v, seg):
    """Zigzag-layout causal forward. Local q/k/v hold chunks
    (i, 2n-1-i) concatenated; returns (o, lse) in the same layout."""
    scale = q.shape[-1] ** -0.5
    n = axis_size
    my_idx = lax.axis_index(axis_name)
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    c = S // 2
    perm = _ring_perm(n)
    q5 = _group(q, Hkv)
    pos_q = _zz_positions(my_idx, n, c)

    o0 = jnp.zeros((B, S, Hkv, Hq // Hkv, D), jnp.float32)
    m0 = jnp.full((B, Hkv, Hq // Hkv, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, Hq // Hkv, S), jnp.float32)

    def step(carry, t):
        o, m, l, k, v, kseg = carry
        j = (my_idx - t) % n

        def local_block(_):
            # t == 0: the only masked step — full local attention with
            # the zigzag-position causal mask.
            s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                           preferred_element_type=jnp.float32) * scale
            allowed = (pos_q[:, None] >= pos_q[None, :])
            if has_seg:
                allowed = allowed & _zz_seg_mask(seg, kseg)
            return _online_update(o, m, l, s, v, allowed, v.dtype)

        def low_only(_):
            # i > j: everything attends the block's low chunk; maskless.
            ka, va = k[:, :c], v[:, :c]
            s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, ka,
                           preferred_element_type=jnp.float32) * scale
            allowed = (_zz_seg_mask(seg, kseg[:, :c]) if has_seg
                       else None)
            return _online_update(o, m, l, s, va, allowed, v.dtype)

        def high_rows(_):
            # i < j: only the high-chunk queries attend, to everything;
            # maskless. Low-row accumulators pass through untouched.
            qb = q5[:, c:]
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qb, k,
                           preferred_element_type=jnp.float32) * scale
            allowed = (_zz_seg_mask(seg[:, c:], kseg) if has_seg
                       else None)
            o_hi, m_hi, l_hi = _online_update(
                o[:, c:], m[..., c:], l[..., c:], s, v, allowed, v.dtype)
            return (jnp.concatenate([o[:, :c], o_hi], axis=1),
                    jnp.concatenate([m[..., :c], m_hi], axis=-1),
                    jnp.concatenate([l[..., :c], l_hi], axis=-1))

        o, m, l = lax.cond(
            t == 0, local_block,
            lambda _: lax.cond(my_idx > j, low_only, high_rows, _),
            None)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (o, m, l, k, v, kseg), None

    (o, m, l, k, v, _), _ = lax.scan(step, (o0, m0, l0, k, v, seg),
                                     jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-30)
    o = (o / l_safe.transpose(0, 3, 1, 2)[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return o.reshape(B, S, Hq, D), lse


def _zigzag_bwd(axis_name: str, axis_size: int, has_seg: bool, res, do):
    q, k, v, o, lse, seg = res
    scale = q.shape[-1] ** -0.5
    n = axis_size
    my_idx = lax.axis_index(axis_name)
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    c = S // 2
    perm = _ring_perm(n)
    q5 = _group(q, Hkv)
    do5 = _group(do, Hkv)
    pos_q = _zz_positions(my_idx, n, c)
    delta = jnp.einsum("bqhgd,bqhgd->bhgq", do5.astype(jnp.float32),
                       _group(o, Hkv).astype(jnp.float32))

    dq0 = jnp.zeros(q5.shape, jnp.float32)
    dk0 = jnp.zeros_like(k, jnp.float32)
    dv0 = jnp.zeros_like(v, jnp.float32)

    def _block_grads(qp, dop, lsep, deltap, kp, vp, allowed):
        """Gradients of one maskless-or-masked sub-block.
        Returns (dq_part, dk_part, dv_part)."""
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qp, kp,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lsep[..., None])
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        dv = jnp.einsum("bhgqk,bqhgd->bkhd", p.astype(dop.dtype), dop,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", dop, vp,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - deltap[..., None]) * scale).astype(q.dtype)
        dq = jnp.einsum("bhgqk,bkhd->bqhgd", ds, kp,
                        preferred_element_type=jnp.float32)
        dk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qp,
                        preferred_element_type=jnp.float32)
        return dq, dk, dv

    def step(carry, t):
        dq, k, v, dk, dv, kseg = carry
        j = (my_idx - t) % n

        def local_block(_):
            allowed = (pos_q[:, None] >= pos_q[None, :])
            if has_seg:
                allowed = allowed & _zz_seg_mask(seg, kseg)
            dq_p, dk_p, dv_p = _block_grads(q5, do5, lse, delta, k, v,
                                            allowed)
            return dq + dq_p, dk + dk_p, dv + dv_p

        def low_only(_):
            allowed = (_zz_seg_mask(seg, kseg[:, :c]) if has_seg
                       else None)
            dq_p, dk_p, dv_p = _block_grads(q5, do5, lse, delta,
                                            k[:, :c], v[:, :c], allowed)
            zeros_k = jnp.zeros_like(dk[:, c:])
            return (dq + dq_p,
                    dk + jnp.concatenate([dk_p, zeros_k], axis=1),
                    dv + jnp.concatenate([dv_p, zeros_k], axis=1))

        def high_rows(_):
            allowed = (_zz_seg_mask(seg[:, c:], kseg) if has_seg
                       else None)
            dq_p, dk_p, dv_p = _block_grads(
                q5[:, c:], do5[:, c:], lse[..., c:], delta[..., c:],
                k, v, allowed)
            dq_new = jnp.concatenate([dq[:, :c], dq[:, c:] + dq_p],
                                     axis=1)
            return dq_new, dk + dk_p, dv + dv_p

        dq, dk, dv = lax.cond(
            t == 0, local_block,
            lambda _: lax.cond(my_idx > j, low_only, high_rows, _),
            None)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (dq, k, v, dk, dv, kseg), None

    (dq, k, v, dk, dv, _), _ = lax.scan(step, (dq0, k, v, dk0, dv0, seg),
                                        jnp.arange(n))
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    return (dq.reshape(B, S, Hq, D).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype), dseg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _zigzag_attn(axis_name: str, axis_size: int, has_seg: bool,
                 q, k, v, seg):
    o, _ = _zigzag_fwd(axis_name, axis_size, has_seg, q, k, v, seg)
    return o


def _zigzag_attn_fwd(axis_name, axis_size, has_seg, q, k, v, seg):
    o, lse = _zigzag_fwd(axis_name, axis_size, has_seg, q, k, v, seg)
    return o, (q, k, v, o, lse, seg)


_zigzag_attn.defvjp(_zigzag_attn_fwd, _zigzag_bwd)


def zigzag_ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                          batch_axes=("dp", "fsdp"),
                          heads_axis: Optional[str] = "tp",
                          segment_ids=None):
    """Load-balanced CAUSAL ring attention over `axis`. Inputs must be
    in the zigzag layout (`zigzag_permute` along the sequence, together
    with positions/segment ids); the output stays in that layout, so a
    model that permutes once at the input never pays a resharding
    (the loss is order-invariant under a jointly-permuted mask)."""
    n = mesh.shape[axis]
    if q.shape[1] % (2 * n) != 0:
        raise ValueError(
            f"zigzag needs seq divisible by 2*{axis}={2 * n}, got "
            f"{q.shape[1]}")
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(f"q heads {q.shape[2]} not a multiple of kv "
                         f"heads {k.shape[2]}")
    q_spec, kv_spec, seg_spec = _qkv_specs(mesh, axis, batch_axes,
                                           heads_axis, q, k)
    has_seg = segment_ids is not None
    seg = segment_ids if has_seg else _dummy_seg(q)
    fn = _shard_map(
        functools.partial(_zigzag_attn, axis, n, has_seg),
        mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec, seg_spec),
        out_specs=q_spec, check_vma=False)
    return fn(q, k, v, seg)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) local body
# ---------------------------------------------------------------------------

def _ulysses_local(axis_name: str, axis_size: int, causal: bool,
                   has_seg: bool, q, k, v, seg):
    """[B, S/n, H, D] local -> attention over full seq on H/n heads."""
    from skypilot_tpu.ops import attention as attn_ops
    # seq-sharded -> head-sharded: split heads (axis 2), concat seq (axis 1)
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    seg_full = None
    if has_seg:
        # The id vector is tiny: gather the full sequence's ids locally.
        seg_full = lax.all_gather(seg, axis_name, axis=1, tiled=True)
    o = attn_ops.gqa_attention(q, k, v, causal=causal,
                               segment_ids=seg_full)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


# ---------------------------------------------------------------------------
# Public API (global arrays, jit-compatible: shard_map inside jit)
# ---------------------------------------------------------------------------

def _batch_spec(batch_axes, mesh: Mesh, b: int):
    """Largest prefix of batch_axes whose product divides b (else None)."""
    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    keep = []
    prod = 1
    for a in batch_axes or ():
        prod *= mesh.shape[a]
        if b % prod != 0:
            break
        keep.append(a)
    return tuple(keep) if keep else None


def _qkv_specs(mesh: Mesh, axis: str, batch_axes, heads_axis, q, k):
    """Shard specs with the same divisibility fallback as sharding.spec_for:
    a dim the mapped axis does not divide is replicated, not an error.

    Heads sharding is all-or-nothing across q AND kv: sharding q heads
    while replicating kv heads would re-pair grouped (GQA) heads with the
    wrong kv head inside each shard.
    """
    bspec = _batch_spec(batch_axes, mesh, q.shape[0])
    hspec = heads_axis
    if (heads_axis is None
            or q.shape[2] % mesh.shape[heads_axis] != 0
            or k.shape[2] % mesh.shape[heads_axis] != 0):
        hspec = None
    q_spec = P(bspec, axis, hspec, None)
    kv_spec = P(bspec, axis, hspec, None)
    seg_spec = P(bspec, axis)
    return q_spec, kv_spec, seg_spec


def _dummy_seg(q):
    return jnp.zeros((q.shape[0], q.shape[1]), jnp.int32)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   axis: str = "sp", batch_axes=("dp", "fsdp"),
                   heads_axis: Optional[str] = "tp",
                   segment_ids=None):
    """Ring attention over `axis`. q [B,S,Hq,D]; k/v [B,S,Hkv,D] (GQA ok:
    Hq % Hkv == 0; unrepeated K/V heads circulate the ring).
    ``segment_ids`` [B, S] enables packed-sequence masking."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"seq {q.shape[1]} not divisible by {axis}={n}")
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(f"q heads {q.shape[2]} not a multiple of kv heads "
                         f"{k.shape[2]}")
    q_spec, kv_spec, seg_spec = _qkv_specs(mesh, axis, batch_axes,
                                           heads_axis, q, k)
    has_seg = segment_ids is not None
    seg = segment_ids if has_seg else _dummy_seg(q)
    fn = _shard_map(
        functools.partial(_ring_attn, axis, n, causal, has_seg),
        mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec, seg_spec),
        out_specs=q_spec, check_vma=False)
    return fn(q, k, v, seg)


def ulysses_attention(q, k, v, mesh: Mesh, causal: bool = True,
                      axis: str = "sp", batch_axes=("dp", "fsdp"),
                      heads_axis: Optional[str] = "tp",
                      segment_ids=None):
    """All-to-all (Ulysses) sequence parallelism over `axis`.

    Requires per-shard head counts (q and kv) divisible by the sp size:
    the all_to_all converts the seq shard into a head shard.
    ``segment_ids`` [B, S] enables packed-sequence masking.
    """
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"seq {q.shape[1]} not divisible by {axis}={n}")
    q_spec, kv_spec, seg_spec = _qkv_specs(mesh, axis, batch_axes,
                                           heads_axis, q, k)
    tp = mesh.shape[heads_axis] if q_spec[2] is not None else 1
    for name, arr in (("q", q), ("kv", k)):
        local_heads = arr.shape[2] // (tp if arr.shape[2] % tp == 0 else 1)
        if local_heads % n != 0:
            raise ValueError(
                f"{name} heads/shard = {local_heads} not divisible by "
                f"{axis}={n}; use ring_attention instead")
    has_seg = segment_ids is not None
    seg = segment_ids if has_seg else _dummy_seg(q)
    fn = _shard_map(
        functools.partial(_ulysses_local, axis, n, causal, has_seg),
        mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec, seg_spec),
        out_specs=q_spec, check_vma=False)
    return fn(q, k, v, seg)


def context_parallel_attention(q, k, v, mesh: Mesh, causal: bool = True,
                               impl: str = "ring", **kw):
    """Dispatch: impl in {"ring", "ulysses"}."""
    if impl == "ulysses":
        return ulysses_attention(q, k, v, mesh, causal=causal, **kw)
    return ring_attention(q, k, v, mesh, causal=causal, **kw)
