"""jax.distributed bootstrap from the runtime env contract.

The gang driver (runtime/driver.py) injects JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID on every host, plus MEGASCALE_* on
multislice clusters (runtime/constants.py). jax reads only the
coordinator address natively, so user programs call this helper to join
the cluster-wide rendezvous with zero arguments.

Reference parity: the reference's contract is torchrun-shaped env vars
consumed by the user's launcher (sky/skylet/constants.py:319-322);
here the contract is jax-native and this helper is the launcher.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ProcessTopology:
    num_processes: int
    process_id: int
    num_slices: int
    slice_id: int
    coordinator: Optional[str]


def topology_from_env() -> ProcessTopology:
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    num = int(os.environ.get("JAX_NUM_PROCESSES")
              or os.environ.get("SKYTPU_NUM_HOSTS") or "1")
    pid = int(os.environ.get("JAX_PROCESS_ID")
              or os.environ.get("SKYTPU_HOST_ID") or "0")
    n_slices = int(os.environ.get("MEGASCALE_NUM_SLICES") or "1")
    slice_id = int(os.environ.get("MEGASCALE_SLICE_ID") or "0")
    return ProcessTopology(num, pid, n_slices, slice_id, coord)


def initialize_from_env() -> ProcessTopology:
    """Join the cluster-wide jax.distributed rendezvous using only the
    injected env. No-op for single-process jobs. Idempotent."""
    topo = topology_from_env()
    if topo.num_processes > 1 and topo.coordinator:
        import jax
        from jax._src import distributed as _dist
        if getattr(_dist.global_state, "client", None) is None:
            jax.distributed.initialize(
                coordinator_address=topo.coordinator,
                num_processes=topo.num_processes,
                process_id=topo.process_id)
    return topo
