"""Checkpoint/resume: async Orbax snapshots of the sharded TrainState.

TPU-first design notes
----------------------
* Saves are **async**: the train loop donates nothing and keeps stepping
  while Orbax streams device shards to storage in a background thread —
  on a pod slice each host writes only its own shards (process-local
  data), which is what makes 8B+ states practical.
* Restore takes an *abstract* target (shapes + shardings from
  ``trainer.state_shardings``), so parameters land already distributed —
  no host-RAM full copy, same property as sharded init.
* The directory can be a GCS path (``gs://...``) on TPU-VMs — this is
  the first-class replacement for the reference's bucket-mounted
  checkpoint pattern (reference: llm/llama-3_1-finetuning/lora.yaml:24-30
  — /output bucket mount + workload-side resume; SURVEY.md §5
  "Checkpoint/resume — not in-framework").

Reference parity: reference has no in-framework checkpointing; this is
the TPU-native upgrade called for by SURVEY.md §7 stage 8.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

import jax

from skypilot_tpu import chaos
from skypilot_tpu.observability import attribution
from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.utils import timeline

# Saves are async: ``_save_seconds`` is the dispatch cost the train
# loop pays inline; ``_wait_seconds`` is the durability tail paid at
# wait(). Their sum bounds the true checkpoint wall time.
CKPT_SAVE_SECONDS = obs_metrics.histogram(
    "skytpu_checkpoint_save_seconds",
    "CheckpointManager.save dispatch latency (async saves: the inline "
    "cost only)")
CKPT_WAIT_SECONDS = obs_metrics.histogram(
    "skytpu_checkpoint_wait_seconds",
    "CheckpointManager.wait latency (async save durability tail)")
CKPT_SAVES = obs_metrics.counter(
    "skytpu_checkpoint_saves_total", "Checkpoint saves accepted")
CKPT_BYTES = obs_metrics.gauge(
    "skytpu_ckpt_bytes",
    "Analytical bytes of the last saved checkpoint state by tensor "
    "family (params, opt_state, total) — nbytes metadata only, never "
    "a device fetch",
    labelnames=("kind",))
CKPT_LAST_DURATION = obs_metrics.gauge(
    "skytpu_ckpt_last_duration_seconds",
    "Most recent checkpoint operation wall seconds by op (save = "
    "async dispatch, wait = durability tail, restore = full restore "
    "wall) — restore feeds the goodput restart_replay bucket",
    labelnames=("op",))


def _publish_state_bytes(state: Any) -> None:
    total = attribution.tensor_bytes(state)
    CKPT_BYTES.labels(kind="total").set(total)
    if hasattr(state, "get"):
        for kind in ("params", "opt_state"):
            part = state.get(kind)
            if part is not None:
                CKPT_BYTES.labels(kind=kind).set(
                    attribution.tensor_bytes(part))


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Usage::

        mgr = checkpoints.CheckpointManager(path, max_to_keep=3)
        for step in range(...):
            state, metrics = train_step(state, batch)
            mgr.save(step, state)          # async, returns immediately
        mgr.wait()

        # Resume (possibly in a fresh process):
        target = trainer.create_abstract_state(cfg, tc, mesh)
        state = mgr.restore(target)        # lands sharded
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        directory = os.path.expanduser(directory)
        if "://" not in directory:
            os.makedirs(directory, exist_ok=True)
            directory = os.path.abspath(directory)
        self.directory = directory
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Queue an async save. Returns False if skipped by interval."""
        chaos.point("train.checkpoint_save", step=int(step))
        t0 = time.monotonic()
        with tracing.start_span("train.checkpoint_save",
                                attrs={"step": int(step)}), \
                timeline.Event("skytpu_checkpoint_save_seconds",
                               histogram=CKPT_SAVE_SECONDS):
            saved = self._mgr.save(
                step, args=self._ocp.args.StandardSave(state),
                force=force)
        CKPT_LAST_DURATION.labels(op="save").set(time.monotonic() - t0)
        if saved:
            CKPT_SAVES.inc()
            _publish_state_bytes(state)
        return saved

    def restore(self, target: Optional[Any] = None,
                step: Optional[int] = None) -> Any:
        """Restore ``step`` (default: latest). ``target`` is an abstract
        pytree (jax.ShapeDtypeStruct with .sharding) for sharded landing;
        None restores as numpy on host."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        chaos.point("train.checkpoint_restore", step=int(step))
        t0 = time.monotonic()
        with tracing.start_span("train.checkpoint_restore",
                                attrs={"step": int(step)}):
            if target is None:
                out = self._mgr.restore(step)
            else:
                out = self._mgr.restore(
                    step, args=self._ocp.args.StandardRestore(target))
        CKPT_LAST_DURATION.labels(op="restore").set(
            time.monotonic() - t0)
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self) -> None:
        """Block until queued async saves are durable."""
        t0 = time.monotonic()
        with tracing.start_span("train.checkpoint_wait"), \
                timeline.Event("skytpu_checkpoint_wait_seconds",
                               histogram=CKPT_WAIT_SECONDS):
            self._mgr.wait_until_finished()
        CKPT_LAST_DURATION.labels(op="wait").set(time.monotonic() - t0)

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def abstract_like(state: Any) -> Any:
    """ShapeDtypeStruct pytree (with shardings) matching a live state."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        state)
