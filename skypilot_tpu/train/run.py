"""Training recipe entry point: ``python -m skypilot_tpu.train.run``.

The in-tree replacement for the reference's external workload recipes
(reference: examples/tpu/v6e/train-llama3-8b.yaml runs a PyTorch/XLA
HF Trainer; llm/llama-3_1-finetuning/ runs torchtune). One process per
TPU host; multi-host slices initialize jax.distributed from the env
contract injected by the runtime (SKYTPU_COORDINATOR, SKYTPU_NUM_HOSTS,
SKYTPU_HOST_ID — runtime/driver.py).

Examples::

    # single host, FSDP over all local chips:
    python -m skypilot_tpu.train.run --config llama3-400m --steps 100

    # 4-host v5p-16, fsdp x tp, checkpoints to a bucket mount:
    python -m skypilot_tpu.train.run --config llama3-8b --tp 4 \
        --steps 1000 --ckpt-dir /outputs/ckpts --ckpt-every 100

    # MoE with expert parallelism:
    python -m skypilot_tpu.train.run --model moe --config moe-small --ep 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama",
                    choices=("llama", "moe", "pipeline"))
    ap.add_argument("--config", default=None)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=0,
                    help="global batch (0 = 4 x data-parallel degree)")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--xent-chunk", type=int, default=None,
                    help="chunked cross-entropy: the [B,S,vocab] logits "
                         "never materialize (512 is the measured v5e "
                         "sweet spot; 0 = unchunked)")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--packed", action="store_true",
                    help="packed-sequence input pipeline (segment-aware "
                         "attention) over synthetic variable-length docs")
    ap.add_argument("--zigzag", action="store_true",
                    help="zigzag (load-balanced causal) ring attention "
                         "for sp>1; llama only")
    ap.add_argument("--lora", type=int, default=0, metavar="RANK",
                    help="LoRA finetune: train rank-RANK adapters over "
                         "frozen base weights (llama only)")
    ap.add_argument("--qlora", type=int, default=0, metavar="RANK",
                    help="QLoRA finetune: int8-quantized frozen base + "
                         "rank-RANK adapters — 8B-class on one 16 GB "
                         "chip (llama only, single chip; use --lora "
                         "for sharded multi-chip)")
    ap.add_argument("--qlora-random-base", action="store_true",
                    help="random int8 base generated ON device (bench/"
                         "smoke: skips the fp init an 8B config can't "
                         "fit)")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--log-every", type=int, default=10)
    args = ap.parse_args()
    if args.packed and args.model == "pipeline":
        ap.error("--packed is not supported with --model pipeline")
    if args.lora and args.model != "llama":
        ap.error("--lora currently supports --model llama only")
    if args.lora < 0:
        ap.error("--lora rank must be positive")
    if args.qlora:
        if args.model != "llama":
            ap.error("--qlora currently supports --model llama only")
        if args.lora:
            ap.error("--lora and --qlora are mutually exclusive")
        if args.qlora < 0:
            ap.error("--qlora rank must be positive")
    if args.zigzag and args.model not in ("llama", "moe"):
        # Only llama's and moe's forwards apply the zigzag permute;
        # letting the rule reach another model would silently mis-mask
        # attention.
        ap.error("--zigzag supports --model llama or moe only")

    # Multi-host: join the cluster-wide jax.distributed rendezvous using
    # the runtime's env contract (runtime/constants.py) before touching
    # devices. Multislice (MEGASCALE_*) is consumed by libtpu directly.
    from skypilot_tpu.parallel.distributed import initialize_from_env
    initialize_from_env()

    import jax

    import skypilot_tpu.callbacks as sky_callback
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import sharding as sh_rules
    from skypilot_tpu.train import trainer

    if args.model == "llama":
        from skypilot_tpu.models import llama as model
        default_cfg = "llama3-400m"
    elif args.model == "moe":
        from skypilot_tpu.models import moe as model
        default_cfg = "moe-small"
    else:
        from skypilot_tpu.parallel import pipeline as model
        default_cfg = "pp-tiny"
    cfg = model.CONFIGS[args.config or default_cfg]
    if args.xent_chunk is not None:
        import dataclasses
        if not hasattr(cfg, "xent_chunk"):
            ap.error(f"--xent-chunk is not supported by {args.model}")
        cfg = dataclasses.replace(cfg, xent_chunk=args.xent_chunk)
    args.seq = min(args.seq, cfg.max_seq_len)

    n = jax.device_count()
    shape = mesh_lib.default_shape_for(n, tp=args.tp, sp=args.sp,
                                       dp=args.dp, ep=args.ep, pp=args.pp)
    mesh = mesh_lib.make_mesh(shape)
    log(f"mesh: {shape.as_dict()} over {n} devices "
        f"({jax.devices()[0].device_kind})")

    data_degree = shape.dp * shape.fsdp
    batch = args.batch or 4 * data_degree
    if batch % data_degree:
        batch = data_degree * max(1, batch // data_degree)
    micro = getattr(cfg, "n_microbatches", None)
    if micro and batch % micro:
        batch = micro * max(1, batch // micro)

    tc = trainer.TrainConfig(learning_rate=args.lr,
                             warmup_steps=max(1, min(100, args.steps // 10)),
                             total_steps=args.steps)

    act_rules = sh_rules.ACT_RULES
    if args.zigzag:
        act_rules = dict(act_rules, seq_layout="zigzag")

    # Goodput forensics: the trainer's own compile watch (a mid-run
    # retrace is a typed train.unexpected_compile), sampled device-time
    # calibration, the per-step phase ledger, and the anomaly watchdog
    # over the losses the logging cadence fetches anyway. The roofline
    # peak is published here so skytpu top's train MFU has its
    # denominator in a train-only process.
    from skypilot_tpu.observability import attribution, flight
    from skypilot_tpu.observability import goodput as goodput_lib
    peak_f, peak_bw = attribution.device_peaks()
    attribution.ROOFLINE_PEAK_FLOPS.set(peak_f * n)
    attribution.ROOFLINE_PEAK_BW.set(peak_bw * n)
    watch = flight.CompileWatch(event_name="train.unexpected_compile")
    watch.calibrator = attribution.DeviceTimeCalibrator()
    gp = goodput_lib.GoodputRecorder(
        param_count=cfg.num_params() if hasattr(cfg, "num_params") else 0,
        watch=watch, calibrator=watch.calibrator)
    watchdog = goodput_lib.AnomalyWatchdog(goodput=gp)

    mgr = None
    start_step = 0
    state = None
    if args.ckpt_dir:
        from skypilot_tpu.train import checkpoints
        mgr = checkpoints.CheckpointManager(args.ckpt_dir)

    if args.qlora:
        if n > 1:
            # The int8 base is unsharded: pin everything to one chip
            # (the whole point is one-16GB-chip finetuning) rather
            # than dying on multi-chip hosts like v5e-8. Sharded
            # multi-chip finetuning is --lora.
            log(f"--qlora is single-chip: using 1 of {n} devices "
                f"(use --lora for sharded multi-chip finetuning)")
            jax.config.update("jax_default_device", jax.devices()[0])
            n = 1
            batch = args.batch or 4
        from skypilot_tpu.infer import kvcache
        from skypilot_tpu.train import lora as lora_lib
        from skypilot_tpu.train import qlora as qlora_lib
        lc = lora_lib.LoRAConfig(rank=args.qlora)
        if args.qlora_random_base:
            fp_params, qweights = kvcache.random_quantized_params(cfg)
        else:
            base = jax.jit(
                lambda r: model.init_params(r, cfg))(jax.random.key(1))
            qweights = {
                "blocks": jax.jit(kvcache.quantize_block_weights)(base),
                "head": jax.jit(
                    lambda p: kvcache.quantize_head(p, cfg))(base),
            }
            fp_params = kvcache.slim_params(base)
            del base   # the int8 copy replaces the fp block weights
        log(f"QLoRA rank {args.qlora}: "
            f"{lora_lib.num_trainable_params(cfg, lc):,} trainable over "
            f"an int8 base of {cfg.num_params():,} params")
        if mgr and args.resume and mgr.latest_step() is not None:
            gp.load_stamps(mgr.directory)
            # The adapter state tree is identical to --lora's.
            with gp.account("restart_replay"):
                state = mgr.restore(
                    lora_lib.abstract_lora_state(cfg, lc, tc, mesh=None))
            start_step = int(mgr.latest_step())
            log(f"resumed from step {start_step}")
        else:
            state = qlora_lib.create_qlora_state(cfg, lc, tc)
        raw_step = qlora_lib.make_qlora_train_step(cfg, lc, tc)
        step_fn = lambda s, b: raw_step(s, qweights, fp_params, b)
    elif args.lora:
        from skypilot_tpu.train import lora as lora_lib
        lc = lora_lib.LoRAConfig(rank=args.lora)
        base_sh = lora_lib.base_param_shardings(cfg, mesh, model)
        base_params = jax.jit(
            lambda r: model.init_params(r, cfg),
            out_shardings=base_sh)(jax.random.key(1))
        log(f"LoRA rank {args.lora}: "
            f"{lora_lib.num_trainable_params(cfg, lc):,} trainable / "
            f"{cfg.num_params():,} base params (frozen)")
        if mgr and args.resume and mgr.latest_step() is not None:
            gp.load_stamps(mgr.directory)
            with gp.account("restart_replay"):
                state = mgr.restore(
                    lora_lib.abstract_lora_state(cfg, lc, tc, mesh))
            start_step = int(mgr.latest_step())
            log(f"resumed from step {start_step}")
        else:
            state = lora_lib.create_lora_state(cfg, lc, tc, mesh)
        raw_step = lora_lib.make_lora_train_step(cfg, lc, tc, mesh,
                                                 model=model,
                                                 base_sh=base_sh,
                                                 act_rules=act_rules)
        step_fn = lambda s, b: raw_step(s, base_params, b)
    else:
        step_fn = trainer.make_train_step(cfg, tc, mesh, model=model,
                                          act_rules=act_rules,
                                          watch=watch)
        if mgr and args.resume and mgr.latest_step() is not None:
            gp.load_stamps(mgr.directory)
            with gp.account("restart_replay"):
                target = trainer.create_abstract_state(cfg, tc, mesh,
                                                       model=model)
                state = mgr.restore(target)
            start_step = int(mgr.latest_step())
            log(f"resumed from step {start_step}")
        if state is None:
            with gp.account("warmup_compile"):
                state = trainer.create_train_state(cfg, tc, mesh,
                                                   model=model)

    if args.packed:
        import jax.numpy as jnp

        from skypilot_tpu.data import input_pipeline as ip

        def batch_stream():
            seed = 0
            while True:
                docs = ip.synthetic_doc_stream(
                    256, cfg.vocab_size, mean_len=args.seq // 3,
                    seed=seed)
                yield from ip.packed_batches(docs, batch, args.seq)
                seed += 1

        batches = ip.prefetch(
            batch_stream(),
            device_put=lambda b: {k: jnp.asarray(v)
                                  for k, v in b.items()})
    else:
        batches = None
        batch_data = trainer.synthetic_batch(cfg, batch, args.seq)
    if start_step >= args.steps:
        log(f"checkpoint already at step {start_step}; nothing to train")
        if mgr:
            mgr.close()
        print(json.dumps({"steps": 0, "resumed_step": start_step,
                          "mesh": shape.as_dict()}))
        return
    sky_callback.init(total_steps=args.steps)
    t0 = time.time()
    for step in range(start_step, args.steps):
        gp.step_start(step)
        if batches is not None:
            with gp.phase("data_wait"):
                batch_data = next(batches)
        with sky_callback.step():
            with gp.phase("compute"):
                state, metrics = step_fn(state, batch_data)
        if step == start_step:
            # Every program the loop can reach is compiled now; from
            # here a new key is a mid-run retrace worth alarming on.
            watch.declare_warm()
        loss = grad_norm = None
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            with gp.phase("eval"):
                # The deliberate host fetch the logging cadence always
                # paid; grad_norm rides the same sync.
                loss = float(metrics["loss"])
                gn = metrics.get("grad_norm") \
                    if hasattr(metrics, "get") else None
                grad_norm = float(gn) if gn is not None else None
                trainer.observe_loss(loss)
            anomaly = watchdog.observe(step + 1, loss, grad_norm)
            if anomaly:
                log(f"step {step + 1}: train.anomaly "
                    f"{anomaly['kind']} {anomaly}")
            log(f"step {step + 1}/{args.steps} loss={loss:.4f}")
        if mgr and (step + 1) % args.ckpt_every == 0:
            with gp.phase("ckpt_save"):
                mgr.save(step + 1, state)
                gp.persist(mgr.directory)
        tokens = getattr(batch_data.get("tokens"), "size", 0) \
            if hasattr(batch_data, "get") else 0
        gp.step_end(tokens=tokens, loss=loss, grad_norm=grad_norm)
    loss = float(metrics["loss"])  # host fetch = real sync
    wall = time.time() - t0
    if mgr:
        with gp.account("ckpt_stall"):
            if mgr.latest_step() != args.steps:
                mgr.save(args.steps, state, force=True)
            mgr.wait()
        gp.persist(mgr.directory)
        mgr.close()
    snap = gp.snapshot()
    tokens_per_s = batch * args.seq * (args.steps - start_step) / wall
    print(json.dumps({
        "final_loss": round(loss, 4),
        "steps": args.steps - start_step,
        "wall_s": round(wall, 2),
        "tokens_per_sec": round(tokens_per_s, 1),
        "tokens_per_sec_per_chip": round(tokens_per_s / n, 1),
        "goodput": round(snap["goodput_ratio"], 4),
        "mesh": shape.as_dict(),
    }))


if __name__ == "__main__":
    main()
