"""Sharded training loop: optax AdamW + pjit over an explicit mesh.

Everything here is a *thin* orchestration of jitted functions:

  * ``create_train_state`` — params initialized *directly sharded* (init is
    jitted with ``out_shardings``, so no host-side full copy ever exists;
    an 8B model initializes fine on hosts with modest RAM).
  * ``make_train_step`` — one fused step: loss -> grad -> clip -> AdamW ->
    param update, donated state, with activation sharding constraints from
    the rule table. XLA inserts the reduce-scatter/all-gather pattern for
    FSDP and the per-layer all-reduces for TP.

Reference parity: the reference delegates training loops to external
workloads (reference: examples/tpu/v6e/train-llama3-8b.yaml runs
transformers Trainer under PyTorch/XLA). In-tree trainer is the TPU-native
replacement for that recipe layer.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import statistics
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.parallel import sharding as sh

# Step-time note: the histogram records the HOST-side step call. The
# state is donated, so dispatching step k+1 blocks until step k's
# buffers free — at steady state the call duration converges to the
# true device step time without ever forcing a host sync for the
# metric's sake.
STEP_SECONDS = obs_metrics.histogram(
    "skytpu_train_step_seconds",
    "Train step call latency (back-pressured by donated state to the "
    "device step time at steady state)")
TRAIN_STEPS = obs_metrics.counter(
    "skytpu_train_steps_total", "Train steps dispatched")
TRAIN_TOKENS = obs_metrics.counter(
    "skytpu_train_tokens_total", "Tokens dispatched to train steps")
TRAIN_TOKENS_PER_S = obs_metrics.gauge(
    "skytpu_train_tokens_per_second",
    "Dispatch-rate tokens/s, EMA over recent steps")
TRAIN_LOSS = obs_metrics.gauge(
    "skytpu_train_loss",
    "Most recently fetched training loss (see observe_loss)")
# Step-time regression pair for the SLO watchdog: the trailing median
# is the baseline ("what a step normally costs on this run"), and the
# watchdog compares the windowed mean (histogram sum/count delta)
# against it — a data-pipeline stall or a slow host shows up without
# anyone pre-configuring an absolute step-time threshold.
TRAIN_STEP_LAST = obs_metrics.gauge(
    "skytpu_train_step_last_seconds",
    "Most recent post-compile train step wall time")
TRAIN_STEP_MEDIAN = obs_metrics.gauge(
    "skytpu_train_step_median_seconds",
    "Trailing median of recent post-compile step times (SLO regression "
    "baseline)")
_MEDIAN_WINDOW = 101


def observe_loss(loss: float) -> None:
    """Record a fetched loss into the gauge. Called where the train
    loop already pays the host sync (its logging cadence) — the step
    wrapper itself never forces a device fetch."""
    TRAIN_LOSS.set(float(loss))


def _instrument_step(step_fn: Callable) -> Callable:
    ema = {"rate": 0.0, "warm": False}
    recent = collections.deque(maxlen=_MEDIAN_WINDOW)

    @functools.wraps(step_fn)
    def wrapper(state, batch):
        t0 = time.monotonic()
        t0_wall = time.time()
        out = step_fn(state, batch)
        dt = max(time.monotonic() - t0, 1e-9)
        TRAIN_STEPS.inc()
        tokens = getattr(batch.get("tokens"), "size", 0) \
            if hasattr(batch, "get") else 0
        if tokens:
            TRAIN_TOKENS.inc(tokens)
        if not ema["warm"]:
            # The first call pays the XLA compile (tens of seconds at
            # scale); seeding the EMA or the histogram with it would
            # poison both for dozens of steps.
            ema["warm"] = True
            return out
        STEP_SECONDS.observe(dt)
        recent.append(dt)
        TRAIN_STEP_LAST.set(dt)
        TRAIN_STEP_MEDIAN.set(statistics.median(recent))
        # Per-step trace span (joins an ambient trace when the run was
        # launched with one; the compile step is skipped like above).
        tracing.record_span("train.step", t0_wall, t0_wall + dt,
                            attrs={"tokens": tokens} if tokens else None)
        if tokens:
            rate = tokens / dt
            ema["rate"] = (rate if ema["rate"] == 0.0
                           else 0.9 * ema["rate"] + 0.1 * rate)
            TRAIN_TOKENS_PER_S.set(ema["rate"])
        return out

    return wrapper


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    total_steps: int = 10_000
    # Storage dtype for Adam's first moment ("bfloat16" halves that
    # buffer — how billion-param configs fit a 16 GB chip). None keeps
    # the params' dtype.
    mu_dtype: Optional[str] = None


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=tc.learning_rate,
        warmup_steps=tc.warmup_steps,
        # optax requires decay_steps > warmup_steps (the cosine segment
        # length is the difference).
        decay_steps=max(tc.total_steps, tc.warmup_steps + 1, 1),
        end_value=tc.learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(schedule, b1=tc.beta1, b2=tc.beta2,
                    weight_decay=tc.weight_decay,
                    mu_dtype=tc.mu_dtype),
    )


# Train state is a plain dict {"params", "opt_state", "step"}: already a
# pytree with no registration, and pickles trivially. (A dict *subclass*
# would silently become a pytree leaf — do not "upgrade" this.)
TrainState = Dict[str, Any]


def _train_state(params, opt_state, step) -> TrainState:
    return {"params": params, "opt_state": opt_state, "step": step}


def state_shardings(cfg: llama.LlamaConfig, mesh: Mesh,
                    rules: sh.Rules = sh.DEFAULT_RULES, model=llama):
    """Shardings for the full train state (opt state mirrors params)."""
    tc = TrainConfig()
    opt = make_optimizer(tc)
    p_shapes = jax.eval_shape(lambda: model.init_params(jax.random.key(0), cfg))
    opt_shapes = jax.eval_shape(opt.init, p_shapes)
    p_sh = sh.logical_to_sharding(model.param_logical_axes(cfg), mesh, rules,
                                  shapes=p_shapes)

    opt_sh = opt_state_shardings(p_sh, p_shapes, opt_shapes, mesh)
    return {"params": p_sh, "opt_state": opt_sh,
            "step": NamedSharding(mesh, P())}


def opt_state_shardings(param_sh, param_shapes, opt_shapes, mesh: Mesh):
    """Shardings for an optax state given the params' shardings.

    Adam moments have param shapes -> reuse the matching param sharding
    by shape lookup; scalars (step counts) replicate. Shared by the
    full trainer and the LoRA adapter trainer.
    """

    def opt_leaf_sharding(leaf):
        if leaf.ndim == 0:
            return NamedSharding(mesh, P())
        for ps, pl in zip(jax.tree.leaves(param_sh),
                          jax.tree.leaves(param_shapes)):
            if pl.shape == leaf.shape:
                return ps
        return NamedSharding(mesh, P())

    # Walk opt_state structurally: moments subtree matches params treedef.
    return jax.tree.map(opt_leaf_sharding, opt_shapes,
                        is_leaf=lambda x: hasattr(x, "shape"))


def create_train_state(cfg: llama.LlamaConfig, tc: TrainConfig,
                       mesh: Optional[Mesh], seed: int = 0,
                       rules: sh.Rules = sh.DEFAULT_RULES,
                       model=llama) -> TrainState:
    opt = make_optimizer(tc)

    def init_fn(rng):
        params = model.init_params(rng, cfg)
        return _train_state(params, opt.init(params),
                            jnp.zeros((), jnp.int32))

    rng = jax.random.key(seed)
    if mesh is None:
        return jax.jit(init_fn)(rng)
    shardings = state_shardings(cfg, mesh, rules, model)
    return jax.jit(init_fn, out_shardings=shardings)(rng)


def create_abstract_state(cfg: llama.LlamaConfig, tc: TrainConfig,
                          mesh: Optional[Mesh],
                          rules: sh.Rules = sh.DEFAULT_RULES,
                          model=llama) -> TrainState:
    """ShapeDtypeStruct pytree (with shardings) of the train state —
    the restore target for ``train.checkpoints`` without materializing
    anything."""
    opt = make_optimizer(tc)

    def init_fn(rng):
        params = model.init_params(rng, cfg)
        return _train_state(params, opt.init(params),
                            jnp.zeros((), jnp.int32))

    shapes = jax.eval_shape(init_fn, jax.random.key(0))
    if mesh is None:
        return shapes
    shardings = state_shardings(cfg, mesh, rules, model)
    return jax.tree.map(
        lambda s, shd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shd),
        shapes, shardings)


def _batch_key_fn(args: tuple, kwargs: dict):
    """Shape-derived program identity for the trainer's compile watch:
    jit retraces on a new batch shape even under an unchanged entry
    point, and a mid-run shape change is exactly the silent retrace
    ``train.unexpected_compile`` exists to expose."""
    batch = args[1] if len(args) > 1 else kwargs.get("batch")
    parts = []
    if hasattr(batch, "items"):
        for k in sorted(batch):
            shape = getattr(batch[k], "shape", None)
            if shape is not None:
                parts.append((k, "x".join(str(d) for d in shape)))
    return parts


def make_train_step(cfg: llama.LlamaConfig, tc: TrainConfig,
                    mesh: Optional[Mesh],
                    rules: sh.Rules = sh.DEFAULT_RULES,
                    act_rules: sh.Rules = sh.ACT_RULES,
                    model=llama, watch=None) -> Callable:
    """Returns jitted step(state, batch) -> (state, metrics).

    ``watch`` is an optional ``flight.CompileWatch`` (the trainer's
    own, with ``event_name="train.unexpected_compile"``): the jitted
    step is wrapped so every distinct batch-shape identity registers
    as a program, and a post-warmup retrace emits the typed event the
    goodput ledger and SLO watchdog alarm on.
    """
    opt = make_optimizer(tc)
    constrain = sh.make_constrain(mesh, act_rules)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        def lossf(params):
            return model.loss_fn(params, batch, cfg, constrain, mesh,
                                 act_rules)

        (loss, metrics), grads = jax.value_and_grad(lossf, has_aux=True)(
            state["params"])
        updates, new_opt = opt.update(grads, state["opt_state"],
                                      state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        new_state = _train_state(new_params, new_opt, state["step"] + 1)
        metrics = dict(metrics, grad_norm=gnorm)
        return new_state, metrics

    if mesh is None:
        jitted = jax.jit(step, donate_argnums=(0,))
    else:
        shardings = state_shardings(cfg, mesh, rules, model)
        batch_spec = NamedSharding(mesh, P(("dp", "fsdp")))
        jitted = jax.jit(
            step,
            donate_argnums=(0,),
            in_shardings=(shardings, batch_spec),
            out_shardings=(shardings, None),
        )
    if watch is not None:
        jitted = watch.wrap("train_step", jitted, key_fn=_batch_key_fn)
    return _instrument_step(jitted)


def synthetic_batch(cfg: llama.LlamaConfig, batch_size: int, seq_len: int,
                    seed: int = 0) -> Dict[str, jax.Array]:
    rng = jax.random.key(seed)
    tokens = jax.random.randint(rng, (batch_size, seq_len), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    return {"tokens": tokens}
