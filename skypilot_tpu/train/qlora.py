"""QLoRA finetuning: LoRA adapters over a FROZEN int8 base.

This is how an 8B-class model finetunes on one 16 GB chip: the base
weights live in HBM as int8 (+ per-output-channel scales, ~8 GB for
8B), each matmul dequantizes its weight tile on the fly into the MXU's
bf16 input (XLA fuses convert+scale into the matmul read — the weights
never exist as a full bf16 tree), and LoRA adapters ride as separate
low-rank matmuls beside the frozen projections:

    y = x @ dequant(Wq) + (alpha/r) * (x @ A) @ B

Only A/B receive gradients; backprop flows through the dequantized
matmuls (linear in x — unlike the w8a8 serving path, whose activation
rounding would zero every upstream gradient).

Differences from train.lora (fp base): lora merges adapters into the
base tree per step, which requires the fp tree to exist; here the base
is int8-only, so deltas stay factored.

Reference parity: llm/llama-3_1-finetuning (torchtune LoRA recipe on
Llama-3.1, the reference's flagship finetune, external) +
examples/tpu/v6e/README.md §Train — the tok/s/chip benchmark class.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from skypilot_tpu.models import llama
from skypilot_tpu.train import trainer
from skypilot_tpu.train.lora import LoRAConfig

Params = Dict[str, Any]


def dequant_weight(qw: Dict[str, jax.Array], n_contract: int,
                   dtype) -> jax.Array:
    """int8 {w, s} -> dtype weight. ``s`` spans the output dims (w's
    trailing ndim - n_contract); broadcast across the contracted head.
    Linear in w and constant under grad — safe inside a differentiable
    forward."""
    s = qw["s"][(None,) * n_contract + (...,)]
    return (qw["w"].astype(jnp.float32) * s).astype(dtype)


def _lora_in(h, ab, scale):
    """Delta for an embed->heads/kv projection. h: [B,S,D]."""
    u = jnp.einsum("bsd,dr->bsr", h, ab["a"].astype(h.dtype))
    return scale * jnp.einsum("bsr,r...->bs...", u,
                              ab["b"].astype(h.dtype))


def _lora_out(o, ab, scale):
    """Delta for the heads->embed (wo) projection. o: [B,S,H,K]."""
    u = jnp.einsum("bshk,hkr->bsr", o, ab["a"].astype(o.dtype))
    return scale * jnp.einsum("bsr,rd->bsd", u, ab["b"].astype(o.dtype))


def _qdecoder_layer(cfg: llama.LlamaConfig, lc: LoRAConfig, x, qlayer,
                    norms, adapters, cos, sin, constrain, mesh, rules,
                    segment_ids):
    """One pre-norm decoder block off int8 weights + factored LoRA."""
    dt = cfg.dtype

    def proj(name, h, eq, n_contract):
        w = dequant_weight(qlayer[name], n_contract, dt)
        return jnp.einsum(eq, h, w)

    h = llama.rms_norm(x, norms["ln1"], cfg.norm_eps)
    q = proj("wq", h, "bsd,dhk->bshk", 1)
    k = proj("wk", h, "bsd,dhk->bshk", 1)
    v = proj("wv", h, "bsd,dhk->bshk", 1)
    sc = lc.scale
    if "wq" in adapters:
        q = q + _lora_in(h, adapters["wq"], sc)
    if "wk" in adapters:
        k = k + _lora_in(h, adapters["wk"], sc)
    if "wv" in adapters:
        v = v + _lora_in(h, adapters["wv"], sc)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    q = constrain(q, ("batch", "seq", "heads", "head_dim"))
    k = constrain(k, ("batch", "seq", "kv_heads", "head_dim"))
    o = llama._attention(q, k, v, cfg, mesh, rules, segment_ids)
    y = proj("wo", o, "bshk,hkd->bsd", 2)
    if "wo" in adapters:
        y = y + _lora_out(o, adapters["wo"], sc)
    x = x + constrain(y, ("batch", "seq", "embed"))

    h = llama.rms_norm(x, norms["ln2"], cfg.norm_eps)
    g = proj("w_gate", h, "bsd,df->bsf", 1)
    u = proj("w_up", h, "bsd,df->bsf", 1)
    m = jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u,
                   dequant_weight(qlayer["w_down"], 1, dt))
    return x + constrain(m, ("batch", "seq", "embed"))


def forward_hidden(qweights: Params, fp_params: Params, adapters: Params,
                   tokens: jax.Array, cfg: llama.LlamaConfig,
                   lc: LoRAConfig, constrain=None, mesh=None, rules=None,
                   positions=None, segment_ids=None) -> jax.Array:
    """Token ids [B, S] -> final-norm hidden states, int8 base.

    ``fp_params`` is the slim tree (embed + norms, kvcache.slim_params
    layout); ``qweights["blocks"]`` the stacked int8 block weights.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    B, S = tokens.shape
    tokens = constrain(tokens, ("batch", "seq"))
    table = constrain(fp_params["embed"].astype(cfg.dtype),
                      ("vocab", "embed"))
    x = table[tokens]
    x = constrain(x, ("batch", "seq", "embed"))
    if positions is None:
        positions = jnp.arange(S)
    from skypilot_tpu.parallel import ring_attention as ra
    (x, positions, segment_ids, layer_rules, use_zigzag,
     n_sp) = ra.apply_zigzag_layout(x, positions, segment_ids, mesh,
                                    rules)
    cos, sin = llama.rope_frequencies(cfg, positions)
    norms = {"ln1": fp_params["blocks"]["ln1"],
             "ln2": fp_params["blocks"]["ln2"]}

    def body(carry, xs):
        qlayer, norm, ab = xs
        y = _qdecoder_layer(cfg, lc, carry, qlayer, norm, ab, cos, sin,
                            constrain, mesh, layer_rules, segment_ids)
        return y, None

    if cfg.remat:
        body = jax.checkpoint(body, policy=llama.remat_policy(cfg))

    x, _ = lax.scan(body, x, (qweights["blocks"], norms, adapters))
    if use_zigzag:
        x = ra.zigzag_unpermute(x, n_sp)
    return llama.rms_norm(x, fp_params["final_norm"], cfg.norm_eps)


def loss_fn(qweights: Params, fp_params: Params, adapters: Params,
            batch: Dict[str, jax.Array], cfg: llama.LlamaConfig,
            lc: LoRAConfig, constrain=None, mesh=None, rules=None):
    """Next-token cross-entropy off the int8 base + adapters."""
    if constrain is None:
        constrain = lambda x, axes: x
    tokens = batch["tokens"]
    h = forward_hidden(qweights, fp_params, adapters, tokens, cfg, lc,
                       constrain, mesh, rules,
                       positions=batch.get("positions"),
                       segment_ids=batch.get("segment_ids"))
    head = dequant_weight(qweights["head"], 1, cfg.dtype)
    loss, acc, denom = llama.xent_metrics(
        fp_params, h, tokens, llama.packed_loss_mask(batch), cfg,
        constrain, head=head)
    return loss, {"loss": loss, "accuracy": acc, "tokens": denom}


def make_qlora_train_step(cfg: llama.LlamaConfig, lc: LoRAConfig,
                          tc: trainer.TrainConfig,
                          mesh=None) -> Callable:
    """step(state, qweights, fp_params, batch) -> (state, metrics).

    The int8 base + slim fp tree are frozen inputs (no gradient, no
    donation); optimizer state exists only for the adapters.
    Single-chip oriented: the 8B bench's whole point is one 16 GB chip
    (multi-chip finetunes shard the fp base via train.lora instead).
    """
    opt = trainer.make_optimizer(tc)

    def step(state, qweights, fp_params, batch):
        def lossf(adapters):
            return loss_fn(qweights, fp_params, adapters, batch, cfg,
                           lc, mesh=mesh)

        (loss, metrics), grads = jax.value_and_grad(
            lossf, has_aux=True)(state["params"])
        updates, new_opt = opt.update(grads, state["opt_state"],
                                      state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        metrics = dict(metrics, grad_norm=optax.global_norm(grads))
        return {"params": new_params, "opt_state": new_opt,
                "step": state["step"] + 1}, metrics

    return jax.jit(step, donate_argnums=(0,))


def create_qlora_state(cfg: llama.LlamaConfig, lc: LoRAConfig,
                       tc: trainer.TrainConfig, seed: int = 0):
    """The adapter train state IS lora's (params/opt_state/step over
    A/B) — one definition, so `--qlora --resume` restore targets can
    never diverge from fresh init (see lora._state_init_fn)."""
    from skypilot_tpu.train import lora as lora_lib
    return lora_lib.create_lora_state(cfg, lc, tc, mesh=None, seed=seed)
