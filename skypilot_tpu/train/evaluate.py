"""Evaluation: loss / perplexity / accuracy over a batch stream.

``python -m skypilot_tpu.train.evaluate --ckpt-dir ... [--packed]``
restores the latest checkpoint and reports aggregate metrics — the
resume-side counterpart of train.run (reference analogue: eval steps
inside external workload recipes).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Callable, Dict, Iterable, Optional


def evaluate(step_less_loss_fn: Callable, params,
             batches: Iterable[Dict]) -> Dict[str, float]:
    """Aggregate token-weighted loss/accuracy over ``batches``.

    ``step_less_loss_fn(params, batch) -> (loss, metrics)`` is the
    model's loss_fn, already jitted/sharded by the caller.
    """
    total_loss = 0.0
    total_tokens = 0.0
    total_correct = 0.0
    n_batches = 0
    for batch in batches:
        loss, metrics = step_less_loss_fn(params, batch)
        tokens = float(metrics.get("tokens", 1.0))
        total_loss += float(loss) * tokens
        total_correct += float(metrics.get("accuracy", 0.0)) * tokens
        total_tokens += tokens
        n_batches += 1
    if total_tokens == 0:
        return {"loss": float("nan"), "perplexity": float("nan"),
                "accuracy": float("nan"), "tokens": 0, "batches": 0}
    loss = total_loss / total_tokens
    return {
        "loss": round(loss, 6),
        "perplexity": round(math.exp(min(loss, 30.0)), 4),
        "accuracy": round(total_correct / total_tokens, 6),
        "tokens": int(total_tokens),
        "batches": n_batches,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama", choices=("llama", "moe"))
    ap.add_argument("--config", default=None)
    ap.add_argument("--ckpt-dir", default=None,
                    help="restore params from the latest checkpoint")
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--packed", action="store_true")
    ap.add_argument("--tp", type=int, default=1)
    args = ap.parse_args()

    import jax

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer

    if args.model == "llama":
        from skypilot_tpu.models import llama as model
        default_cfg = "llama3-400m"
    else:
        from skypilot_tpu.models import moe as model
        default_cfg = "moe-small"
    cfg = model.CONFIGS[args.config or default_cfg]
    args.seq = min(args.seq, cfg.max_seq_len)

    mesh = mesh_lib.make_mesh(
        mesh_lib.default_shape_for(jax.device_count(), tp=args.tp))
    tc = trainer.TrainConfig()

    if args.ckpt_dir:
        from skypilot_tpu.train import checkpoints
        mgr = checkpoints.CheckpointManager(args.ckpt_dir)
        target = trainer.create_abstract_state(cfg, tc, mesh, model=model)
        state = mgr.restore(target)
        params = state["params"]
        print(f"restored step {mgr.latest_step()}", file=sys.stderr)
    else:
        params = trainer.create_train_state(cfg, tc, mesh,
                                            model=model)["params"]

    from skypilot_tpu.parallel import sharding as sh
    constrain = sh.make_constrain(mesh, sh.ACT_RULES)
    loss_fn = jax.jit(
        lambda p, b: model.loss_fn(p, b, cfg, constrain, mesh))

    if args.packed:
        import jax.numpy as jnp

        from skypilot_tpu.data import input_pipeline as ip
        docs = ip.synthetic_doc_stream(
            args.batches * args.batch * 4, cfg.vocab_size,
            mean_len=args.seq // 3, seed=1)
        stream = ip.packed_batches(docs, args.batch, args.seq)
        batches = ({k: jnp.asarray(v) for k, v in b.items()}
                   for _, b in zip(range(args.batches), stream))
    else:
        batches = (trainer.synthetic_batch(cfg, args.batch, args.seq,
                                           seed=i)
                   for i in range(args.batches))

    out = evaluate(loss_fn, params, batches)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
