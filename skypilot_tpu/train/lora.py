"""LoRA finetuning: low-rank adapters over frozen base weights.

Functional design — the model code is untouched: adapters are merged
into a *derived* parameter tree inside the jitted step
(``w + (alpha/r) * A @ B``), so the forward runs exactly the base
model's HLO while gradients flow only through A/B. Optimizer state
exists only for the adapters (the whole point: an 8B base finetunes
with megabytes of trainable state).

Merging costs O(L * d * r * d_out) per step — noise next to the
forward for r <= 64 — and XLA fuses it with the consuming matmuls.

Reference parity: llm/llama-3_1-finetuning/lora.yaml (torchtune
``lora_finetune_distributed`` — the reference's flagship finetune
recipe, external). In-tree TPU-native equivalent.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import sharding as sh
from skypilot_tpu.train import trainer

Params = Dict[str, Any]

# Single source of truth for adapter geometry: per target, the base
# weight's (input logical axes, output logical axes) after the leading
# layer axis. Everything else (shapes, logical axes, merge einsum)
# derives from this table.
_TARGETS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    "wq": (("embed",), ("heads", "head_dim")),
    "wk": (("embed",), ("kv_heads", "head_dim")),
    "wv": (("embed",), ("kv_heads", "head_dim")),
    "wo": (("heads", "head_dim"), ("embed",)),
}


def _dim(cfg: llama.LlamaConfig, axis: str) -> int:
    return {"embed": cfg.d_model, "heads": cfg.n_heads,
            "kv_heads": cfg.n_kv_heads, "head_dim": cfg.head_dim}[axis]


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 16
    alpha: float = 32.0
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        if self.rank <= 0:
            raise ValueError(f"LoRA rank must be positive, got {self.rank}")
        for t in self.targets:
            if t not in _TARGETS:
                raise ValueError(f"unknown LoRA target {t!r}; "
                                 f"supported: {sorted(_TARGETS)}")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora_params(rng: jax.Array, cfg: llama.LlamaConfig,
                     lc: LoRAConfig) -> Params:
    """A ~ N(0, 1/d_in), B = 0: adapted model == base model at init."""
    L = cfg.n_layers
    keys = jax.random.split(rng, len(lc.targets))
    adapters: Params = {}
    for key, t in zip(keys, lc.targets):
        in_axes, out_axes = _TARGETS[t]
        in_dims = tuple(_dim(cfg, a) for a in in_axes)
        out_dims = tuple(_dim(cfg, a) for a in out_axes)
        d_in = 1
        for x in in_dims:
            d_in *= x
        adapters[t] = {
            "a": (jax.random.normal(key, (L, *in_dims, lc.rank),
                                    jnp.float32) * (d_in ** -0.5)),
            "b": jnp.zeros((L, lc.rank, *out_dims), jnp.float32),
        }
    return adapters


def lora_logical_axes(cfg: llama.LlamaConfig, lc: LoRAConfig) -> Params:
    out: Params = {}
    for t in lc.targets:
        in_axes, out_axes = _TARGETS[t]
        out[t] = {"a": ("layer", *in_axes, None),
                  "b": ("layer", None, *out_axes)}
    return out


def merge(base: Params, adapters: Params, lc: LoRAConfig) -> Params:
    """base params + scaled A@B deltas on the targeted projections."""
    blocks = dict(base["blocks"])
    for t, ab in adapters.items():
        a, b = ab["a"], ab["b"]
        in_axes, _ = _TARGETS[t]
        if len(in_axes) == 1:
            # a: [L, d, r]; b: [L, r, *out] -> delta [L, d, *out]
            delta = jnp.einsum("ldr,lrhk->ldhk", a, b)
        else:
            # a: [L, h, hd, r]; b: [L, r, d] -> delta [L, h, hd, d]
            delta = jnp.einsum("lhkr,lrd->lhkd", a, b)
        blocks[t] = blocks[t] + (lc.scale * delta).astype(blocks[t].dtype)
    return {**base, "blocks": blocks}


def lora_state_shardings(cfg: llama.LlamaConfig, lc: LoRAConfig,
                         tc: trainer.TrainConfig, mesh: Mesh):
    opt = trainer.make_optimizer(tc)
    a_shapes = jax.eval_shape(
        lambda: init_lora_params(jax.random.key(0), cfg, lc))
    a_sh = sh.logical_to_sharding(lora_logical_axes(cfg, lc), mesh,
                                  sh.DEFAULT_RULES, shapes=a_shapes)
    opt_shapes = jax.eval_shape(opt.init, a_shapes)
    opt_sh = trainer.opt_state_shardings(a_sh, a_shapes, opt_shapes, mesh)
    return {"params": a_sh, "opt_state": opt_sh,
            "step": NamedSharding(mesh, P())}


def _state_init_fn(cfg: llama.LlamaConfig, lc: LoRAConfig, opt):
    """The single definition of the LoRA train-state tree (shared by
    create/abstract so restore targets can never diverge)."""

    def init_fn(rng):
        adapters = init_lora_params(rng, cfg, lc)
        return {"params": adapters, "opt_state": opt.init(adapters),
                "step": jnp.zeros((), jnp.int32)}

    return init_fn


def abstract_lora_state(cfg: llama.LlamaConfig, lc: LoRAConfig,
                        tc: trainer.TrainConfig, mesh: Optional[Mesh]):
    """ShapeDtypeStruct pytree (with shardings) — the checkpoint-restore
    target, nothing materialized."""
    opt = trainer.make_optimizer(tc)
    shapes = jax.eval_shape(_state_init_fn(cfg, lc, opt),
                            jax.random.key(0))
    if mesh is None:
        return shapes
    shardings = lora_state_shardings(cfg, lc, tc, mesh)
    return jax.tree.map(
        lambda s, shd: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                            sharding=shd),
        shapes, shardings)


def create_lora_state(cfg: llama.LlamaConfig, lc: LoRAConfig,
                      tc: trainer.TrainConfig, mesh: Optional[Mesh],
                      seed: int = 0):
    opt = trainer.make_optimizer(tc)
    init_fn = _state_init_fn(cfg, lc, opt)
    rng = jax.random.key(seed)
    if mesh is None:
        return jax.jit(init_fn)(rng)
    shardings = lora_state_shardings(cfg, lc, tc, mesh)
    return jax.jit(init_fn, out_shardings=shardings)(rng)


def base_param_shardings(cfg: llama.LlamaConfig, mesh: Mesh, model=llama):
    """Shardings for the frozen base parameter tree."""
    return sh.logical_to_sharding(
        model.param_logical_axes(cfg), mesh, sh.DEFAULT_RULES,
        shapes=jax.eval_shape(
            lambda: model.init_params(jax.random.key(0), cfg)))


def make_lora_train_step(cfg: llama.LlamaConfig, lc: LoRAConfig,
                         tc: trainer.TrainConfig,
                         mesh: Optional[Mesh],
                         model=llama, base_sh=None,
                         act_rules: sh.Rules = sh.ACT_RULES) -> Callable:
    """step(lora_state, base_params, batch) -> (lora_state, metrics).

    base_params are a frozen input (no gradient, no donation): the same
    base tree serves every step. Pass ``base_sh`` if already computed.
    """
    opt = trainer.make_optimizer(tc)
    constrain = sh.make_constrain(mesh, act_rules)

    def step(state, base_params, batch):
        def lossf(adapters):
            params = merge(base_params, adapters, lc)
            return model.loss_fn(params, batch, cfg, constrain, mesh,
                                 act_rules)

        (loss, metrics), grads = jax.value_and_grad(
            lossf, has_aux=True)(state["params"])
        updates, new_opt = opt.update(grads, state["opt_state"],
                                      state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        metrics = dict(metrics, grad_norm=optax.global_norm(grads))
        return {"params": new_params, "opt_state": new_opt,
                "step": state["step"] + 1}, metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))
    shardings = lora_state_shardings(cfg, lc, tc, mesh)
    if base_sh is None:
        base_sh = base_param_shardings(cfg, mesh, model)
    batch_spec = NamedSharding(mesh, P(("dp", "fsdp")))
    return jax.jit(step, donate_argnums=(0,),
                   in_shardings=(shardings, base_sh, batch_spec),
                   out_shardings=(shardings, None))


def num_trainable_params(cfg: llama.LlamaConfig,
                         lc: LoRAConfig) -> int:
    shapes = jax.eval_shape(
        lambda: init_lora_params(jax.random.key(0), cfg, lc))
    return sum(math.prod(s.shape) for s in jax.tree.leaves(shapes))
