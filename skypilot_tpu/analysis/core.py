"""Checker framework: registry, file contexts, and the runner.

Checkers declare a ``scope``:

  * ``"file"``     — findings depend only on one file's contents.
    Results are cached per (file digest, checker version) in
    ``cache.py``; a warm ``--changed`` run re-checks only edits.
  * ``"project"``  — findings depend on cross-file state (the jit
    reachability graph, the docs metric catalog). These re-run every
    time over the parsed tree; they are cheap once parsing is done,
    and caching them per-file would be wrong (editing file A can
    change file B's findings).

The runner owns the walk (``skypilot_tpu/`` only — fixtures and tests
are out of scope by construction), the cache, and the baseline
comparison. ``run()`` is the single entry point the CLI and the tier-1
gate share.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence

from skypilot_tpu.analysis import baseline as baseline_mod
from skypilot_tpu.analysis import cache as cache_mod
from skypilot_tpu.analysis.findings import Finding


def repo_root() -> str:
    """The repository root (the directory holding ``skypilot_tpu/``)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


class FileContext:
    """One source file: path, text, and a lazily parsed AST shared by
    every checker (parsing once per file is what keeps a full-tree run
    fast)."""

    def __init__(self, path: str, rel: str,
                 source: Optional[str] = None):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self._source = source
        self._tree: Optional[ast.Module] = None
        self._lines: Optional[List[str]] = None
        self._nodes: Optional[List[ast.AST]] = None
        self._functions = None
        self._aliases: Optional[Dict[str, str]] = None

    @property
    def source(self) -> str:
        if self._source is None:
            with open(self.path, encoding="utf-8") as f:
                self._source = f.read()
        return self._source

    @property
    def lines(self) -> List[str]:
        if self._lines is None:
            self._lines = self.source.splitlines()
        return self._lines

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.source, filename=self.path)
        return self._tree

    def line(self, lineno: int) -> str:
        """1-based source line ('' past EOF)."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # Shared single-pass indexes: project-scope checkers iterate the
    # whole tree every run; walking each file once and letting every
    # checker reuse the result is what keeps `--changed` under its 2s
    # budget.

    @property
    def nodes(self) -> List[ast.AST]:
        """Flat ``ast.walk`` of the module, computed once."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def functions(self):
        """[(qualname, class_name, funcdef)] for every def/async def,
        nested included; qualname dot-joins classes and enclosing
        functions."""
        if self._functions is None:
            self._functions = walk_functions(self.tree)
        return self._functions

    @property
    def import_aliases(self) -> Dict[str, str]:
        """local name -> dotted target for every import statement in
        the file (lazy in-function imports included)."""
        if self._aliases is None:
            out: Dict[str, str] = {}
            for node in self.nodes:
                if isinstance(node, ast.Import):
                    for a in node.names:
                        out[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        out[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
            self._aliases = out
        return self._aliases


def walk_functions(tree: ast.Module):
    """The canonical function traversal (``FileContext.functions``
    caches its result; ``checkers._util.walk_functions`` delegates
    here): [(qualname, class_name, funcdef)] for every def/async def,
    nested included."""
    out = []

    def visit(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, cls, child))
                visit(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", child.name)
            else:
                visit(child, prefix, cls)

    visit(tree, "", None)
    return out


class Checker:
    """Base class. Subclasses set the class attrs and implement the
    method matching their ``scope``."""

    name: str = ""
    description: str = ""
    scope: str = "file"            # "file" | "project"
    version: int = 1               # bump to invalidate cached results

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return []

    def check_project(self, ctxs: Sequence[FileContext],
                      root: str) -> List[Finding]:
        return []

    def extra_inputs(self, root: str) -> List[str]:
        """Paths outside the scanned tree this checker reads (they
        join the project digest, so editing them invalidates cached
        project results)."""
        return []


_REGISTRY: Dict[str, Checker] = {}


def register(cls):
    """Class decorator adding one instance to the global registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"{cls.__name__} has no name")
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate checker {inst.name!r}")
    _REGISTRY[inst.name] = inst
    return cls


def _ensure_loaded() -> None:
    # Import for the registration side effect (idempotent).
    from skypilot_tpu.analysis import checkers  # noqa: F401


def all_checkers() -> List[Checker]:
    _ensure_loaded()
    return [_REGISTRY[n] for n in sorted(_REGISTRY)]


def get_checker(name: str) -> Checker:
    _ensure_loaded()
    return _REGISTRY[name]


def default_files(root: Optional[str] = None) -> List[str]:
    """Every ``skypilot_tpu/**/*.py`` under ``root``, repo-relative."""
    root = root or repo_root()
    pkg = os.path.join(root, "skypilot_tpu")
    out = []
    for dirpath, dirnames, names in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(names):
            if name.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, name), root)
                out.append(rel.replace(os.sep, "/"))
    return sorted(out)


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]            # every raw finding
    new: List[Finding]                 # beyond the baseline's budget
    stale: List[str]                   # baseline keys no finding matches
    unjustified: List[str]             # baseline keys without a reason
    files_scanned: int = 0
    files_from_cache: int = 0
    partial: bool = False              # subset run: stale not computed

    @property
    def clean(self) -> bool:
        """Gate verdict: no new findings, no rotted baseline."""
        if self.partial:
            return not (self.new or self.unjustified)
        return not (self.new or self.stale or self.unjustified)


def _project_digest(root: str, all_rel: List[str],
                    project_checkers: Sequence[Checker]) -> str:
    """Content digest of everything the project checkers can see."""
    import hashlib
    h = hashlib.sha256()
    for c in sorted(project_checkers, key=lambda c: c.name):
        h.update(f"{c.name}={c.version};".encode())
        for extra in c.extra_inputs(root):
            h.update(extra.encode())
            try:
                with open(extra, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"<missing>")
    for rel in all_rel:
        h.update(rel.encode())
        try:
            with open(os.path.join(root, rel), "rb") as f:
                h.update(hashlib.sha256(f.read()).digest())
        except OSError:
            h.update(b"<missing>")
    return h.hexdigest()


def _parse_error_finding(rel: str, exc: SyntaxError) -> Finding:
    return Finding(checker="framework", rule="parse-error", path=rel,
                   line=exc.lineno or 1,
                   message=f"file does not parse: {exc.msg}",
                   ident="parse-error",
                   hint="fix the syntax error; nothing else was checked")


def run(root: Optional[str] = None,
        files: Optional[Iterable[str]] = None,
        checkers: Optional[Sequence[str]] = None,
        use_cache: bool = True,
        baseline_path: Optional[str] = None,
        cache_path: Optional[str] = None) -> AnalysisResult:
    """Run the suite.

    ``files``: repo-relative paths restricting the scan (``--changed``).
    When given, the run is *partial*: project-scope checkers still scan
    the whole tree (their findings are filtered to the subset) and
    stale-baseline detection is skipped — a subset can't prove an
    entry dead.
    """
    root = root or repo_root()
    selected = all_checkers()
    if checkers is not None:
        want = set(checkers)
        unknown = want - {c.name for c in selected}
        if unknown:
            raise ValueError(f"unknown checker(s): {sorted(unknown)}")
        selected = [c for c in selected if c.name in want]
        # A checker-subset run must not touch the shared cache: its
        # digest covers only the selected checkers, so saving would
        # clobber the full run's warm state (and a later full run
        # would clobber it back — neither ever warm).
        use_cache = False

    all_rel = default_files(root)
    if files is not None:
        subset = {f.replace(os.sep, "/") for f in files}
        target_rel = [r for r in all_rel if r in subset]
        partial = True
    else:
        target_rel = all_rel
        partial = False

    ctxs: Dict[str, FileContext] = {}

    def ctx_for(rel: str) -> FileContext:
        if rel not in ctxs:
            ctxs[rel] = FileContext(os.path.join(root, rel), rel)
        return ctxs[rel]

    file_checkers = [c for c in selected if c.scope == "file"]
    project_checkers = [c for c in selected if c.scope == "project"]

    cache = (cache_mod.Cache.load(cache_path, selected)
             if use_cache else cache_mod.Cache.disabled())

    findings: List[Finding] = []
    files_from_cache = 0
    broken: set = set()                # files that failed to parse

    for rel in target_rel:
        path = os.path.join(root, rel)
        cached = cache.get(rel, path) if file_checkers else None
        if cached is not None:
            files_from_cache += 1
            findings.extend(cached)
            continue
        ctx = ctx_for(rel)
        try:
            ctx.tree
        except SyntaxError as e:
            findings.append(_parse_error_finding(rel, e))
            broken.add(rel)
            continue
        file_findings: List[Finding] = []
        for checker in file_checkers:
            file_findings.extend(checker.check_file(ctx))
        cache.put(rel, path, file_findings)
        findings.extend(file_findings)

    if project_checkers:
        # Cross-file results are cached under ONE digest over every
        # scanned file's content (+ the checkers' extra inputs) — the
        # only per-file key that is correct when editing file A can
        # change file B's findings. A warm `--changed` run with a
        # matching digest skips parsing the rest of the tree entirely.
        digest = _project_digest(root, all_rel, project_checkers)
        cached_project = cache.project_get(digest)
        keep = set(target_rel)
        if cached_project is not None:
            project_findings = cached_project
        else:
            # Project checkers always see the full tree (minus files
            # that don't parse) so reachability and catalogs stay
            # whole.
            project_ctxs = []
            for rel in all_rel:
                if rel in broken:
                    continue
                ctx = ctx_for(rel)
                try:
                    ctx.tree
                except SyntaxError as e:
                    if rel in target_rel:
                        findings.append(_parse_error_finding(rel, e))
                    broken.add(rel)
                    continue
                project_ctxs.append(ctx)
            project_findings = []
            for checker in project_checkers:
                project_findings.extend(
                    checker.check_project(project_ctxs, root))
            cache.project_put(digest, project_findings)
        for f in project_findings:
            if f.path in keep or not partial:
                findings.append(f)

    cache.save()

    base = baseline_mod.load(baseline_path
                             or baseline_mod.default_path(root))
    if checkers is not None:
        # A subset run must not read the other checkers' baseline
        # entries as stale — only they can prove themselves dead.
        prefixes = tuple(f"{c.name}::" for c in selected)
        base = {k: v for k, v in base.items()
                if k.startswith(prefixes)}
    new, stale, unjustified = baseline_mod.compare(findings, base)
    if partial:
        # Only STALE detection needs the full tree (a subset can't
        # prove an entry dead); justification checks are
        # subset-independent and stay on.
        stale = []
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.rule))
    new.sort(key=lambda f: (f.path, f.line, f.checker, f.rule))
    return AnalysisResult(findings=findings, new=new, stale=stale,
                          unjustified=unjustified,
                          files_scanned=len(target_rel),
                          files_from_cache=files_from_cache,
                          partial=partial)


def changed_files(root: Optional[str] = None) -> List[str]:
    """Repo-relative paths of files changed vs HEAD (staged, unstaged,
    and untracked) — the ``--changed`` file set. Falls back to the full
    tree when git is unavailable."""
    import subprocess
    root = root or repo_root()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
            check=True).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=10,
            check=True).stdout.splitlines()
    except (OSError, subprocess.SubprocessError):
        return default_files(root)
    return sorted({p.strip() for p in diff + untracked
                   if p.strip().endswith(".py")})
