"""Per-file result cache for file-scope checkers.

One JSON file under ``$SKYPILOT_TPU_HOME`` (so tests with a tmp home
get an isolated cache), keyed by repo-relative path:

    {"schema": 1, "checkers": "<versions digest>",
     "files": {rel: {"mtime": f, "size": n, "sha": hex,
                     "findings": [...]}}}

Validation is two-tier: matching (mtime, size) short-circuits without
reading the file; on mismatch the content hash decides (a ``touch``
must not bust the cache, an edit preserving mtime must). Any change to
the checker set or any checker's ``version`` invalidates everything —
the digest covers both. Corrupt or unreadable cache files degrade to
a cold run, never an error: the cache is an accelerator, not state.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence

from skypilot_tpu.analysis.findings import Finding

_SCHEMA = 1


def default_path() -> str:
    from skypilot_tpu.utils import paths
    return os.path.join(paths.home(), "lint_cache.json")


def _checkers_digest(checkers: Sequence) -> str:
    blob = ";".join(f"{c.name}={c.version}"
                    for c in sorted(checkers, key=lambda c: c.name))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _sha(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 16), b""):
            h.update(block)
    return h.hexdigest()


class Cache:
    def __init__(self, path: Optional[str], digest: str,
                 files: Dict[str, dict]):
        self._path = path
        self._digest = digest
        self._files = files
        self._dirty = False

    @staticmethod
    def load(path: Optional[str], checkers: Sequence) -> "Cache":
        path = path or default_path()
        digest = _checkers_digest(
            [c for c in checkers if c.scope == "file"])
        files: Dict[str, dict] = {}
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if (data.get("schema") == _SCHEMA
                    and data.get("checkers") == digest):
                files = data.get("files", {})
        except (OSError, ValueError):
            pass
        return Cache(path, digest, files)

    @staticmethod
    def disabled() -> "Cache":
        return Cache(None, "", {})

    def get(self, rel: str, path: str) -> Optional[List[Finding]]:
        """Cached findings for ``rel``, or None on miss/stale."""
        if self._path is None:
            return None
        ent = self._files.get(rel)
        if ent is None:
            return None
        try:
            st = os.stat(path)
        except OSError:
            return None
        if (st.st_mtime != ent["mtime"] or st.st_size != ent["size"]):
            # Slow path: the content decides. Refresh the stat key on a
            # content match so the next run short-circuits again.
            try:
                if _sha(path) != ent["sha"]:
                    return None
            except OSError:
                return None
            ent["mtime"], ent["size"] = st.st_mtime, st.st_size
            self._dirty = True
        try:
            return [Finding.from_dict(d) for d in ent["findings"]]
        except (KeyError, TypeError):
            return None

    # Project-scope result cache: one entry keyed by a digest over
    # EVERY scanned file's content (plus the checkers' other inputs,
    # e.g. the docs catalog) — any edit anywhere invalidates it, which
    # is exactly the cross-file semantics per-file caching can't give.

    def project_get(self, digest: str) -> Optional[List[Finding]]:
        if self._path is None:
            return None
        ent = self._files.get("//project")
        if not isinstance(ent, dict) or ent.get("digest") != digest:
            return None
        try:
            return [Finding.from_dict(d) for d in ent["findings"]]
        except (KeyError, TypeError):
            return None

    def project_put(self, digest: str,
                    findings: List[Finding]) -> None:
        if self._path is None:
            return
        self._files["//project"] = {
            "digest": digest,
            "findings": [f.to_dict() for f in findings]}
        self._dirty = True

    def put(self, rel: str, path: str,
            findings: List[Finding]) -> None:
        if self._path is None:
            return
        try:
            st = os.stat(path)
            sha = _sha(path)
        except OSError:
            return
        self._files[rel] = {
            "mtime": st.st_mtime, "size": st.st_size, "sha": sha,
            "findings": [f.to_dict() for f in findings]}
        self._dirty = True

    def save(self) -> None:
        if self._path is None or not self._dirty:
            return
        data = {"schema": _SCHEMA, "checkers": self._digest,
                "files": self._files}
        d = os.path.dirname(self._path) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=d, prefix=os.path.basename(self._path) + ".")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, self._path)
        except OSError:
            pass   # best-effort: an unwritable cache just stays cold
