"""host-sync: hidden device syncs in the serving/training hot loops.

The engine's throughput model assumes the step/burst/chunk loops only
*dispatch* device programs; every host fetch (``int(tok)``,
``np.asarray``, ``.item()``, ``.block_until_ready()``) is a full
dispatch-pipeline drain — the exact stall the async burst double-
buffering exists to avoid. A sync that belongs there (the completion
fetch IS the sync point) is baselined with a justification; a new one
fails the gate so it gets argued about in review instead of shipped.

Scope is the hot loops only — bench files and tests measure by
syncing, that is their job.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from skypilot_tpu.analysis.checkers import _util
from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding

# rel path -> function/method *leaf* names forming the hot loop.
# (Admission, chunking, decode and completion paths in the engine; the
# serving loop in the server; the per-step wrapper in the trainer.)
_SCOPES: Dict[str, Set[str]] = {
    "skypilot_tpu/infer/engine.py": {
        "step", "step_burst", "step_decode_once", "decode_burst",
        "dispatch_decode_burst", "complete_decode_burst",
        "prefill_chunk_step", "run_to_completion", "_admit", "admit",
        "_dispatch_wave", "_complete_wave", "_claim_chunked",
        "_store_prefix",
        # Crash recovery (PR 19): the dispatch seams grew thin
        # failure-boundary wrappers; the hot-loop bodies moved to
        # *_impl and stay in scope under their new names.
        "_admit_impl", "_prefill_chunk_impl", "_spec_decode_burst_impl",
        "_dispatch_decode_burst_impl", "_complete_decode_burst_impl",
        "recover",
        # Paged-KV block management (PR 7): all host-side numpy/list
        # bookkeeping — a device fetch here would drain the dispatch
        # pipeline once per claim/retire.
        "table_device", "_alloc_blocks", "_wave_claim",
        "_free_slot_blocks", "_need_blocks",
        # Speculative decode (PR 8): drafting is pure host work (the
        # n-gram index) and the verify burst's ONE deliberate fetch is
        # its completion sync — anything else here stalls the verify/
        # accept hot path once per burst.
        "spec_decode_burst", "_draft_for",
        # Span-bucketed attention + lazy growth (PR 9): bucket
        # selection and block headroom run per burst from HOST state
        # (request token lists, the numpy block table) — a device
        # fetch to pick a span would stall every dispatch.
        "_span_groups", "_span_for", "_span_arg", "_slot_rows",
        "_ensure_headroom",
        # Flight recorder (PR 10): the per-burst record is assembled
        # from host bookkeeping inside the step/burst/chunk loops — a
        # device fetch here would stall the very dispatch pipeline
        # the recorder observes.
        "_record_flight",
        # Multi-tenant QoS (PR 11): scheduling, re-queue and
        # preemption-by-eviction run before every admission pass from
        # HOST state (request token lists, the numpy block table,
        # refcounts) — eviction is a table edit, and a device fetch to
        # pick a victim would stall admission itself.
        "_requeue", "_ctx", "_resumable", "preempt_slot",
        "_preempt_for_waiting",
        # Paged-attention kernel + KV quotas (PR 12): the kernel
        # DISPATCH seam stays the same burst methods above (the flag
        # is engine-constant), but the per-tenant block accounting
        # runs at every claim/growth/free and the quota check per
        # admission pass — all host numpy-table bookkeeping; a device
        # fetch to count a tenant's blocks would stall admission.
        "_kv_quota", "_kv_quota_blocked", "_set_tenant_kv",
        "_sync_kv_charge",
        # Multi-LoRA adapter catalog (PR 13): acquire/release and the
        # per-slot adapter-id bookkeeping run at every claim/retire,
        # and the aid device-copy cache mirrors table_device — all
        # host dict/array work; a device fetch to pick a pool slot
        # would stall admission exactly like a block-count fetch.
        "_acquire_adapter", "_release_adapter", "_set_slot_adapter",
        "aid_device", "_lora_args", "_fail_request",
        # Draft-model pipeline (PR 14): drafter-mode resolution runs
        # per slot per verify round from pure request bookkeeping — a
        # device fetch to pick a drafter rung would stall every spec
        # dispatch.
        "_spec_mode",
        # Request forensics (PR 17): stall-episode bookkeeping rides
        # every claim attempt, and the retire record + P^2 tail
        # observe ride every retirement — all pure host dict/float
        # work; a device fetch inside _retire would stall the very
        # completion path whose latency the ledger decomposes.
        "_retire", "_mark_stall", "_end_stall", "_observe_tail",
    },
    # Model-backed drafter (PR 14): draft_batch/rollout run once per
    # verify round on the engine loop; everything except the draft
    # path's OWN completion fetch (the next verify window needs the
    # token values — baselined with justification) must stay pure
    # host bookkeeping, or the pipeline stalls the very verify
    # in-flight window it exists to overlap.
    "skypilot_tpu/infer/draft.py": {
        "draft_batch", "rollout", "_apply_pending", "_apply_rollout",
        "_sync_slot", "_ingest", "_dispatch_sync",
        "_dispatch_rollout", "release", "_acquire", "table_device",
        "_span_for", "_span_arg", "claimed", "stats",
    },
    # Adapter-catalog residency bookkeeping: acquire runs at every
    # claim (the hot-load inside it is a cold path by design — a
    # demand load IS a device dispatch — but the bookkeeping around
    # it must stay pure host work), release at every retire.
    "skypilot_tpu/infer/adapters.py": {
        "acquire", "release", "_grab_slot", "check", "names",
        "resident_count", "slot_names", "pins",
    },
    # QoS scheduler + admission control: the DRR reorder runs on the
    # engine loop before every admission pass and the admission check
    # runs per HTTP request — both are pure host bookkeeping over
    # request lists and token buckets.
    "skypilot_tpu/infer/qos.py": {
        "reorder", "request_cost", "weight", "admit", "take",
        "tenant_label",
    },
    # Flight recorder + compile watch internals: record() runs once
    # per burst on the engine loop and the watch wrapper rides EVERY
    # jit dispatch — both must stay pure host work.
    "skypilot_tpu/observability/flight.py": {
        "record", "wrap", "tail", "since", "drain_new", "summary",
    },
    # Device-truth attribution (PR 16): the calibrator's tick/estimate
    # and the roofline cost model ride every dispatch and every
    # _record_flight call — pure host arithmetic, except timed_call's
    # ONE deliberate block_until_ready: that bracket IS the
    # calibration measurement, fires on a sampled ~1/64 of hit-path
    # dispatches, and is baselined with justification.
    "skypilot_tpu/observability/attribution.py": {
        "timed_call", "tick", "update", "estimate", "record_cost",
        "set_bytes", "snapshot", "total",
    },
    # Request forensics (PR 17): the ledger builder replays flight
    # records on demand (CLI/debug endpoint — cold), but the P^2
    # quantile observe and the exemplar pin run inline at every
    # retirement on the engine loop — pure host arithmetic over
    # floats and dicts.
    "skypilot_tpu/observability/forensics.py": {
        "observe", "pin", "value", "_parabolic",
    },
    "skypilot_tpu/infer/server.py": {
        "_loop", "_step", "_drain_inbox", "_flush_streams",
        "_complete_burst", "_on_wave",
    },
    "skypilot_tpu/train/trainer.py": {
        "_instrument_step", "observe_loss",
        # Training goodput (PR 18): the compile-watch key function
        # rides EVERY train-step dispatch — shape metadata reads only,
        # never array values.
        "_batch_key_fn",
    },
    # Training goodput forensics (PR 18): the step ledger's
    # start/phase/end path and the cursor/bucket credits run once per
    # train step on the loop thread, and the anomaly watchdog's
    # observe folds in the losses the logging cadence ALREADY fetched
    # — all pure host float/dict arithmetic; a device fetch here would
    # stall the very step pipeline whose goodput it measures.
    "skypilot_tpu/observability/goodput.py": {
        "step_start", "phase", "step_end", "account", "snapshot",
        "_credit_locked", "_advance_locked", "observe",
    },
}

_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
               "numpy.array", "jax.device_get"}


@register
class HostSyncChecker(Checker):
    name = "host-sync"
    description = ("host syncs (.block_until_ready, np.asarray, "
                   ".item, int()/float() fetches) inside the engine "
                   "step/burst/chunk loops and the trainer step path")
    scope = "file"
    # v2: paged-KV block-management methods joined the engine scope.
    # v3: the speculative verify/accept path joined it.
    # v4: span-selection + lazy-growth methods joined it.
    # v5: the flight-recorder record path + compile-watch wrapper.
    # v6: QoS — the DRR scheduler/admission (infer/qos.py) and the
    #     preemption-by-eviction path joined the scope.
    # v7: paged-attention kernel rollout (PR 12) — the per-tenant
    #     KV-block quota/charge bookkeeping joined the engine scope;
    #     the bump rescans the edited dispatch seam cold.
    # v8: multi-LoRA adapter catalog (PR 13) — the engine's adapter
    #     acquire/release/aid bookkeeping and the catalog's residency
    #     path (infer/adapters.py) joined the scope; the bump rescans
    #     the edited claim/retire hot path cold.
    # v9: draft-model speculation + async pipeline (PR 14) — the
    #     engine's drafter-mode ladder and the DraftEngine's
    #     draft/rollout/lockstep path (infer/draft.py) joined the
    #     scope; the bump rescans the edited spec hot path cold.
    # v10: device-truth attribution (PR 16) — the calibrator tick/
    #     estimate path, the roofline cost model and the HBM ledger
    #     (observability/attribution.py) joined the scope; the one
    #     deliberate calibration bracket is baselined.
    # v11: request forensics (PR 17) — the engine's retire/stall/tail
    #     path and the P^2 observe + exemplar pin
    #     (observability/forensics.py) joined the scope; the bump
    #     rescans the edited retirement hot path cold.
    # v12: training goodput (PR 18) — the goodput step-ledger/anomaly
    #     path (observability/goodput.py) and the trainer's compile-
    #     watch key function joined the scope; the calibrator's
    #     sampled block_until_ready bracket stays baselined from v10.
    # v13: crash recovery (PR 19) — the dispatch-seam bodies moved to
    #     *_impl names and recover() joined the scope; the bump
    #     rescans the renamed hot paths cold.
    version = 13

    def check_file(self, ctx: FileContext) -> List[Finding]:
        scoped = _SCOPES.get(ctx.rel)
        if not scoped:
            return []
        out: List[Finding] = []
        for qual, _cls, func in ctx.functions:
            leaf = qual.split(".")[-1]
            if leaf not in scoped:
                continue
            out.extend(self._check_func(ctx, qual, func))
        return out

    def _check_func(self, ctx: FileContext, qual: str,
                    func: ast.AST) -> List[Finding]:
        out: List[Finding] = []

        def finding(node, pattern, message):
            out.append(Finding(
                checker=self.name, rule="host-sync", path=ctx.rel,
                line=node.lineno, col=node.col_offset,
                message=f"in hot loop `{qual}`: {message}",
                ident=f"{qual}:{pattern}",
                hint="a host fetch drains the dispatch pipeline; "
                     "move it to the completion path or keep the "
                     "value on device (baseline deliberate sync "
                     "points with a justification)"))

        for node in _util.body_walk(func):
            # A nested def is its own scope when listed; skip bodies of
            # nested helpers NOT in the scope set? They run inline —
            # keep them: the loop calls them synchronously.
            if not isinstance(node, ast.Call):
                continue
            name = _util.call_name(node) or ""
            leaf = name.split(".")[-1]
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            if attr == "block_until_ready":
                finding(node, "block_until_ready",
                        "`.block_until_ready()` stalls the loop "
                        "thread on the device")
            elif attr == "item" and not node.args:
                finding(node, "item", "`.item()` is a device fetch")
            elif name in _SYNC_CALLS and node.args \
                    and not _util.is_constant_expr(node.args[0]):
                finding(node, leaf,
                        f"`{name}(...)` fetches the array to the host")
            elif name in {"int", "float"} and len(node.args) == 1 \
                    and not _util.is_constant_expr(node.args[0]) \
                    and not self._host_cast(node.args[0]):
                finding(node, leaf,
                        f"`{leaf}(...)` on a device value is a "
                        f"blocking fetch")
        return out

    def _host_cast(self, arg: ast.AST) -> bool:
        """Casts that can't touch the device: len(), time values,
        environment reads, pure-host attributes."""
        for node in ast.walk(arg):
            if isinstance(node, ast.Call):
                name = _util.call_name(node) or ""
                if name == "len" or name.startswith(("os.", "time.")):
                    return True
        return False
