"""lock-discipline: guarded state and what happens while locked.

Two rules:

``guarded-mutation`` — a ``# guarded-by: <lock>`` comment on an
attribute's declaration line (class ``__init__`` or module level)
declares it shared between threads::

    self._inbox: list = []          # guarded-by: _inbox_lock
    _records: List[dict] = []       # guarded-by: _lock

Every *mutation* of that attribute (assignment, augmented assignment,
``del``, subscript store, or a mutating method call: append/pop/
update/clear/...) outside a ``with <lock>:`` block is a finding.
``__init__`` and module top-level are construction time — exempt.
Reads are not checked (too many benign racy reads of scalars; the
writes are where corruption comes from).

``blocking-under-lock`` — a blocking call (sleep, retry.call/pause,
file/socket I/O, subprocess, ``json.dumps`` of who-knows-how-big a
ring) lexically inside a ``with`` holding anything lock-named. This
is the bug class PR 2 fixed by hand when the event-log flush
serialized O(ring) JSON inside the recorder lock: every recording
thread stalled behind one writer. Locks that exist *to* serialize
I/O (flush locks) baseline their findings with that justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu.analysis.checkers import _util
from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_LOCK_NAME_RE = re.compile(r"lock", re.IGNORECASE)

_MUTATORS = {"append", "extend", "insert", "pop", "popleft",
             "appendleft", "extendleft", "remove", "clear", "update",
             "add", "discard", "setdefault", "sort", "reverse"}

_BLOCKING_CALLS = {
    "retry.call", "retry.pause", "json.dump", "json.dumps", "open",
    "tempfile.mkstemp", "mkstemp", "os.replace", "os.makedirs",
    "os.fsync", "subprocess.run", "subprocess.Popen",
    "subprocess.check_output", "subprocess.check_call", "urlopen",
    "requests.get", "requests.post",
}
_BLOCKING_ATTRS = {"sleep", "connect", "send", "sendall", "recv",
                   "accept", "wait"}


def _decl_targets(node: ast.AST) -> List[Tuple[Optional[str], str]]:
    """(owner, attr) pairs declared by an assignment statement:
    owner "self" for ``self.x = ...``, None for module-level ``x``."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    out = []
    for t in targets:
        if isinstance(t, ast.Name):
            out.append((None, t.id))
        elif isinstance(t, ast.Attribute) \
                and isinstance(t.value, ast.Name):
            out.append((t.value.id, t.attr))
    return out


def _lock_names_held(withs: List[ast.AST]) -> Set[str]:
    names = set()
    for w in withs:
        for item in getattr(w, "items", []):
            leaf = _util.last_attr(item.context_expr)
            if leaf:
                names.add(leaf)
    return names


@register
class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    description = ("mutations of `# guarded-by:` attributes outside "
                   "their lock; blocking calls while holding a lock")
    scope = "file"
    version = 1

    def check_file(self, ctx: FileContext) -> List[Finding]:
        guarded = self._guarded_decls(ctx)
        out: List[Finding] = []
        out.extend(self._check_mutations(ctx, guarded))
        out.extend(self._check_blocking(ctx))
        return out

    # -- guarded-by declarations -------------------------------------------

    def _guarded_decls(self, ctx: FileContext
                       ) -> Dict[Tuple[Optional[str], str], str]:
        """(class_or_None, attr) -> lock name."""
        # The annotation rides the declaration line, or a comment-only
        # line directly above it (multi-line declarations).
        lock_by_line: Dict[int, str] = {}
        for i, line in enumerate(ctx.lines, start=1):
            m = _GUARDED_RE.search(line)
            if m:
                lock_by_line[i] = m.group(1)
                if line.lstrip().startswith("#"):
                    lock_by_line[i + 1] = m.group(1)
        if not lock_by_line:
            return {}
        decls: Dict[Tuple[Optional[str], str], str] = {}

        def visit(node: ast.AST, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                    continue
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    lock = lock_by_line.get(child.lineno)
                    if lock:
                        for owner, attr in _decl_targets(child):
                            if owner in ("self", None):
                                decls[(cls, attr)] = lock
                visit(child, cls)

        visit(ctx.tree, None)
        return decls

    # -- rule: guarded-mutation --------------------------------------------

    def _check_mutations(self, ctx: FileContext,
                         guarded: Dict[Tuple[Optional[str], str], str]
                         ) -> List[Finding]:
        if not guarded:
            return []
        out: List[Finding] = []

        def mutated_refs(node: ast.AST) -> List[Tuple[Optional[str],
                                                      str, ast.AST]]:
            """(owner, attr, node) mutated by this statement/expr."""
            refs = []
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                # `a, self.x = ..., ...` unpacking counts per element.
                flat = []
                for t in targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        flat.extend(t.elts)
                    else:
                        flat.append(t)
                for t in flat:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Starred)):
                        base = base.value
                    if isinstance(base, ast.Name):
                        refs.append((None, base.id, t))
                    elif isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name):
                        refs.append((base.value.id, base.attr, t))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Name):
                        refs.append((None, base.id, t))
                    elif isinstance(base, ast.Attribute) \
                            and isinstance(base.value, ast.Name):
                        refs.append((base.value.id, base.attr, t))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                base = node.func.value
                if isinstance(base, ast.Name):
                    refs.append((None, base.id, node))
                elif isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name):
                    refs.append((base.value.id, base.attr, node))
            return refs

        def walk(node: ast.AST, cls: Optional[str], func: Optional[str],
                 withs: List[ast.AST]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name, None, [])
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # Construction is single-threaded by convention.
                    if child.name in ("__init__", "__new__") \
                            and func is None:
                        continue
                    walk(child, cls, child.name, [])
                    continue
                if func is not None:
                    held = _lock_names_held(withs)
                    for owner, attr, ref in mutated_refs(child):
                        key = ((cls, attr) if owner == "self"
                               else (None, attr) if owner is None
                               else None)
                        lock = guarded.get(key) if key else None
                        if lock and lock not in held:
                            disp = (f"self.{attr}"
                                    if owner == "self" else attr)
                            out.append(Finding(
                                checker=self.name,
                                rule="guarded-mutation",
                                path=ctx.rel, line=child.lineno,
                                col=child.col_offset,
                                message=(
                                    f"`{disp}` is declared guarded-by "
                                    f"`{lock}` but is mutated in "
                                    f"`{func}` without holding it"),
                                ident=f"{func}:{disp}",
                                hint=f"wrap the mutation in "
                                     f"`with {lock}:` (or move it "
                                     f"into a locked section)"))
                nwiths = (withs + [child]
                          if isinstance(child, (ast.With,
                                                ast.AsyncWith))
                          else withs)
                walk(child, cls, func, nwiths)

        walk(ctx.tree, None, None, [])
        return out

    # -- rule: blocking-under-lock -----------------------------------------

    def _check_blocking(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []

        def walk(node: ast.AST, func: Optional[str],
                 locks: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # A callback defined under a lock doesn't RUN
                    # under it.
                    walk(child, child.name, [])
                    continue
                if isinstance(child, ast.Lambda):
                    continue
                held = list(locks)
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        leaf = _util.last_attr(item.context_expr)
                        if leaf and _LOCK_NAME_RE.search(leaf):
                            held.append(leaf)
                if held and isinstance(child, ast.Call):
                    name = _util.call_name(child) or ""
                    attr = (child.func.attr
                            if isinstance(child.func, ast.Attribute)
                            else None)
                    blocking = (name in _BLOCKING_CALLS
                                or name.split(".")[-1] == "sleep"
                                or attr in _BLOCKING_ATTRS)
                    if blocking:
                        out.append(Finding(
                            checker=self.name,
                            rule="blocking-under-lock",
                            path=ctx.rel, line=child.lineno,
                            col=child.col_offset,
                            message=(
                                f"blocking call "
                                f"`{name or attr}(...)` while "
                                f"holding `{held[-1]}`"
                                + (f" in `{func}`" if func else "")),
                            ident=(f"{func or '<module>'}:"
                                   f"{name or attr}"),
                            hint="snapshot under the lock, do the "
                                 "slow work outside it (the PR 2 "
                                 "flush pattern); locks whose job is "
                                 "serializing I/O baseline this with "
                                 "that justification"))
                walk(child, func, held)

        walk(ctx.tree, None, [])
        return out
