"""typed-errors: no generic raises on request paths.

A ``raise Exception(...)`` / ``raise RuntimeError(...)`` in the API
server, RPC layer, load balancer, or model server surfaces to clients
as an opaque 500. The repo's contract (PR 5's ``prompt_too_long``
pattern) is typed errors: an ``exceptions.SkyTpuError`` subclass — or
a client-error class carrying a ``typed_error`` body the HTTP layer
can serialize — so callers can branch on the type and operators can
grep the event log for it.

Narrow builtins (``ValueError`` on malformed input, ``ConnectionError``
as LB failover control flow) are deliberate and stay allowed; only the
catch-nothing generics are flagged.
"""

from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis.checkers import _util
from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding

_SCOPE_PREFIXES = ("skypilot_tpu/server/",)
_SCOPE_FILES = {
    "skypilot_tpu/runtime/rpc.py",
    "skypilot_tpu/runtime/rpc_client.py",
    "skypilot_tpu/serve/load_balancer.py",
    "skypilot_tpu/serve/replica_managers.py",
    "skypilot_tpu/infer/server.py",
    # Crash-recovery/drain paths: an engine failure that reaches a
    # client must ride a typed error (EngineDispatchError,
    # KvPoolWedgedError), never a bare RuntimeError.
    "skypilot_tpu/infer/engine.py",
}
_GENERIC = {"Exception", "RuntimeError", "BaseException"}


@register
class TypedErrorsChecker(Checker):
    name = "typed-errors"
    description = ("bare Exception/RuntimeError raises on server/RPC/"
                   "LB request paths instead of typed error classes")
    scope = "file"
    version = 1

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not (ctx.rel.startswith(_SCOPE_PREFIXES)
                or ctx.rel in _SCOPE_FILES):
            return []
        out: List[Finding] = []
        func_of = {}
        for qual, _cls, node in ctx.functions:
            for sub in _util.body_walk(node):
                func_of[id(sub)] = qual
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                name = _util.dotted(exc.func)
            else:
                name = _util.dotted(exc)
            if name in _GENERIC:
                qual = func_of.get(id(node), "<module>")
                out.append(Finding(
                    checker=self.name, rule="generic-raise",
                    path=ctx.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(f"`raise {name}` on a request path "
                             f"(in `{qual}`) — clients see an opaque "
                             f"500"),
                    ident=f"{qual}:{name}",
                    hint="raise an exceptions.SkyTpuError subclass, "
                         "or a client-error class with a "
                         "`typed_error` body (see "
                         "PromptTooLongError)"))
        return out
