"""retrace-safety: trace-incompatible Python inside jit-reachable code.

The serving invariant since PR 5 is "one compiled program per bucket,
no retracing": every device program is traced once per static shape
and replayed forever. Code that runs *under trace* must therefore
never concretize a traced value (``int(x)``, ``.item()``), branch on
one in Python (``if (x > 0).any():``), pull it to the host
(``np.asarray``, ``.block_until_ready``), or build an array whose
shape depends on one — each of those either throws at trace time or,
worse, silently bakes a value in and recompiles per request.

Detection is reachability-based: roots are functions jitted in
``infer/`` and ``train/`` (``@jax.jit`` / ``functools.partial(jax.jit,
...)`` decorators, ``jax.jit(f)`` / ``shard_map(f)`` call sites,
jitted lambdas), and the call graph is followed through module aliases
into ``models/``, ``ops/`` and ``parallel/``. Python branches on
*static* values (config attrs, ``static_argnames``, ``is None``
checks) are trace-time constants and are not flagged; the branch rule
only fires on tests that contain array-API calls, which are traced by
construction.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis.checkers import _util
from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding

_ROOT_DIRS = ("skypilot_tpu/infer/", "skypilot_tpu/train/")

# jnp constructors whose first argument is a shape.
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "eye"}
_RANGE_CTORS = {"arange", "linspace"}
_ARRAY_MODULES = {"jnp", "lax", "jax"}
_TRACED_METHODS = {"any", "all", "item", "sum", "min", "max", "mean"}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` / ``shard_map`` (optionally dotted)."""
    name = _util.dotted(node)
    if name is None:
        return False
    leaf = name.split(".")[-1]
    return leaf in {"jit", "pjit", "shard_map"}


def _jit_wrapper_target(call: ast.Call) -> Optional[ast.AST]:
    """For ``jax.jit(f, ...)`` / ``shard_map(f, ...)``: the wrapped
    function expression (Name or Lambda); for ``functools.partial(
    jax.jit, ...)`` there is no target (it's used as a decorator)."""
    if _is_jit_expr(call.func) and call.args:
        return call.args[0]
    return None


def _has_jit_decorator(func: ast.AST) -> bool:
    for dec in getattr(func, "decorator_list", []):
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func):
                return True
            # functools.partial(jax.jit, ...)
            name = _util.dotted(dec.func) or ""
            if name.split(".")[-1] == "partial" and dec.args \
                    and _is_jit_expr(dec.args[0]):
                return True
    return False


def _module_key(rel: str) -> str:
    return rel[:-3].replace("/", ".")


class _FuncInfo:
    def __init__(self, ctx: FileContext, qual: str, node: ast.AST):
        self.ctx = ctx
        self.qual = qual
        self.node = node


@register
class RetraceSafetyChecker(Checker):
    name = "retrace-safety"
    description = ("Python that breaks tracing (concretization, "
                   "host transfer, traced branches, dynamic shapes) "
                   "reachable from jax.jit/shard_map entry points")
    scope = "project"
    # v2: span-parameterized attention programs (static span args)
    # joined the guarded surface — golden fixtures cover the
    # span-gather shape; the bump invalidates warm caches so the new
    # fixtures and the edited kvcache/engine hot path rescan cold.
    # v3: Pallas kernel bodies joined the guarded surface — the BFS
    # follows ``functools.partial(kernel_fn, ...)`` targets (the
    # pallas_call idiom wraps the kernel in a partial, which hid its
    # body from reachability), covering ops/paged_attention.py's
    # kernel + wrapper and the kvcache dispatch seam; the bump
    # rescans the edited hot path and the new fixtures cold.
    # v4: multi-LoRA adapter gathers (PR 13) — the per-slot (A, B)
    # delta helpers and the adapter-pool install program joined the
    # jit-reachable surface (kvcache lora plumbing + engine
    # _adapter_install); the bump rescans the edited programs and the
    # new adapter fixtures cold.
    # v5: draft-model speculation (PR 14) — infer/draft.py's jitted
    # rollout/ingest/sync programs are new roots in the infer/ root
    # dir and kvcache.sync_slots joined the reachable surface; the
    # bump rescans the edited spec programs and the new draft
    # fixtures cold.
    version = 5

    def check_project(self, ctxs: Sequence[FileContext],
                      root: str) -> List[Finding]:
        # Symbol table: dotted module -> {func name -> _FuncInfo}.
        by_module: Dict[str, Dict[str, _FuncInfo]] = {}
        aliases: Dict[str, Dict[str, str]] = {}
        for ctx in ctxs:
            mod = _module_key(ctx.rel)
            funcs: Dict[str, _FuncInfo] = {}
            for qual, _cls, node in ctx.functions:
                # Module-level name wins over same-named nested defs.
                leaf = qual.split(".")[-1]
                if leaf not in funcs or "." not in qual:
                    funcs[leaf] = _FuncInfo(ctx, qual, node)
            by_module[mod] = funcs
            aliases[ctx.rel] = ctx.import_aliases

        # Roots: jitted functions/lambdas in infer/ and train/.
        roots: List[Tuple[FileContext, str, ast.AST]] = []
        for ctx in ctxs:
            if not ctx.rel.startswith(_ROOT_DIRS):
                continue
            for qual, _cls, node in ctx.functions:
                if _has_jit_decorator(node):
                    roots.append((ctx, qual, node))
            for node in ctx.nodes:
                if not isinstance(node, ast.Call):
                    continue
                target = _jit_wrapper_target(node)
                if target is None:
                    continue
                if isinstance(target, ast.Lambda):
                    roots.append((ctx, f"<lambda@L{target.lineno}>",
                                  target))
                elif isinstance(target, ast.Name):
                    info = by_module.get(
                        _module_key(ctx.rel), {}).get(target.id)
                    if info is not None:
                        roots.append((info.ctx, info.qual, info.node))

        # BFS the call graph through module aliases.
        seen: Set[int] = set()
        queue: List[Tuple[FileContext, str, ast.AST]] = []
        for ctx, qual, node in roots:
            if id(node) not in seen:
                seen.add(id(node))
                queue.append((ctx, qual, node))
        reached: List[Tuple[FileContext, str, ast.AST]] = []
        while queue:
            ctx, qual, node = queue.pop()
            reached.append((ctx, qual, node))
            mod = _module_key(ctx.rel)
            file_aliases = aliases.get(ctx.rel, {})
            for sub in _util.body_walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                target = sub.func
                # ``functools.partial(f, ...)``: the partial runs
                # f's body wherever the partial is called (the
                # pallas_call kernel idiom) — follow f itself.
                name = _util.dotted(sub.func) or ""
                if name.split(".")[-1] == "partial" and sub.args \
                        and not _is_jit_expr(sub.args[0]):
                    target = sub.args[0]
                info = self._resolve(target, mod, file_aliases,
                                     by_module)
                if info is not None and id(info.node) not in seen:
                    seen.add(id(info.node))
                    queue.append((info.ctx, info.qual, info.node))

        findings: List[Finding] = []
        for ctx, qual, node in reached:
            findings.extend(self._check_traced(ctx, qual, node))
        return findings

    def _resolve(self, func: ast.AST, mod: str,
                 file_aliases: Dict[str, str],
                 by_module: Dict[str, Dict[str, _FuncInfo]]
                 ) -> Optional[_FuncInfo]:
        if isinstance(func, ast.Name):
            local = by_module.get(mod, {}).get(func.id)
            if local is not None:
                return local
            # `from skypilot_tpu.infer.kvcache import prefill`: the
            # alias maps to a module *member*; resolve via its parent.
            dotted = file_aliases.get(func.id)
            if dotted and "." in dotted:
                parent, leaf = dotted.rsplit(".", 1)
                return by_module.get(parent, {}).get(leaf)
            return None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            target_mod = file_aliases.get(func.value.id)
            if target_mod is None:
                return None
            funcs = by_module.get(target_mod)
            if funcs is None:
                return None
            return funcs.get(func.attr)
        return None

    # -- rules inside traced code -----------------------------------------

    def _check_traced(self, ctx: FileContext, qual: str,
                      func: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        # One-step local dataflow: name -> the expression last assigned
        # to it. `cap = math.ceil(...)` then `int(cap)` is a static
        # cast; without this every helper computing host math from
        # config would false-positive.
        assigns: Dict[str, ast.AST] = {}
        for node in _util.body_walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assigns[node.targets[0].id] = node.value

        def finding(node, rule, message, hint):
            out.append(Finding(
                checker=self.name, rule=rule, path=ctx.rel,
                line=node.lineno, col=node.col_offset,
                message=f"in traced `{qual}`: {message}",
                ident=f"{qual}:{rule}", hint=hint))

        for node in _util.body_walk(func):
            if isinstance(node, (ast.If, ast.While)):
                bad = self._traced_test(node.test)
                if bad is not None:
                    finding(node, "traced-branch",
                            f"Python `{type(node).__name__.lower()}` "
                            f"branches on a traced value "
                            f"(`{ast.unparse(bad)[:60]}`)",
                            "use jnp.where / lax.cond / lax.select — "
                            "a Python branch on a tracer throws at "
                            "trace time or bakes one path in")
            elif isinstance(node, ast.Call):
                name = _util.call_name(node) or ""
                leaf = name.split(".")[-1]
                attr = (node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else None)
                if leaf in {"int", "float", "bool"} and "." not in name \
                        and len(node.args) == 1 \
                        and not self._static_arg(node.args[0],
                                                 assigns):
                    finding(node, "concretize",
                            f"`{leaf}(...)` on a non-static value "
                            f"forces concretization",
                            "keep the value on device (jnp ops), or "
                            "hoist the cast out of the jitted code")
                elif attr in {"item", "tolist"} and not node.args:
                    finding(node, "concretize",
                            f"`.{attr}()` forces a device->host sync "
                            f"under trace",
                            "return the array and fetch it outside "
                            "the jitted function")
                elif attr == "block_until_ready":
                    finding(node, "host-transfer",
                            "`.block_until_ready()` under trace",
                            "sync outside jitted code (and on the "
                            "axon relay, prefer a host fetch)")
                elif name in {"np.asarray", "np.array",
                              "numpy.asarray", "numpy.array",
                              "jax.device_get"} \
                        and node.args \
                        and not self._static_arg(node.args[0],
                                                 assigns):
                    finding(node, "host-transfer",
                            f"`{name}` on a traced value pulls it to "
                            f"the host",
                            "use jnp.asarray / keep the computation "
                            "in jax.numpy under trace")
                elif self._dynamic_shape_ctor(node, name, leaf):
                    finding(node, "dynamic-shape",
                            f"`{name}` built with a shape computed "
                            f"from traced values",
                            "shapes must be static under jit: derive "
                            "them from .shape / config, or pad to a "
                            "bucket")
        return out

    def _traced_test(self, test: ast.AST) -> Optional[ast.AST]:
        """The subexpression proving the test is traced, if any."""
        for node in ast.walk(test):
            if not isinstance(node, ast.Call):
                continue
            name = _util.call_name(node) or ""
            head = name.split(".")[0]
            if head in _ARRAY_MODULES and "." in name:
                return node
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _TRACED_METHODS \
                    and not node.args and not node.keywords:
                return node
        return None

    def _static_arg(self, arg: ast.AST,
                    assigns: Optional[Dict[str, ast.AST]] = None,
                    depth: int = 0) -> bool:
        """Casts/transfers of static quantities are fine: constants,
        ``.shape`` chains, ``len()``, ``.ndim``/``.size``, host
        ``math.*`` results — following one-step local assignments
        (bounded, so a self-referential rebind can't recurse)."""
        if _util.is_constant_expr(arg):
            return True
        for node in ast.walk(arg):
            if isinstance(node, ast.Attribute) \
                    and node.attr in {"shape", "ndim", "size"}:
                return True
            if isinstance(node, ast.Call):
                name = _util.call_name(node) or ""
                if name == "len" or name.startswith("math."):
                    return True
        if isinstance(arg, ast.Name) and assigns and depth < 3:
            src_expr = assigns.get(arg.id)
            if src_expr is not None and src_expr is not arg:
                return self._static_arg(src_expr, assigns, depth + 1)
        return False

    def _dynamic_shape_ctor(self, node: ast.Call, name: str,
                            leaf: str) -> bool:
        head = name.split(".")[0]
        if head not in _ARRAY_MODULES:
            return False
        if leaf in _SHAPE_CTORS and node.args:
            shape_args = [node.args[0]]
        elif leaf in _RANGE_CTORS:
            shape_args = list(node.args)
        else:
            return False
        for arg in shape_args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    sub_name = _util.call_name(sub) or ""
                    if sub_name.split(".")[0] in _ARRAY_MODULES \
                            and "." in sub_name:
                        return True
        return False
