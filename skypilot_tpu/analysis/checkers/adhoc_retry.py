"""adhoc-retry: all retrying goes through ``utils/retry.py``.

Two rules, migrated from the pre-framework ``test_no_adhoc_retry``
lint (same AST logic; the fixed allowlists became baseline entries):

``sleep-in-except`` — ``*.sleep(...)`` lexically inside an ``except``
handler that sits inside a loop: the signature of a hand-rolled
retry/backoff loop. Each one reinvents backoff math and deadline
handling — exactly what made recovery behavior untestable before the
chaos layer. Route through ``retry.call`` / ``retry.pause``.

``except-pass`` — ``except Exception:`` (or bare ``except:``) whose
body is only ``pass``: silently eats the failures the chaos harness
injects. Catch the narrow type, or record a typed event.

``utils/retry.py`` itself is the allowed sleeper.
"""

from __future__ import annotations

import ast
from typing import List

from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding


@register
class AdhocRetryChecker(Checker):
    name = "adhoc-retry"
    description = ("hand-rolled retry loops (sleep inside "
                   "except-in-loop) and broad except-pass swallows")
    scope = "file"
    version = 1

    def check_file(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        skip_sleeps = ctx.rel == "skypilot_tpu/utils/retry.py"

        def handler_sleeps(handler: ast.ExceptHandler):
            for sub in ast.walk(handler):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "sleep"):
                    yield sub

        def walk(node: ast.AST, loop_depth: int):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.For, ast.While,
                                      ast.AsyncFor)):
                    walk(child, loop_depth + 1)
                    continue
                if isinstance(child, ast.ExceptHandler):
                    broad = child.type is None or (
                        isinstance(child.type, ast.Name)
                        and child.type.id in ("Exception",
                                              "BaseException"))
                    if broad and all(isinstance(s, ast.Pass)
                                     for s in child.body):
                        out.append(Finding(
                            checker=self.name, rule="except-pass",
                            path=ctx.rel, line=child.lineno,
                            col=child.col_offset,
                            message="broad except-pass swallows "
                                    "failures (including injected "
                                    "chaos faults)",
                            ident="except-pass",
                            hint="catch the narrow exception type, "
                                 "or record a typed event before "
                                 "continuing"))
                    if loop_depth > 0 and not skip_sleeps:
                        for call in handler_sleeps(child):
                            out.append(Finding(
                                checker=self.name,
                                rule="sleep-in-except",
                                path=ctx.rel, line=call.lineno,
                                col=call.col_offset,
                                message="sleep inside an except "
                                        "handler inside a loop — a "
                                        "hand-rolled retry",
                                ident="sleep-in-except",
                                hint="use skypilot_tpu.utils.retry "
                                     "(retry.call / retry.pause) so "
                                     "backoff, deadlines and "
                                     "telemetry stay uniform"))
                        continue   # handler fully scanned above
                # A nested def/lambda resets loop context: a sleep in
                # a callback defined within a loop is not this loop's
                # retry.
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    walk(child, 0)
                else:
                    walk(child, loop_depth)

        walk(ctx.tree, 0)
        return out
