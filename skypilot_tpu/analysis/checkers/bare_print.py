"""bare-print: daemon diagnostics must reach the structured log.

A ``print()`` in a daemon/server-side module is invisible to ``skytpu
trace`` and unparseable by anything downstream; the structured
replacement is ``tracing.add_event(..., echo=True)`` (one JSON line to
stderr AND the flight recorder). Migrated from the pre-framework
``test_no_bare_print`` lint; its fixed per-file allowlist became
baseline entries, and the scope grew to cover the serving layer
(``infer/``, ``serve/``) whose daemons predated the rule.

Bench files are exempt: their stdout IS the artifact.
"""

from __future__ import annotations

import ast
import os
from typing import List

from skypilot_tpu.analysis.core import Checker, FileContext, register
from skypilot_tpu.analysis.findings import Finding

_SCOPE_DIRS = ("skypilot_tpu/runtime/", "skypilot_tpu/server/",
               "skypilot_tpu/jobs/", "skypilot_tpu/infer/",
               "skypilot_tpu/serve/")


@register
class BarePrintChecker(Checker):
    name = "bare-print"
    description = ("bare print() in daemon/server modules instead of "
                   "tracing.add_event(..., echo=True)")
    scope = "file"
    version = 1

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not ctx.rel.startswith(_SCOPE_DIRS):
            return []
        if "bench" in os.path.basename(ctx.rel):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                out.append(Finding(
                    checker=self.name, rule="bare-print",
                    path=ctx.rel, line=node.lineno,
                    col=node.col_offset,
                    message="bare print() in a daemon/server module",
                    ident="print",
                    hint="use tracing.add_event(..., echo=True) so "
                         "the message reaches the structured event "
                         "log (and stderr)"))
        return out
