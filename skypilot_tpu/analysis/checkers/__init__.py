"""Checker modules — importing this package registers all of them.

Catalog (docs/analysis.md has the operator-facing version):

  retrace-safety   — trace-incompatible Python inside jit-reachable code
  host-sync        — hidden device syncs in the engine/trainer hot loops
  lock-discipline  — guarded-by mutations outside their lock; blocking
                     calls while holding a lock
  typed-errors     — generic raises on server/RPC/LB request paths
  bare-print       — daemon diagnostics bypassing the structured log
  adhoc-retry      — hand-rolled retry loops / broad except-pass
  metric-catalog   — metric naming + docs-catalog drift
"""

from skypilot_tpu.analysis.checkers import (adhoc_retry, bare_print,
                                            host_sync, locks,
                                            metric_catalog, retrace,
                                            typed_errors)

__all__ = ["adhoc_retry", "bare_print", "host_sync", "locks",
           "metric_catalog", "retrace", "typed_errors"]
