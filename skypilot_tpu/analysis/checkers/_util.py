"""Shared AST helpers for checkers."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


def last_attr(node: ast.AST) -> Optional[str]:
    """The final attribute/name segment of an expression (``c`` for
    ``a.b.c``, ``x`` for ``x``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_constant_expr(node: ast.AST) -> bool:
    """Literal-only subtree: constants, containers of constants, unary
    minus, and arithmetic on constants."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(is_constant_expr(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return is_constant_expr(node.operand)
    if isinstance(node, ast.BinOp):
        return (is_constant_expr(node.left)
                and is_constant_expr(node.right))
    return False


def body_walk(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body (the function node itself excluded).
    A Lambda's body is a single expression, not a statement list."""
    body = getattr(func, "body", [])
    if isinstance(body, ast.AST):
        yield from ast.walk(body)
        return
    for stmt in body:
        yield from ast.walk(stmt)


def func_params(func: ast.AST) -> List[str]:
    a = func.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names
