"""metric-catalog: metric naming and docs-catalog drift.

Every metric the framework registers must carry the ``skytpu_`` prefix
AND appear in the ``docs/observability.md`` catalog — drift between
the code's registry and the operator-facing catalog means the fleet
dashboard lies by omission. Migrated from the pre-framework
``test_metric_catalog`` lint.

Scope: literal-name declarations through the module-level sugar
(``metrics.counter/gauge/histogram(...)`` and the ``obs_metrics`` /
``metrics_lib`` aliases). Dynamic names and per-test registries are
out of scope by construction. Families the federation tier
synthesizes at render time (no declaration to scan) are held to the
same documentation contract. A scan that suddenly sees almost no
declarations is itself a finding — a refactor of the declaration
idiom must not let the catalog rot vacuously.
"""

from __future__ import annotations

import ast
import os
from typing import List, Sequence

from skypilot_tpu.analysis.core import (Checker, FileContext,
                                        register)
from skypilot_tpu.analysis.findings import Finding

_FACTORY_ATTRS = {"counter", "gauge", "histogram"}
_RECEIVERS = {"metrics", "obs_metrics", "metrics_lib"}
_SYNTHESIZED = {"skytpu_fleet_scrape_up", "skytpu_fleet_merge_errors"}
_MIN_DECLARATIONS = 30
_METRICS_MODULE = "skypilot_tpu/observability/metrics.py"
_DOC_REL = os.path.join("docs", "observability.md")


@register
class MetricCatalogChecker(Checker):
    name = "metric-catalog"
    description = ("metric names must be skytpu_-prefixed and "
                   "documented in docs/observability.md")
    scope = "project"
    version = 1

    def extra_inputs(self, root: str) -> List[str]:
        # Editing the catalog must invalidate cached project results.
        return [os.path.join(root, _DOC_REL)]

    def check_project(self, ctxs: Sequence[FileContext],
                      root: str) -> List[Finding]:
        doc_path = os.path.join(root, _DOC_REL)
        try:
            with open(doc_path, encoding="utf-8") as f:
                doc = f.read()
        except OSError:
            doc = ""
        declared = []
        for ctx in ctxs:
            if ctx.rel == _METRICS_MODULE:
                continue   # the factories themselves
            for node in ctx.nodes:
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _FACTORY_ATTRS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in _RECEIVERS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    declared.append((ctx.rel, node.lineno,
                                     node.args[0].value))
        out: List[Finding] = []
        if len(declared) < _MIN_DECLARATIONS:
            out.append(Finding(
                checker=self.name, rule="scan-degenerate",
                path=_METRICS_MODULE, line=1,
                message=(f"metric declaration scan found only "
                         f"{len(declared)} sites (expected >= "
                         f"{_MIN_DECLARATIONS}) — did the "
                         f"declaration idiom change?"),
                ident="scan-degenerate",
                hint="update metric_catalog.py's factory/receiver "
                     "sets to match the new idiom"))
        for rel, lineno, name in declared:
            if not name.startswith("skytpu_"):
                out.append(Finding(
                    checker=self.name, rule="bad-prefix", path=rel,
                    line=lineno,
                    message=f"metric `{name}` lacks the skytpu_ "
                            f"prefix",
                    ident=f"bad-prefix:{name}",
                    hint="rename to skytpu_<subsystem>_<what>_"
                         "<unit>"))
            if name not in doc:
                out.append(Finding(
                    checker=self.name, rule="undocumented", path=rel,
                    line=lineno,
                    message=f"metric `{name}` is missing from the "
                            f"docs/observability.md catalog",
                    ident=f"undocumented:{name}",
                    hint="add a catalog row (the fleet dashboard "
                         "lies by omission otherwise)"))
        for name in sorted(_SYNTHESIZED):
            if name not in doc:
                out.append(Finding(
                    checker=self.name, rule="undocumented",
                    path=_DOC_REL.replace(os.sep, "/"), line=1,
                    message=f"synthesized metric `{name}` is missing "
                            f"from the docs catalog",
                    ident=f"undocumented:{name}",
                    hint="the federation tier renders this family "
                         "at scrape time; document it like any "
                         "other"))
        return out
