"""Typed finding objects.

A finding's *identity* (``key``) deliberately excludes the line number:
baselines must survive unrelated edits shifting code up or down. The
key is ``checker::path::ident`` where ``ident`` is a checker-chosen
stable token (enclosing function + pattern, attribute name, ...).
Multiple findings can share a key — the baseline stores a *count* per
key, the same budget semantics the pre-framework lints used — so a
file may carry N grandfathered hits and the N+1th still fails.
"""

from __future__ import annotations

import dataclasses

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str          # checker name (kebab-case)
    rule: str             # rule id within the checker
    path: str             # repo-relative posix path
    line: int             # 1-based
    message: str
    ident: str            # stable identity token (no line numbers!)
    hint: str = ""        # how to fix it
    severity: str = ERROR
    col: int = 0          # 0-based, best effort

    @property
    def key(self) -> str:
        return f"{self.checker}::{self.path}::{self.ident}"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: [{self.checker}/{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d

    @staticmethod
    def from_dict(d: dict) -> "Finding":
        d = {k: v for k, v in d.items() if k != "key"}
        return Finding(**d)
