"""The grandfathered-findings baseline (``lint_baseline.json``).

Schema::

    {"version": 1,
     "entries": {
       "<checker>::<path>::<ident>": {
         "count": N,
         "justification": "one line on why this is intentional"}}}

Budget semantics: up to ``count`` findings with that key are
suppressed; the count+1th fails. Keys carry no line numbers, so the
baseline survives unrelated edits. An entry whose key matches nothing
is *stale* — the finding was fixed or the file renamed — and full-tree
runs fail until the entry is removed (a rotted budget would silently
cover a future regression, the exact failure mode the old per-file
allowlists guarded against with their entries-still-exist tests).

``--baseline-update`` rewrites the file from current findings,
preserving existing justifications; new entries get a ``TODO`` marker
the tier-1 gate rejects, so a human must write the one-line reason
before it can land.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from skypilot_tpu.analysis.findings import Finding

TODO_JUSTIFICATION = "TODO: justify this baseline entry"
_SCHEMA_VERSION = 1


def default_path(root: str) -> str:
    return os.path.join(root, "lint_baseline.json")


def load(path: str) -> Dict[str, dict]:
    """key -> {"count": int, "justification": str}. Missing file =>
    empty baseline (a fresh tree starts at zero)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != _SCHEMA_VERSION:
        raise ValueError(
            f"{path}: unsupported baseline version "
            f"{data.get('version')!r} (expected {_SCHEMA_VERSION})")
    entries = data.get("entries", {})
    out = {}
    for key, ent in entries.items():
        out[key] = {"count": int(ent.get("count", 1)),
                    "justification": str(ent.get("justification", ""))}
    return out


def save(path: str, entries: Dict[str, dict]) -> None:
    data = {"version": _SCHEMA_VERSION,
            "entries": {k: entries[k] for k in sorted(entries)}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def compare(findings: List[Finding], entries: Dict[str, dict]
            ) -> Tuple[List[Finding], List[str], List[str]]:
    """-> (new_findings, stale_keys, unjustified_keys).

    Suppression is per key with a count budget: findings beyond the
    budget surface in file/line order (the latest additions read as
    the new ones)."""
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    for key, group in by_key.items():
        budget = entries.get(key, {}).get("count", 0)
        if len(group) > budget:
            group = sorted(group, key=lambda f: (f.line, f.col))
            new.extend(group[budget:])
    stale = [k for k in entries if not by_key.get(k)]
    unjustified = [
        k for k in entries
        if not entries[k]["justification"].strip()
        or entries[k]["justification"].startswith("TODO")]
    return new, sorted(stale), sorted(unjustified)


def updated(findings: List[Finding], old: Dict[str, dict]
            ) -> Dict[str, dict]:
    """The baseline that makes ``findings`` exactly clean: one entry
    per key with the observed count; justifications carried over from
    ``old``, new keys marked TODO."""
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    out = {}
    for key, n in counts.items():
        just = old.get(key, {}).get("justification",
                                    TODO_JUSTIFICATION)
        out[key] = {"count": n, "justification": just}
    return out
