"""Repo-native static analysis: the standing correctness gate.

The engine's performance story rests on properties only visible in the
source: static shapes inside jitted code, no hidden host syncs in the
decode loop, lock discipline across the engine/watchdog/server threads,
typed errors on request paths, and event/metric hygiene. ``skytpu
lint`` (and the tier-1 ``tests/test_static_analysis.py`` gate) checks
all of them on every change, against a checked-in baseline of
grandfathered findings (``lint_baseline.json``, every entry justified).

Layout:
  core.py      — Checker base + registry, FileContext, the runner
  findings.py  — typed Finding objects (file:line, severity, fix hint)
  cache.py     — per-file mtime+hash result cache (warm --changed < 2s)
  baseline.py  — baseline load/save/compare (counts + justifications)
  checkers/    — the checkers themselves (docs/analysis.md catalog)
"""

from skypilot_tpu.analysis.core import (AnalysisResult, Checker,
                                        FileContext, all_checkers,
                                        get_checker, register, run)
from skypilot_tpu.analysis.findings import Finding

__all__ = [
    "AnalysisResult", "Checker", "FileContext", "Finding",
    "all_checkers", "get_checker", "register", "run",
]
