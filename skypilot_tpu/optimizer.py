"""Cost/time optimizer over catalog offerings, with blocklist-driven
re-optimization for the failover loop.

Reference parity: sky/optimizer.py (Optimizer:68 — optimize:106, chain DP
:408, candidate fill :1252). The reference also carries a PuLP ILP for
general DAGs (:469); since only chains are executable end-to-end there
(execution.py:188), this build implements the chain DP exactly and keeps
the general-DAG hook as a TODO rather than an unused ILP dependency.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import timeline
from skypilot_tpu.task import Task

# (cloud, region|None, zone|None) triples; None = block whole scope.
BlockedSet = Set[Tuple[Optional[str], Optional[str], Optional[str]]]

DEFAULT_RUNTIME_ESTIMATE_S = 3600.0

# $/GB egress between regions (cross-continent flat rate; intra-region 0).
_EGRESS_PER_GB = 0.12
# Nominal cross-region transfer bandwidth for the TIME objective
# (reference: sky/optimizer.py:93 egress_time uses the same idea).
_EGRESS_GB_PER_S = 1.0


class OptimizeTarget(enum.Enum):
    COST = "cost"
    TIME = "time"


@dataclasses.dataclass
class Candidate:
    resources: Resources
    cost: float          # $ for the task's estimated runtime, all nodes
    time_s: float        # estimated runtime on THIS candidate


def _reserved_nodes_available(resources: Resources,
                              cache: Dict[Tuple, int]) -> int:
    """Unused reserved capacity usable by this candidate (0 unless the
    config names ``gcp.specific_reservations``). Cached per zone for
    one optimize call — the availability query is a cloud API hit.
    Reference: sky/optimizer.py:345-355 treats reserved nodes as
    already-paid-for (cost 0)."""
    if resources.cloud != "gcp" or resources.use_spot:
        return 0
    from skypilot_tpu.provision import gcp
    if not gcp.configured_reservations():
        return 0
    key = (resources.zone, resources.instance_type)
    if key not in cache:
        try:
            cache[key] = sum(gcp.list_reservations_available(
                resources.zone, resources.instance_type).values())
        except Exception:  # noqa: BLE001 — availability is advisory
            cache[key] = 0
    return cache[key]


def _candidates_for(task: Task, blocked: BlockedSet) -> List[Candidate]:
    """Launchable candidates with per-accelerator runtime scaling
    (reference: _estimate_nodes_cost_or_time, sky/optimizer.py:236).

    ``task.estimated_runtime_seconds`` is wall time on ONE v5e-chip
    equivalent; a candidate with more/faster chips finishes
    proportionally sooner (catalog.compute_units). Without an estimate,
    a flat default duration applies to every candidate — a duration, not
    an amount of work, so it does NOT scale.
    """
    from skypilot_tpu.catalog import catalog
    est = task.estimated_runtime_seconds
    out: List[Candidate] = []
    reserved_cache: Dict[Tuple, int] = {}
    for r in task.resources:
        for launchable in r.launchables(blocked):
            if est is not None and est > 0:
                units = catalog.compute_units(
                    launchable.accelerator_name,
                    launchable.accelerator_count) * task.num_nodes
                time_s = est / max(units, 1e-9)
            else:
                time_s = DEFAULT_RUNTIME_ESTIMATE_S
            # Reserved capacity is already paid for: those nodes cost 0
            # in the plan (reference: sky/optimizer.py:345-355).
            n_reserved = _reserved_nodes_available(launchable,
                                                   reserved_cache)
            billable = max(task.num_nodes - n_reserved, 0)
            cost = launchable.get_cost(time_s) * billable
            out.append(Candidate(launchable, cost, time_s))
    if not out:
        raise exceptions.ResourcesUnavailableError(
            f"no feasible resources for {task} "
            f"(requested {task.resources}, {len(blocked)} blocked)")
    return out


def _egress_cost(a: Resources, b: Resources, gigabytes: float) -> float:
    """Cross-region data movement between consecutive chain tasks ($)."""
    if gigabytes <= 0 or a.region == b.region:
        return 0.0
    return gigabytes * _EGRESS_PER_GB


def _egress_time(a: Resources, b: Resources, gigabytes: float) -> float:
    """Cross-region transfer wall time (seconds) for the TIME target —
    the edge term must share units with the node objective."""
    if gigabytes <= 0 or a.region == b.region:
        return 0.0
    return gigabytes / _EGRESS_GB_PER_S


def _edge_gigabytes(upstream: Task) -> float:
    return float(upstream.estimated_outputs_gb or 0.0)


@timeline.event
def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[BlockedSet] = None,
             quiet: bool = True) -> Dict[Task, Resources]:
    """Pick one launchable Resources per task, minimizing total cost/time.

    Chain DAGs get an exact DP over (task, candidate) states with egress
    terms on the edges; a bare task set degenerates to per-task argmin.
    """
    blocked = blocked_resources or set()
    if not dag.is_chain():
        raise exceptions.InvalidTaskError(
            "only chain DAGs are supported (matches the reference's "
            "executable surface, sky/execution.py:188)")

    order = dag.topological_order()
    if not order:
        return {}

    per_task = {t: _candidates_for(t, blocked) for t in order}
    if minimize is OptimizeTarget.COST:
        key = lambda c: c.cost
        edge_fn = _egress_cost
    else:
        key = lambda c: c.time_s
        edge_fn = _egress_time

    # DP over the chain: best[i][j] = min objective ending at task i using
    # candidate j, including egress from the chosen parent candidate.
    best: List[List[float]] = []
    back: List[List[int]] = []
    for i, t in enumerate(order):
        cands = per_task[t]
        row, brow = [], []
        for j, c in enumerate(cands):
            if i == 0:
                row.append(key(c))
                brow.append(-1)
                continue
            prev_cands = per_task[order[i - 1]]
            edge_gb = _edge_gigabytes(order[i - 1])
            best_val, best_k = None, -1
            for k, pc in enumerate(prev_cands):
                egress = edge_fn(pc.resources, c.resources, edge_gb)
                v = best[i - 1][k] + key(c) + egress
                if best_val is None or v < best_val:
                    best_val, best_k = v, k
            row.append(best_val)
            brow.append(best_k)
        best.append(row)
        back.append(brow)

    # Trace back the argmin path.
    plan: Dict[Task, Resources] = {}
    j = min(range(len(best[-1])), key=lambda j: best[-1][j])
    for i in range(len(order) - 1, -1, -1):
        plan[order[i]] = per_task[order[i]][j].resources
        j = back[i][j]

    if not quiet:
        _print_plan(order, per_task, plan)
    return plan


def optimize_task(task: Task,
                  blocked_resources: Optional[BlockedSet] = None,
                  quiet: bool = True) -> Resources:
    """Single-task fast path (the common `launch` case)."""
    d = dag_lib.Dag()
    d.add(task)
    return optimize(d, blocked_resources=blocked_resources,
                    quiet=quiet)[task]


def _print_plan(order, per_task, plan) -> None:
    """Reference-style comparison table (sky/optimizer.py:717): the
    chosen candidate per task plus the best per-accelerator
    alternatives with their estimated cost/time."""
    print(f"{'TASK':<20}{'RESOURCES':<40}{'$/HR':>8}{'EST $':>9}"
          f"{'EST TIME':>10}  CHOSEN")
    for t in order:
        chosen = plan[t]
        # Best (cheapest) candidate per distinct accelerator — but the
        # CHOSEN candidate always keeps its row (an egress-steered pick
        # may not be its accelerator's cheapest).
        by_accel = {}
        for c in per_task[t]:
            a = c.resources.accelerator_name or c.resources.instance_type
            if c.resources == chosen:
                a = f"{a} (chosen)"
            if a not in by_accel or c.cost < by_accel[a].cost:
                by_accel[a] = c
        rows = sorted(by_accel.values(), key=lambda c: c.cost)
        chosen_rows = [c for c in rows if c.resources == chosen]
        rows = rows[:4]
        # The chosen row survives truncation unconditionally.
        if chosen_rows and chosen_rows[0] not in rows:
            rows[-1] = chosen_rows[0]
        for c in rows:
            mark = "  <-" if c.resources == chosen else ""
            price = c.resources.price or 0.0
            print(f"{(t.name or '-'):<20}{str(c.resources):<40}"
                  f"{price:>8.2f}{c.cost:>9.2f}"
                  f"{c.time_s / 60:>9.1f}m{mark}")
