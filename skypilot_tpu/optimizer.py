"""Cost/time optimizer over catalog offerings, with blocklist-driven
re-optimization for the failover loop.

Reference parity: sky/optimizer.py (Optimizer:68 — optimize:106, chain DP
:408, candidate fill :1252). The reference also carries a PuLP ILP for
general DAGs (:469); since only chains are executable end-to-end there
(execution.py:188), this build implements the chain DP exactly and keeps
the general-DAG hook as a TODO rather than an unused ILP dependency.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import timeline
from skypilot_tpu.task import Task

# (cloud, region|None, zone|None) triples; None = block whole scope.
BlockedSet = Set[Tuple[Optional[str], Optional[str], Optional[str]]]

DEFAULT_RUNTIME_ESTIMATE_S = 3600.0

# $/GB egress between regions (cross-continent flat rate; intra-region 0).
_EGRESS_PER_GB = 0.12


class OptimizeTarget(enum.Enum):
    COST = "cost"
    TIME = "time"


@dataclasses.dataclass
class Candidate:
    resources: Resources
    cost: float          # $ for the task's estimated runtime, all nodes
    time_s: float        # estimated runtime


def _candidates_for(task: Task, blocked: BlockedSet) -> List[Candidate]:
    est = task.estimated_runtime_seconds or DEFAULT_RUNTIME_ESTIMATE_S
    out: List[Candidate] = []
    for r in task.resources:
        for launchable in r.launchables(blocked):
            cost = launchable.get_cost(est) * task.num_nodes
            out.append(Candidate(launchable, cost, est))
    if not out:
        raise exceptions.ResourcesUnavailableError(
            f"no feasible resources for {task} "
            f"(requested {task.resources}, {len(blocked)} blocked)")
    return out


def _egress_cost(a: Resources, b: Resources, gigabytes: float = 0.0) -> float:
    if gigabytes <= 0 or a.region == b.region:
        return 0.0
    return gigabytes * _EGRESS_PER_GB


@timeline.event
def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[BlockedSet] = None,
             quiet: bool = True) -> Dict[Task, Resources]:
    """Pick one launchable Resources per task, minimizing total cost/time.

    Chain DAGs get an exact DP over (task, candidate) states with egress
    terms on the edges; a bare task set degenerates to per-task argmin.
    """
    blocked = blocked_resources or set()
    if not dag.is_chain():
        raise exceptions.InvalidTaskError(
            "only chain DAGs are supported (matches the reference's "
            "executable surface, sky/execution.py:188)")

    order = dag.topological_order()
    if not order:
        return {}

    per_task = {t: _candidates_for(t, blocked) for t in order}
    key = (lambda c: c.cost) if minimize is OptimizeTarget.COST else \
        (lambda c: c.time_s)

    # DP over the chain: best[i][j] = min objective ending at task i using
    # candidate j, including egress from the chosen parent candidate.
    best: List[List[float]] = []
    back: List[List[int]] = []
    for i, t in enumerate(order):
        cands = per_task[t]
        row, brow = [], []
        for j, c in enumerate(cands):
            if i == 0:
                row.append(key(c))
                brow.append(-1)
                continue
            prev_cands = per_task[order[i - 1]]
            best_val, best_k = None, -1
            for k, pc in enumerate(prev_cands):
                egress = _egress_cost(pc.resources, c.resources)
                v = best[i - 1][k] + key(c) + egress
                if best_val is None or v < best_val:
                    best_val, best_k = v, k
            row.append(best_val)
            brow.append(best_k)
        best.append(row)
        back.append(brow)

    # Trace back the argmin path.
    plan: Dict[Task, Resources] = {}
    j = min(range(len(best[-1])), key=lambda j: best[-1][j])
    for i in range(len(order) - 1, -1, -1):
        plan[order[i]] = per_task[order[i]][j].resources
        j = back[i][j]

    if not quiet:
        _print_plan(order, per_task, plan)
    return plan


def optimize_task(task: Task,
                  blocked_resources: Optional[BlockedSet] = None
                  ) -> Resources:
    """Single-task fast path (the common `launch` case)."""
    d = dag_lib.Dag()
    d.add(task)
    return optimize(d, blocked_resources=blocked_resources)[task]


def _print_plan(order, per_task, plan) -> None:
    print(f"{'TASK':<24}{'CHOSEN':<44}{'$/HR':>8}  ALTERNATIVES")
    for t in order:
        chosen = plan[t]
        alts = len(per_task[t]) - 1
        print(f"{(t.name or '-'):<24}{str(chosen):<44}"
              f"{chosen.price if chosen.price is not None else 0:>8.2f}"
              f"  {alts}")
