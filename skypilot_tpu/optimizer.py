"""Cost/time optimizer over catalog offerings, with blocklist-driven
re-optimization for the failover loop.

Reference parity: sky/optimizer.py (Optimizer:68 — optimize:106, chain DP
:408, candidate fill :1252). The reference also carries a PuLP ILP for
general DAGs (:469); this build plans general DAGs natively — exact DP
on trees/forests, monotone coordinate descent on multi-parent graphs —
with COST as total spend and TIME as end-to-end makespan (only chains
are executable end-to-end in the reference, execution.py:188).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import timeline
from skypilot_tpu.task import Task

# (cloud, region|None, zone|None) triples; None = block whole scope.
BlockedSet = Set[Tuple[Optional[str], Optional[str], Optional[str]]]

DEFAULT_RUNTIME_ESTIMATE_S = 3600.0

# $/GB egress between regions (cross-continent flat rate; intra-region 0).
_EGRESS_PER_GB = 0.12
# Nominal cross-region transfer bandwidth for the TIME objective
# (reference: sky/optimizer.py:93 egress_time uses the same idea).
_EGRESS_GB_PER_S = 1.0


class OptimizeTarget(enum.Enum):
    COST = "cost"
    TIME = "time"


@dataclasses.dataclass
class Candidate:
    resources: Resources
    cost: float          # $ for the task's estimated runtime, all nodes
    time_s: float        # estimated runtime on THIS candidate


def _reserved_key(resources: Resources) -> Optional[Tuple]:
    if resources.cloud != "gcp" or resources.use_spot:
        return None
    from skypilot_tpu.provision import gcp
    if not gcp.configured_reservations():
        return None
    return (resources.zone, resources.instance_type)


def _reserved_nodes_available(resources: Resources,
                              cache: Dict[Tuple, int]) -> int:
    """Unused reserved capacity usable by this candidate (0 unless the
    config names ``gcp.specific_reservations``). Cached per zone for
    one optimize call — the availability query is a cloud API hit.
    Reference: sky/optimizer.py:345-355 treats reserved nodes as
    already-paid-for (cost 0)."""
    key = _reserved_key(resources)
    if key is None:
        return 0
    if key not in cache:
        from skypilot_tpu.provision import gcp
        try:
            cache[key] = sum(gcp.list_reservations_available(
                resources.zone, resources.instance_type).values())
        except Exception:  # noqa: BLE001 — availability is advisory
            cache[key] = 0
    return cache[key]


def _candidates_for(task: Task, blocked: BlockedSet,
                    reserved_cache: Optional[Dict[Tuple, int]] = None,
                    ) -> List[Candidate]:
    """Launchable candidates with per-accelerator runtime scaling
    (reference: _estimate_nodes_cost_or_time, sky/optimizer.py:236).

    ``task.estimated_runtime_seconds`` is wall time on ONE v5e-chip
    equivalent; a candidate with more/faster chips finishes
    proportionally sooner (catalog.compute_units). Without an estimate,
    a flat default duration applies to every candidate — a duration, not
    an amount of work, so it does NOT scale.
    """
    from skypilot_tpu.catalog import catalog
    est = task.estimated_runtime_seconds
    out: List[Candidate] = []
    if reserved_cache is None:
        reserved_cache = {}
    consumed: Dict[Tuple, int] = {}
    for r in task.resources:
        for launchable in r.launchables(blocked):
            if est is not None and est > 0:
                units = catalog.compute_units(
                    launchable.accelerator_name,
                    launchable.accelerator_count) * task.num_nodes
                time_s = est / max(units, 1e-9)
            else:
                time_s = DEFAULT_RUNTIME_ESTIMATE_S
            # Reserved capacity is already paid for: those nodes cost 0
            # in the plan (reference: sky/optimizer.py:345-355).
            n_reserved = _reserved_nodes_available(launchable,
                                                   reserved_cache)
            billable = max(task.num_nodes - n_reserved, 0)
            cost = launchable.get_cost(time_s) * billable
            if n_reserved > 0:
                key = _reserved_key(launchable)
                consumed[key] = max(consumed.get(key, 0),
                                    min(task.num_nodes, n_reserved))
            out.append(Candidate(launchable, cost, time_s))
    # Greedy capacity consumption: a multi-task DAG planned in one call
    # must not discount the SAME unused reservation capacity once per
    # task. Conservative — this task is charged as if it takes the
    # reservation in every zone it considered, so later tasks may see
    # less capacity than runtime reality; never less cost.
    for key, used in consumed.items():
        reserved_cache[key] = max(reserved_cache[key] - used, 0)
    if not out:
        from skypilot_tpu import check as check_lib
        enabled = check_lib.cached_enabled_clouds()
        hint = ""
        if enabled is not None:
            wanted = {r.cloud for r in task.resources if r.cloud}
            disabled = sorted(wanted - set(enabled))
            if disabled or not any(c in enabled
                                   for c in catalog.CATALOG_CLOUDS):
                hint = (f" — cloud(s) {disabled or 'gcp/aws'} not "
                        f"enabled (enabled: {enabled}); run `skytpu "
                        f"check` after configuring credentials")
        raise exceptions.ResourcesUnavailableError(
            f"no feasible resources for {task} "
            f"(requested {task.resources}, {len(blocked)} blocked)"
            f"{hint}")
    return out


def _egress_cost(a: Resources, b: Resources, gigabytes: float) -> float:
    """Cross-region data movement between consecutive chain tasks ($)."""
    if gigabytes <= 0 or a.region == b.region:
        return 0.0
    return gigabytes * _EGRESS_PER_GB


def _egress_time(a: Resources, b: Resources, gigabytes: float) -> float:
    """Cross-region transfer wall time (seconds) for the TIME target —
    the edge term must share units with the node objective."""
    if gigabytes <= 0 or a.region == b.region:
        return 0.0
    return gigabytes / _EGRESS_GB_PER_S


def _edge_gigabytes(upstream: Task) -> float:
    return float(upstream.estimated_outputs_gb or 0.0)


@timeline.event
def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[BlockedSet] = None,
             quiet: bool = True) -> Dict[Task, Resources]:
    """Pick one launchable Resources per task.

    COST minimizes total spend (node costs + egress); TIME minimizes
    end-to-end makespan (longest node+edge path; for a chain that is
    the plain sum). Exact on chains/trees/forests via DP; multi-parent
    DAGs refine by coordinate descent (see :func:`optimize_dag` — the
    reference reaches for a PuLP ILP there, sky/optimizer.py:469, but
    only ever executes chains, execution.py:188).
    """
    return optimize_dag(dag, minimize, blocked_resources, quiet)


def optimize_dag(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked: Optional[BlockedSet] = None,
                 quiet: bool = True) -> Dict[Task, Resources]:
    """General-DAG planner.

    Objectives: COST = sum of node costs + egress edges; TIME =
    MAKESPAN — the longest node+edge path, i.e. when the pipeline's
    last task finishes with branches running in parallel (matching the
    reference ILP's per-node finish-time model, sky/optimizer.py:469;
    a naive branch-time SUM would prefer plans that finish later).

    Exactness tiers:

    1. Forest (every task has <= 1 parent): exact bottom-up tree DP —
       best[v][j] = node(v,j) COMBINE over children c of
       min_k(edge(v_j, c_k) + best[c][k]), where COMBINE is sum for
       COST and max for TIME. Covers chains and fan-out pipelines.
    2. Multi-parent DAGs: per-task argmin init, then topological
       coordinate-descent sweeps re-choosing each task against the
       full objective until no sweep improves (monotone, converges;
       exact on the overwhelming egress-free case, a documented
       heuristic otherwise).
    """
    import networkx as nx
    blocked = blocked or set()
    g = dag.graph
    if not nx.is_directed_acyclic_graph(g):
        raise exceptions.InvalidTaskError("task graph has a cycle")
    order = dag.topological_order()
    if not order:
        return {}
    reserved_cache: Dict[Tuple, int] = {}
    per_task = {t: _candidates_for(t, blocked, reserved_cache)
                for t in order}
    is_cost = minimize is OptimizeTarget.COST
    if is_cost:
        key = lambda c: c.cost
        edge_fn = _egress_cost
    else:
        key = lambda c: c.time_s
        edge_fn = _egress_time

    def edge(u, cu, v, cv):
        return edge_fn(cu.resources, cv.resources, _edge_gigabytes(u))

    if all(g.in_degree(v) <= 1 for v in order):
        # Exact tree DP, leaves up. Children combine by SUM for COST
        # (spend adds across branches) and MAX for TIME (branches run
        # in parallel; the slowest one sets the finish).
        best: Dict[Task, List[float]] = {}
        pick: Dict[Task, List[Dict[Task, int]]] = {}
        for v in reversed(order):
            cands = per_task[v]
            best[v] = []
            pick[v] = []
            for j, c in enumerate(cands):
                node = key(c)
                branch = 0.0
                child_pick: Dict[Task, int] = {}
                for w in g.successors(v):
                    k = min(range(len(per_task[w])),
                            key=lambda k: edge(v, c, w, per_task[w][k])
                            + best[w][k])
                    val = edge(v, c, w, per_task[w][k]) + best[w][k]
                    branch = branch + val if is_cost else max(branch, val)
                    child_pick[w] = k
                best[v].append(node + branch)
                pick[v].append(child_pick)
        plan_idx: Dict[Task, int] = {}
        for r in order:
            if g.in_degree(r) == 0:
                # Roots are independent: per-root argmin minimizes both
                # the sum (COST) and the max (TIME) across roots.
                plan_idx[r] = min(range(len(per_task[r])),
                                  key=lambda j: best[r][j])
        for v in order:  # propagate picks down the tree
            for w, k in pick[v][plan_idx[v]].items():
                plan_idx[w] = k
    else:
        def objective(idx):
            if is_cost:
                total = sum(key(per_task[t][idx[t]]) for t in order)
                for u, v in g.edges:
                    total += edge(u, per_task[u][idx[u]],
                                  v, per_task[v][idx[v]])
                return total
            finish: Dict[Task, float] = {}
            for t in order:   # topological
                start = 0.0
                for u in g.predecessors(t):
                    start = max(start, finish[u]
                                + edge(u, per_task[u][idx[u]],
                                       t, per_task[t][idx[t]]))
                finish[t] = start + key(per_task[t][idx[t]])
            return max(finish.values())

        n_combos = 1
        for t in order:
            n_combos *= max(len(per_task[t]), 1)
            if n_combos > _EXACT_COMBO_CAP:
                break
        if n_combos <= _EXACT_COMBO_CAP:
            # Small graph (the common case): exhaustive enumeration is
            # EXACT — this is the role of the reference's PuLP ILP
            # (sky/optimizer.py:469) without the solver dependency.
            plan_idx = _exact_small_dag(order, per_task, g, key,
                                        edge, is_cost)
        else:
            plan_idx = _coordinate_descent(order, per_task, key,
                                           objective)

    plan = {t: per_task[t][plan_idx[t]].resources for t in order}
    if not quiet:
        _print_plan(order, per_task, plan)
    return plan


_EXACT_COMBO_CAP = 100_000


def _exact_small_dag(order, per_task, g, key, edge, is_cost):
    """Exhaustive exact optimizer for multi-parent DAGs whose joint
    candidate space fits under _EXACT_COMBO_CAP. Node values and edge
    value matrices are precomputed so each combination evaluates in
    microseconds."""
    import itertools

    node_val = {t: [key(c) for c in per_task[t]] for t in order}
    edge_mat = {}
    for u, v in g.edges:
        edge_mat[(u, v)] = [[edge(u, cu, v, cv) for cv in per_task[v]]
                            for cu in per_task[u]]
    preds = {t: list(g.predecessors(t)) for t in order}
    best_val, best_combo = float("inf"), None
    for combo in itertools.product(
            *(range(len(per_task[t])) for t in order)):
        idx = dict(zip(order, combo))
        if is_cost:
            val = sum(node_val[t][idx[t]] for t in order)
            for (u, v), mat in edge_mat.items():
                val += mat[idx[u]][idx[v]]
        else:
            finish = {}
            for t in order:
                start = 0.0
                for u in preds[t]:
                    s = finish[u] + edge_mat[(u, t)][idx[u]][idx[t]]
                    if s > start:
                        start = s
                finish[t] = start + node_val[t][idx[t]]
            val = max(finish.values())
        if val < best_val:
            best_val, best_combo = val, idx
    return best_combo


def _coordinate_descent(order, per_task, key, objective):
    """Fallback above the exact cap: per-task argmin init, then
    topological sweeps re-choosing each task against the full objective
    until no sweep improves (monotone, converges; a documented
    heuristic — no optimality bound)."""
    plan_idx = {t: min(range(len(per_task[t])),
                       key=lambda j, t=t: key(per_task[t][j]))
                for t in order}

    for _ in range(len(order) + 2):   # each sweep is monotone
        improved = False
        for t in order:
            cur = objective(plan_idx)
            for j in range(len(per_task[t])):
                if j == plan_idx[t]:
                    continue
                trial = dict(plan_idx)
                trial[t] = j
                val = objective(trial)
                if val < cur - 1e-12:
                    plan_idx[t] = j
                    cur = val
                    improved = True
        if not improved:
            break
    return plan_idx


def optimize_task(task: Task,
                  blocked_resources: Optional[BlockedSet] = None,
                  quiet: bool = True) -> Resources:
    """Single-task fast path (the common `launch` case)."""
    d = dag_lib.Dag()
    d.add(task)
    return optimize(d, blocked_resources=blocked_resources,
                    quiet=quiet)[task]


def _print_plan(order, per_task, plan) -> None:
    """Reference-style comparison table (sky/optimizer.py:717): the
    chosen candidate per task plus the best per-accelerator
    alternatives with their estimated cost/time."""
    print(f"{'TASK':<20}{'RESOURCES':<40}{'$/HR':>8}{'EST $':>9}"
          f"{'EST TIME':>10}  CHOSEN")
    for t in order:
        chosen = plan[t]
        # Best (cheapest) candidate per distinct accelerator — but the
        # CHOSEN candidate always keeps its row (an egress-steered pick
        # may not be its accelerator's cheapest).
        by_accel = {}
        for c in per_task[t]:
            a = c.resources.accelerator_name or c.resources.instance_type
            if c.resources == chosen:
                a = f"{a} (chosen)"
            if a not in by_accel or c.cost < by_accel[a].cost:
                by_accel[a] = c
        rows = sorted(by_accel.values(), key=lambda c: c.cost)
        chosen_rows = [c for c in rows if c.resources == chosen]
        rows = rows[:4]
        # The chosen row survives truncation unconditionally.
        if chosen_rows and chosen_rows[0] not in rows:
            rows[-1] = chosen_rows[0]
        for c in rows:
            mark = "  <-" if c.resources == chosen else ""
            price = c.resources.price or 0.0
            print(f"{(t.name or '-'):<20}{str(c.resources):<40}"
                  f"{price:>8.2f}{c.cost:>9.2f}"
                  f"{c.time_s / 60:>9.1f}m{mark}")
