"""Cluster state DB (sqlite). Reference parity: sky/global_user_state.py
(clusters table :56, cluster_history :88). The handle is JSON, not a
pickle — the reference pickles ResourceHandle objects into sqlite, which
makes schema evolution painful; JSON keeps it greppable and versioned."""

from __future__ import annotations

import contextlib
import enum
import json
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import db, paths


class ClusterStatus(enum.Enum):
    INIT = "INIT"
    UP = "UP"
    STOPPED = "STOPPED"


# Schema history (PRAGMA user_version):
#   v1: clusters / cluster_history / storage
#   v2: + users table, + clusters.owner (reference parity:
#       sky/global_user_state.py:110 users table, :175 owner recorded
#       per cluster)
SCHEMA_VERSION = 2

_SCHEMA = """
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle TEXT,
    status TEXT,
    autostop_minutes INTEGER DEFAULT -1,
    autostop_down INTEGER DEFAULT 0,
    price_per_hour REAL DEFAULT 0,
    owner TEXT
);
CREATE TABLE IF NOT EXISTS cluster_history (
    name TEXT,
    launched_at INTEGER,
    duration_s REAL,
    price_per_hour REAL,
    resources TEXT,
    num_nodes INTEGER
);
CREATE TABLE IF NOT EXISTS storage (
    name TEXT PRIMARY KEY,
    handle TEXT,
    created_at INTEGER
);
CREATE TABLE IF NOT EXISTS users (
    id TEXT PRIMARY KEY,
    name TEXT,
    created_at INTEGER
);
"""

_MIGRATIONS = {
    2: """
ALTER TABLE clusters ADD COLUMN owner TEXT;
CREATE TABLE IF NOT EXISTS users (
    id TEXT PRIMARY KEY,
    name TEXT,
    created_at INTEGER
);
""",
}


@contextlib.contextmanager
def _db():
    conn = db.open_versioned(paths.state_db(), _SCHEMA, SCHEMA_VERSION,
                             _MIGRATIONS, timeout=10)
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def set_cluster(name: str, handle: Dict[str, Any], status: ClusterStatus,
                price_per_hour: float = 0.0,
                owner: Optional[Dict[str, str]] = None) -> None:
    """Upsert a cluster record. ``owner`` ({"id","name"}) is stamped on
    CREATE and preserved on update — re-launches by another user do not
    steal the cluster (they are refused upstream by
    ``check_owner_identity``)."""
    if owner is not None:
        record_user(owner)
    with _db() as c:
        c.execute(
            "INSERT INTO clusters (name, launched_at, handle, status,"
            " price_per_hour, owner) VALUES (?,?,?,?,?,?)"
            " ON CONFLICT(name) DO UPDATE"
            " SET handle=excluded.handle, status=excluded.status,"
            " price_per_hour=excluded.price_per_hour,"
            " owner=COALESCE(clusters.owner, excluded.owner)",
            (name, int(time.time()), json.dumps(handle), status.value,
             price_per_hour, owner["id"] if owner else None))


def record_user(identity: Dict[str, str]) -> None:
    with _db() as c:
        c.execute(
            "INSERT OR IGNORE INTO users (id, name, created_at)"
            " VALUES (?,?,?)",
            (identity["id"], identity.get("name", ""), int(time.time())))


def get_user(user_id: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute("SELECT id, name, created_at FROM users"
                        " WHERE id=?", (user_id,)).fetchone()
    if row is None:
        return None
    return {"id": row[0], "name": row[1], "created_at": row[2]}


def set_cluster_status(name: str, status: ClusterStatus) -> None:
    with _db() as c:
        c.execute("UPDATE clusters SET status=? WHERE name=?",
                  (status.value, name))


def get_cluster(name: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(
            "SELECT name, launched_at, handle, status, autostop_minutes,"
            " autostop_down, price_per_hour, owner FROM clusters WHERE name=?",
            (name,)).fetchone()
    return _row_to_record(row) if row else None


def list_clusters() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT name, launched_at, handle, status, autostop_minutes,"
            " autostop_down, price_per_hour, owner FROM clusters"
            " ORDER BY launched_at DESC").fetchall()
    return [_row_to_record(r) for r in rows]


def remove_cluster(name: str) -> None:
    rec = get_cluster(name)
    with _db() as c:
        if rec is not None:
            c.execute(
                "INSERT INTO cluster_history (name, launched_at, duration_s,"
                " price_per_hour, resources, num_nodes) VALUES (?,?,?,?,?,?)",
                (name, rec["launched_at"],
                 time.time() - rec["launched_at"], rec["price_per_hour"],
                 json.dumps(rec["handle"].get("resources")),
                 rec["handle"].get("num_nodes", 1)))
        c.execute("DELETE FROM clusters WHERE name=?", (name,))


def set_autostop(name: str, idle_minutes: int, down: bool) -> None:
    with _db() as c:
        c.execute("UPDATE clusters SET autostop_minutes=?, autostop_down=?"
                  " WHERE name=?", (idle_minutes, int(down), name))


def cost_report() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT name, launched_at, duration_s, price_per_hour,"
            " num_nodes FROM cluster_history").fetchall()
    # price_per_hour is already the whole-cluster rate (all nodes).
    return [{"name": n, "launched_at": la, "duration_s": d,
             "cost": d / 3600.0 * p, "num_nodes": nn or 1}
            for n, la, d, p, nn in rows]


def _row_to_record(row) -> Dict[str, Any]:
    name, launched_at, handle, status, am, ad, price, owner = row
    return {
        "name": name,
        "launched_at": launched_at,
        "handle": json.loads(handle),
        "status": ClusterStatus(status),
        "autostop_minutes": am,
        "autostop_down": bool(ad),
        "price_per_hour": price,
        "owner": owner,
    }


# -- storage registry (reference: global_user_state storage table) ------

def add_storage(name: str, handle: Dict[str, Any]) -> None:
    with _db() as c:
        c.execute(
            "INSERT OR REPLACE INTO storage (name, handle, created_at)"
            " VALUES (?,?,?)", (name, json.dumps(handle), int(time.time())))


def list_storage() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT name, handle, created_at FROM storage").fetchall()
    return [{"name": n, "handle": json.loads(h), "created_at": ca}
            for n, h, ca in rows]


def get_storage(name: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute("SELECT name, handle, created_at FROM storage"
                        " WHERE name=?", (name,)).fetchone()
    if row is None:
        return None
    return {"name": row[0], "handle": json.loads(row[1]),
            "created_at": row[2]}


def remove_storage(name: str) -> None:
    with _db() as c:
        c.execute("DELETE FROM storage WHERE name=?", (name,))
