"""Layered global configuration.

Reference parity: sky/skypilot_config.py (docs at :1-50 — `get_nested`
over a nested dict loaded from ``~/.sky/config.yaml``, plus per-task
overrides merged via ``experimental.config_overrides``). Same model
here: a YAML file at ``$SKYPILOT_TPU_HOME/config.yaml`` (overridable
with ``SKYPILOT_TPU_CONFIG``), read once and cached; tasks may carry an
``config_overrides`` dict that is deep-merged on top for the duration
of one request (``override_config`` context manager).

Example config.yaml:

    gcp:
      project: my-proj
      specific_reservations: [res-1]   # VM reservations: affinity +
                                       # optimizer cost discount
      use_reserved_tpu_capacity: true  # TPU QR guaranteed/reserved tier
    provisioner:
      ssh_timeout: 300
    admin_policy: mypkg.policy.MyPolicy
"""

from __future__ import annotations

import contextlib
import copy
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

import yaml

from skypilot_tpu.utils import paths

_lock = threading.Lock()
_loaded: Optional[Dict[str, Any]] = None   # guarded-by: _lock
_loaded_path: Optional[str] = None         # guarded-by: _lock
_overrides = threading.local()


def config_path() -> str:
    return os.environ.get("SKYPILOT_TPU_CONFIG",
                          os.path.join(paths.home(), "config.yaml"))


def _load() -> Dict[str, Any]:
    global _loaded, _loaded_path
    path = config_path()
    with _lock:
        if _loaded is not None and _loaded_path == path:
            return _loaded
        cfg: Dict[str, Any] = {}
        if os.path.exists(path):
            with open(path) as f:
                loaded = yaml.safe_load(f)
            if loaded is not None and not isinstance(loaded, dict):
                raise ValueError(
                    f"config file {path} must parse to a dict, got "
                    f"{type(loaded).__name__}")
            cfg = loaded or {}
        _loaded, _loaded_path = cfg, path
        return cfg


def reload() -> None:
    """Drop the cache (used by tests and after `config set`)."""
    global _loaded, _loaded_path
    with _lock:
        _loaded = None
        _loaded_path = None


def loaded_config_path() -> Optional[str]:
    path = config_path()
    return path if os.path.exists(path) else None


def deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    """Return base with `over` recursively merged on top (new dict)."""
    out = copy.deepcopy(base)
    for key, val in over.items():
        if (key in out and isinstance(out[key], dict)
                and isinstance(val, dict)):
            out[key] = deep_merge(out[key], val)
        else:
            out[key] = copy.deepcopy(val)
    return out


def _effective() -> Dict[str, Any]:
    replacement = getattr(_overrides, "replacement", None)
    cfg = replacement if replacement is not None else _load()
    over = getattr(_overrides, "stack", None)
    if over:
        for layer in over:
            cfg = deep_merge(cfg, layer)
    return cfg


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_effective())


def get_nested(keys: Iterable[str], default: Any = None) -> Any:
    """config.get_nested(('gcp', 'project'), None)"""
    cur: Any = _effective()
    for key in keys:
        if not isinstance(cur, dict) or key not in cur:
            return default
        cur = cur[key]
    # Containers are copied: the no-override path returns views into the
    # process-wide cache, and a mutating caller must not corrupt it.
    return copy.deepcopy(cur) if isinstance(cur, (dict, list)) else cur


def set_nested(keys: Tuple[str, ...], value: Any) -> None:
    """Persist a value into the config file (used by `config set`)."""
    path = config_path()
    cfg: Dict[str, Any] = {}
    if os.path.exists(path):
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
    cur = cfg
    for key in keys[:-1]:
        cur = cur.setdefault(key, {})
        if not isinstance(cur, dict):
            raise ValueError(f"config key {'.'.join(keys)} conflicts with "
                             f"a non-dict value")
    cur[keys[-1]] = value
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f, sort_keys=False)
    reload()


@contextlib.contextmanager
def replace_config(new_config: Optional[Dict[str, Any]]):
    """Replace the effective base config for the enclosed request —
    used to honor an admin policy's mutated skypilot_config."""
    if new_config is None:
        yield
        return
    prev = getattr(_overrides, "replacement", None)
    _overrides.replacement = copy.deepcopy(new_config)
    try:
        yield
    finally:
        _overrides.replacement = prev


@contextlib.contextmanager
def override_config(overrides: Optional[Dict[str, Any]]):
    """Apply per-task `config_overrides` for the enclosed request.

    Reference parity: task `experimental.config_overrides` merged in
    sky/skypilot_config.py / resources.py:487.
    """
    if not overrides:
        yield
        return
    stack = getattr(_overrides, "stack", None)
    if stack is None:
        stack = _overrides.stack = []
    stack.append(overrides)
    try:
        yield
    finally:
        stack.pop()
