"""Paged decode-attention as a Pallas TPU kernel.

The PagedAttention idea (vLLM / the JAX TPU serving stack) built in
this repo's Pallas idiom (``ops/flash_attention.py``): decode/verify/
chunk attention over the paged KV block pool WITHOUT materializing the
gathered ``[slots, span, G, hd]`` logical view per layer — the last
copy standing between the serving engine and memory-bandwidth-bound
decode (docs/serving.md §Paged KV cache named it as the residual gap).

Design (per the pallas TPU playbook):

* Grid ``(slots, kv_heads, span_blocks)``; the kernel walks each
  slot's BLOCK TABLE directly via scalar-prefetch index maps
  (``pltpu.PrefetchScalarGridSpec``): the block-table row and the
  per-slot lengths are prefetched to SMEM, and the K/V pool's
  BlockSpec index map reads ``table[slot, j]`` to DMA the j-th
  *logical* block's *physical* rows straight from HBM — no gather, no
  transient. Sentinel entries (logical blocks past the slot's
  allocation) clamp to physical block 0; their compute is skipped.
* The layer index rides the same scalar-prefetch channel, so the one
  kernel serves every layer of the ``lax.scan`` without slicing a
  per-layer pool copy (which would be a bigger transient than the
  gather it replaces).
* The KV sweep is the innermost grid dimension with the online-softmax
  running (max, sum, acc) carried in VMEM scratch across grid steps —
  the FlashAttention-2 accumulation, initialized at block 0 and
  written out at the last block. A block whose start row is past the
  slot's length is skipped whole (the span-rung ladder bounds the
  grid; the length bounds the work).
* int8 KV dequantizes IN KERNEL from the pool's per-(block, head, row)
  scale tensors: K's scale applies to the scores, V's folds into the
  softmax weights — bit-for-bit the factorization the XLA gather path
  uses, so nothing dequantized at cache shape ever exists.

The kernel returns UNNORMALIZED partial-softmax stats ``(acc, m, l)``
rather than finished attention: the caller merges them with the
staged-columns block (the in-burst K/V rows that live outside the big
cache) via the standard two-block online-softmax combine
(``kvcache._merge_attn_parts``). The merged output equals the XLA
gather path's up to summation order — greedy parity (not bit parity)
is the contract, asserted against the gather oracle in
tests/test_paged_attention.py across dtypes, spec modes and span
rungs.

``interpret=True`` runs the kernel on CPU (tier-1 tests, the
flash-attention precedent); on TPU backends the kernel compiles to
Mosaic. Rows-per-cell is ``rep = n_heads // n_kv_heads`` on the decode
path — small tiles that Mosaic pads; the chunk path batches
``C * rep`` rows per cell and amortizes properly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128     # lane-replicated rowwise stats (Mosaic tiling)
NEG_INF = -1e30


def _kernel(layer_ref, table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
            block_len: int, span_blocks: int, scale: float,
            quant: bool):
    if quant:
        ks_ref, vs_ref, acc_ref, m_ref, l_ref, acc_s, m_s, l_s = rest
    else:
        acc_ref, m_ref, l_ref, acc_s, m_s, l_s = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    length = len_ref[b]

    # A block whose first row is past the slot's length holds nothing
    # the mask admits (sentinel table entries always land here: a
    # slot's length never exceeds its allocated rows) — skip the whole
    # block, the causal-pruning idiom of the flash kernel.
    @pl.when(j * block_len < length)
    def _process():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [R, hd]
        k = k_ref[0, 0, :, 0, :].astype(jnp.float32)      # [bl, hd]
        v = v_ref[0, 0, :, 0, :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if quant:
            s = s * ks_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        col = j * block_len + lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(col < length, s, NEG_INF)
        m_prev = m_s[:, :1]                               # [R, 1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                            # [R, bl]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            p = p * vs_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        acc_s[...] = acc_s[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(j == span_blocks - 1)
    def _emit():
        acc_ref[0, 0] = acc_s[...]
        m_ref[0, 0] = m_s[...]
        l_ref[0, 0] = l_s[...]


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    k_scale: Optional[jax.Array],
                    v_scale: Optional[jax.Array],
                    table: jax.Array, lengths: jax.Array,
                    layer: jax.Array, *, span_blocks: int,
                    interpret: Optional[bool] = None
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Block-table-native online-softmax attention over the paged pool.

    q: ``[B, G, R, hd]`` query rows per (slot, kv-head) — ``R`` is the
    GQA repeat on the decode path, ``C * rep`` on the chunk path.
    k_pool/v_pool: the full pool ``[L, n_blocks, block_len, G, hd]``
    (fp, or int8 with ``k_scale``/``v_scale`` ``[L, n_blocks, G,
    block_len]``). table: ``[B, nb+1]`` int32 per-slot block tables
    (sentinel == n_blocks). lengths: ``[B]`` int32 — the score mask is
    ``col < lengths[b]``, the burst-start validity rule. layer:
    traced int32 scalar selecting the pool's layer via scalar
    prefetch. ``span_blocks`` (static): logical blocks to sweep — the
    span-rung ladder divided by the block length, so the block loop is
    span-bounded exactly like the gather path's table prefix.

    Returns unnormalized stats ``(acc [B,G,R,hd] f32, m [B,G,R] f32,
    l [B,G,R] f32)``: ``acc`` is sum(p * v) with V's dequant scale
    folded in, ``m`` the running row max, ``l`` sum(p). A slot whose
    every block was masked (length 0) reports ``m == -1e30`` and the
    caller's merge annihilates its contribution.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, G, R, hd = q.shape
    n_blocks, bl = k_pool.shape[1], k_pool.shape[2]
    quant = k_scale is not None
    scale = hd ** -0.5

    # Scalar-prefetch operands (SMEM): layer index, block tables,
    # lengths. Index maps read them to route each grid cell's DMA to
    # the right physical block — sentinel (and any overflow) entries
    # clamp to physical block 0: a harmless fetch whose compute the
    # kernel skips (block start >= length).
    layer_arr = jnp.reshape(layer, (1,)).astype(jnp.int32)
    table = table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    def phys(tr, b, j):
        t = tr[b, j]
        return jnp.where(t >= n_blocks, 0, t)

    kv_spec = pl.BlockSpec(
        (1, 1, bl, 1, hd),
        lambda b, g, j, lr, tr, ln: (lr[0], phys(tr, b, j), 0, g, 0))
    in_specs = [
        pl.BlockSpec((1, 1, R, hd),
                     lambda b, g, j, lr, tr, ln: (b, g, 0, 0)),
        kv_spec, kv_spec,
    ]
    args = [q, k_pool, v_pool]
    if quant:
        sc_spec = pl.BlockSpec(
            (1, 1, 1, bl),
            lambda b, g, j, lr, tr, ln: (lr[0], phys(tr, b, j), g, 0))
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]

    out_spec = lambda b, g, j, lr, tr, ln: (b, g, 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, G, span_blocks),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, R, hd), out_spec),
            pl.BlockSpec((1, 1, R, LANES), out_spec),
            pl.BlockSpec((1, 1, R, LANES), out_spec),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, LANES), jnp.float32),
            pltpu.VMEM((R, LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, block_len=bl, span_blocks=span_blocks, scale=scale,
        quant=quant)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, G, R, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, G, R, LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, G, R, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(layer_arr, table, lengths, *args)
    # Stats are lane-replicated (the Mosaic tiling idiom); one lane is
    # the value.
    return acc, m[..., 0], l[..., 0]
