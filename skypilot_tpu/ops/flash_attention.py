"""Flash attention (FlashAttention-2) as Pallas TPU kernels.

Design (per the pallas TPU playbook):
* Grid (batch, heads, q-blocks); the KV sweep is a ``fori_loop`` inside
  the kernel with the online-softmax running max/sum carried in
  registers; accumulation in float32 scratch, output cast to the input
  dtype (bf16 on TPU -> MXU-native matmuls).
* Causal masking prunes whole KV blocks: q-block i only sweeps KV
  blocks 0..i, and only the diagonal block pays the element mask.
* Backward is the standard FA-2 split: a dKV kernel (grid over KV
  blocks, sweeping q-blocks >= diagonal) and a dQ kernel (grid over
  q-blocks, sweeping KV blocks <= diagonal), both recomputing P from
  the saved logsumexp instead of materializing S.

Used by ops.attention.gqa_attention on TPU for long sequences; the
einsum path remains the fallback (and the numerics oracle in tests,
which run this kernel with ``interpret=True`` on CPU).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
LANES = 128     # lane-replicated rowwise stats (Mosaic tiling)
SUBLANES = 8    # kv-side segment-id layout: [B, SUBLANES, S]
NEG_INF = -1e30


def _blocks(s: int, b: int) -> int:
    return (s + b - 1) // b


def _segment_mask(qseg_tile, kseg_ref, ki, block_k):
    """[Bq, Bk] same-segment mask.

    qseg_tile: [Bq, LANES] lane-replicated q segment ids;
    kseg_ref: [SUBLANES, S] ref with the seq dim in lanes (the official
    TPU layout trick — equality broadcasts [Bq, Bk] == [1, Bk] without
    any in-kernel transpose).
    """
    q_seg = jnp.tile(qseg_tile, (1, block_k // LANES))      # [Bq, Bk]
    k_seg = kseg_ref[:1, pl.ds(ki * block_k, block_k)]      # [1, Bk]
    return q_seg == k_seg


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                causal: bool, block_k: int, seq_len: int,
                qseg_ref=None, kseg_ref=None):
    qi = pl.program_id(2)
    block_q = q_ref.shape[0]
    q = q_ref[...].astype(jnp.float32) * scale  # [Bq, D]

    num_kv = _blocks(seq_len, block_k)
    if causal:
        # KV blocks strictly after this q block's end contribute nothing.
        num_kv_live = lax.div(qi * block_q + block_q - 1, block_k) + 1
    else:
        num_kv_live = num_kv

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [Bq, Bk]
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if qseg_ref is not None:
            s = jnp.where(_segment_mask(qseg_ref[...], kseg_ref, ki,
                                        block_k), s, NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q_ref.shape[1]
    init = (jnp.zeros((block_q, d), jnp.float32),
            jnp.full((block_q, 1), NEG_INF, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32))
    acc, m, l = lax.fori_loop(0, num_kv_live, body, init)
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    # Lane-replicated (Bq, 128) layout: Mosaic cannot tile 1-lane blocks.
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape)


def _seg_layouts(segments, s):
    """[B, S] int32 -> (q-side [B, S, LANES], kv-side [B, SUBLANES, S])."""
    qseg = jax.lax.broadcast_in_dim(
        segments, (segments.shape[0], s, LANES), (0, 1))
    kseg = jax.lax.broadcast_in_dim(
        segments, (segments.shape[0], SUBLANES, s), (0, 2))
    return qseg, kseg


def _fwd(q, k, v, segments, *, causal: bool, block_q: int, block_k: int,
         interpret: bool):
    """q,k,v: [B, H, S, D]; segments: [B, S] int32 or None
    -> (o [B,H,S,D], lse [B,H,S,LANES] f32)."""
    b, h, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (b, h, _blocks(s, block_q))
    qspec = pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0))
    kvspec = pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0))
    in_specs = [qspec, kvspec, kvspec]
    args = [q, k, v]
    if segments is not None:
        qseg, kseg = _seg_layouts(segments, s)
        in_specs += [
            pl.BlockSpec((1, block_q, LANES),
                         lambda bi, hi, qi: (bi, qi, 0)),
            pl.BlockSpec((1, SUBLANES, s), lambda bi, hi, qi: (bi, 0, 0)),
        ]
        args += [qseg, kseg]

    def kernel(q_ref, k_ref, v_ref, *rest):
        if segments is not None:
            qseg_ref, kseg_ref, o_ref, lse_ref = rest
            segrefs = dict(qseg_ref=qseg_ref.at[0],
                           kseg_ref=kseg_ref.at[0])
        else:
            o_ref, lse_ref = rest
            segrefs = {}
        _fwd_kernel(q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0],
                    o_ref.at[0, 0], lse_ref.at[0, 0],
                    scale=scale, causal=causal, block_k=block_k,
                    seq_len=s, **segrefs)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[qspec,
                   pl.BlockSpec((1, 1, block_q, LANES),
                                lambda bi, hi, qi: (bi, hi, qi, 0))],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32)],
        interpret=interpret,
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale: float, causal: bool,
                    block_q: int, seq_len: int,
                    qseg_ref=None, kseg_ref=None):
    ki = pl.program_id(2)
    block_k = k_ref.shape[0]
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    num_q = _blocks(seq_len, block_q)
    # Causal: q blocks before this KV block's start see nothing of it.
    q_start = lax.div(ki * block_k, block_q) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = jnp.max(lse_ref[pl.ds(qi * block_q, block_q), :], axis=1,
                      keepdims=True)
        delta = jnp.max(delta_ref[pl.ds(qi * block_q, block_q), :], axis=1,
                        keepdims=True)
        q = q.astype(jnp.float32) * scale
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, k.shape[0]), 0)
            k_pos = ki * k.shape[0] + lax.broadcasted_iota(
                jnp.int32, (block_q, k.shape[0]), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if qseg_ref is not None:
            # kseg_ref here is the ki-blocked tile [SUBLANES, Bk]: the
            # kv index inside _segment_mask must be 0.
            qs = qseg_ref[pl.ds(qi * block_q, block_q), :]
            s = jnp.where(_segment_mask(qs, kseg_ref, 0, block_k), s,
                          NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        do_f = do.astype(jnp.float32)
        dv_new = dv + jax.lax.dot_general(
            p, do_f, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # P^T dO
        dp = jax.lax.dot_general(do_f, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)  # [Bq, Bk]
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # dS^T q (already scaled)
        return dk_new, dv_new

    d = k.shape[1]
    init = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    dk, dv = lax.fori_loop(q_start, num_q, body, init)
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, scale: float, causal: bool, block_k: int,
                   seq_len: int, qseg_ref=None, kseg_ref=None):
    qi = pl.program_id(2)
    block_q = q_ref.shape[0]
    q = q_ref[...].astype(jnp.float32) * scale
    do = do_ref[...].astype(jnp.float32)
    lse = jnp.max(lse_ref[...], axis=1, keepdims=True)
    delta = jnp.max(delta_ref[...], axis=1, keepdims=True)
    num_kv = _blocks(seq_len, block_k)
    num_kv_live = (lax.div(qi * block_q + block_q - 1, block_k) + 1
                   if causal else num_kv)

    def body(ki, dq):
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        k = k.astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if qseg_ref is not None:
            s = jnp.where(_segment_mask(qseg_ref[...], kseg_ref, ki,
                                        block_k), s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, num_kv_live, body,
                       jnp.zeros((block_q, q.shape[1]), jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, segments, o, lse = residuals
    b, h, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    # delta = rowsum(dO * O): tiny elementwise+reduce, XLA fuses it well.
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,S,1]
    delta = jnp.broadcast_to(delta, (b, h, s, LANES))

    full = lambda bi, hi, i: (bi, hi, 0, 0)
    kv_blocked = pl.BlockSpec((1, 1, block_k, d),
                              lambda bi, hi, ki: (bi, hi, ki, 0))
    q_blocked = pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi: (bi, hi, qi, 0))
    seq_full_d = pl.BlockSpec((1, 1, s, d), full)
    seq_full_1 = pl.BlockSpec((1, 1, s, LANES), full)

    seg_args, dkv_seg_specs, dq_seg_specs = [], [], []
    if segments is not None:
        qseg, kseg = _seg_layouts(segments, s)
        seg_args = [qseg, kseg]
        dkv_seg_specs = [
            pl.BlockSpec((1, s, LANES), lambda bi, hi, ki: (bi, 0, 0)),
            pl.BlockSpec((1, SUBLANES, block_k),
                         lambda bi, hi, ki: (bi, 0, ki)),
        ]
        dq_seg_specs = [
            pl.BlockSpec((1, block_q, LANES),
                         lambda bi, hi, qi: (bi, qi, 0)),
            pl.BlockSpec((1, SUBLANES, s), lambda bi, hi, qi: (bi, 0, 0)),
        ]

    dkv_kernel = functools.partial(
        _pack_dkv, scale=scale, causal=causal, block_q=block_q, seq_len=s,
        with_segments=segments is not None)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, _blocks(s, block_k)),
        in_specs=[seq_full_d, kv_blocked, kv_blocked, seq_full_d,
                  seq_full_1, seq_full_1, *dkv_seg_specs],
        out_specs=[kv_blocked, kv_blocked],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=interpret,
    )(q, k, v, g, lse, delta, *seg_args)

    dq_kernel = functools.partial(
        _pack_dq, scale=scale, causal=causal, block_k=block_k, seq_len=s,
        with_segments=segments is not None)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, _blocks(s, block_q)),
        in_specs=[q_blocked, seq_full_d, seq_full_d, q_blocked,
                  pl.BlockSpec((1, 1, block_q, LANES),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
                  pl.BlockSpec((1, 1, block_q, LANES),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
                  *dq_seg_specs],
        out_specs=q_blocked,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta, *seg_args)
    return dq, dk, dv, None


def _split_seg_refs(rest, with_segments, kw):
    """Shared unpack for the optional trailing (qseg, kseg) refs: the
    segment refs, when present, precede the output refs in ``rest``."""
    if with_segments:
        qseg_ref, kseg_ref, *outs = rest
        kw = dict(kw, qseg_ref=qseg_ref.at[0], kseg_ref=kseg_ref.at[0])
        return outs, kw
    return list(rest), kw


def _pack_dkv(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
              with_segments, **kw):
    (dk_ref, dv_ref), kw = _split_seg_refs(rest, with_segments, kw)
    _bwd_dkv_kernel(q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0],
                    do_ref.at[0, 0], lse_ref.at[0, 0], delta_ref.at[0, 0],
                    dk_ref.at[0, 0], dv_ref.at[0, 0], **kw)


def _pack_dq(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
             with_segments, **kw):
    (dq_ref,), kw = _split_seg_refs(rest, with_segments, kw)
    _bwd_dq_kernel(q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0],
                   do_ref.at[0, 0], lse_ref.at[0, 0], delta_ref.at[0, 0],
                   dq_ref.at[0, 0], **kw)


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, segments, causal, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, segments, causal=causal, block_q=block_q,
                block_k=block_k, interpret=interpret)
    return o


def _flash_fwd(q, k, v, segments, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, segments, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret)
    return o, (q, k, v, segments, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    return _bwd(causal, block_q, block_k, interpret, residuals, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    segment_ids=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False):
    """q, k, v: [B, S, H, D] (same layout as ops.attention) -> [B, S, H, D].

    K/V must already be GQA-expanded to H heads (ops.attention does it).
    ``segment_ids`` [B, S] int32 enables packed-sequence masking (needs
    block_k to be a multiple of 128 for the lane-tiled compare).
    """
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must be divisible by block sizes "
                         f"({block_q}, {block_k})")
    if segment_ids is not None:
        if block_k % LANES:
            raise ValueError(
                f"segment masking needs the kv block to be a multiple "
                f"of {LANES} lanes; effective block_k is {block_k} "
                f"(seq len {s} — pad the sequence to a multiple of "
                f"{LANES})")
        segment_ids = segment_ids.astype(jnp.int32)
    # [B,S,H,D] -> [B,H,S,D] for the kernels.
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    o = _flash(qt, kt, vt, segment_ids, causal, block_q, block_k,
               interpret)
    return o.swapaxes(1, 2)
