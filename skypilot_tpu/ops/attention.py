"""Attention ops with TPU (Pallas flash) and XLA fallback paths.

The XLA path is a straightforward einsum softmax attention — XLA already
fuses the mask+softmax chain well on TPU for moderate sequence lengths.
The Pallas flash-attention kernel (``skypilot_tpu.ops.flash_attention``)
is used automatically on TPU backends for longer sequences where
materializing the [B, H, S, S] score tensor would blow HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Sequence length at which the Pallas kernel wins over plain XLA (the
# score tensor stops fitting comfortably in VMEM-friendly fusion sizes).
_FLASH_MIN_SEQ = 1024


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  segment_ids: jax.Array | None = None) -> jax.Array:
    """Reference einsum attention. q: [B, S, H, D]; k/v: [B, S, H, D].

    ``segment_ids`` [B, S] (0 = padding): packed-sequence masking —
    a position only attends within its own segment.
    """
    *_, d = q.shape
    scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -1e30)
    if segment_ids is not None:
        same = (segment_ids[:, None, :, None]
                == segment_ids[:, None, None, :])   # [B, 1, Sq, Sk]
        scores = jnp.where(same, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def gqa_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True, impl: str = "auto",
                  segment_ids: jax.Array | None = None) -> jax.Array:
    """Grouped-query attention. q: [B, S, Hq, D]; k/v: [B, S, Hkv, D].

    impl: "auto" | "flash" | "xla" (env override: SKYTPU_ATTN_IMPL).
    ``segment_ids`` [B, S] (packed sequences) is supported on both
    paths: the Pallas flash kernel masks segments in-block (lane-tiled
    compare), the XLA fallback masks on the materialized scores.
    """
    import os
    impl = os.environ.get("SKYTPU_ATTN_IMPL", impl)
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    seq = q.shape[1]
    # Mosaic tiling slices along head_dim: non-128-multiples fail at
    # COMPILE time (inside the enclosing jit, past the except below), so
    # they must be routed to XLA at trace time.
    head_dim_ok = q.shape[-1] % 128 == 0
    use_flash = (impl == "flash" or
                 (impl == "auto" and _on_tpu() and seq >= _FLASH_MIN_SEQ
                  and head_dim_ok))
    if use_flash:
        try:
            from skypilot_tpu.ops import flash_attention as fa
            return fa.flash_attention(q, k, v, causal=causal,
                                      segment_ids=segment_ids)
        except Exception:
            if impl == "flash":
                raise
    return xla_attention(q, k, v, causal=causal,
                         segment_ids=segment_ids)
