"""Cross-cloud bucket transfer (S3 -> GCS, GCS -> local, ...).

GCS-first: uses Google Storage Transfer Service for cloud-to-cloud
copies (the reference's mechanism) via the gcloud CLI, and
``gcloud storage cp/rsync`` for everything touching the local disk.
Command construction is pure; execution is injected for offline tests.

Reference parity: sky/data/data_transfer.py (GCS Transfer Service for
S3->GCS etc.; SURVEY.md §2.9).
"""

from __future__ import annotations

import shlex
import subprocess
from typing import Callable, Tuple

from skypilot_tpu import exceptions

RunFn = Callable[[str], Tuple[int, str]]


def _local_run(cmd: str) -> Tuple[int, str]:
    proc = subprocess.run(["bash", "-c", cmd], capture_output=True,
                          text=True)
    return proc.returncode, (proc.stdout or "") + (proc.stderr or "")


def s3_to_gcs_command(s3_bucket: str, gcs_bucket: str) -> str:
    """Storage Transfer Service job S3 -> GCS, blocking until the copy
    completes (create is async; monitor waits on it)."""
    import uuid
    job = f"skytpu-transfer-{uuid.uuid4().hex[:10]}"
    return ("gcloud transfer jobs create "
            f"s3://{shlex.quote(s3_bucket)} gs://{shlex.quote(gcs_bucket)} "
            f"--name={job} --source-auth-method=AWS_SIGNATURE_V4 && "
            f"gcloud transfer jobs monitor {job}")


def gcs_to_gcs_command(src_bucket: str, dst_bucket: str) -> str:
    return (f"gcloud storage rsync -r gs://{shlex.quote(src_bucket)} "
            f"gs://{shlex.quote(dst_bucket)}")


def local_to_gcs_command(local_path: str, gcs_url: str) -> str:
    return (f"gcloud storage rsync -r {shlex.quote(local_path)} "
            f"{shlex.quote(gcs_url)}")


def gcs_to_local_command(gcs_url: str, local_path: str) -> str:
    return (f"mkdir -p {shlex.quote(local_path)} && "
            f"gcloud storage rsync -r {shlex.quote(gcs_url)} "
            f"{shlex.quote(local_path)}")


def _scheme(url: str) -> str:
    return url.split("://", 1)[0] if "://" in url else "local"


def transfer(src: str, dst: str, run: RunFn = _local_run) -> None:
    """Copy src -> dst across any supported scheme pair.

    Supported pairs: s3->gs, gs->gs, local->gs, gs->local. Single
    local files use ``cp``; directories use ``rsync -r``.
    """
    import os
    s, d = _scheme(src), _scheme(dst)
    if s == "local":
        src = os.path.expanduser(src)
    if d == "local":
        dst = os.path.expanduser(dst)
    if (s, d) == ("s3", "gs"):
        cmd = s3_to_gcs_command(src.removeprefix("s3://"),
                                dst.removeprefix("gs://"))
    elif (s, d) == ("gs", "gs"):
        cmd = gcs_to_gcs_command(src.removeprefix("gs://"),
                                 dst.removeprefix("gs://"))
    elif (s, d) == ("local", "gs"):
        if os.path.isfile(src):
            cmd = (f"gcloud storage cp {shlex.quote(src)} "
                   f"{shlex.quote(dst)}")
        else:
            cmd = local_to_gcs_command(src, dst)
    elif (s, d) == ("gs", "local"):
        cmd = gcs_to_local_command(src, dst)
    else:
        raise exceptions.StorageError(
            f"unsupported transfer pair {s}->{d} ({src!r} -> {dst!r}); "
            f"supported: s3->gs, gs->gs, local->gs, gs->local")
    rc, out = run(cmd)
    if rc != 0:
        raise exceptions.StorageError(
            f"transfer {src} -> {dst} failed: {out.strip()[:400]}")
