"""Bucket storage (GCS-first). COPY/MOUNT lifecycle lands with the data
layer milestone; this module currently carries the backend-facing hook.

Reference parity target: sky/data/storage.py (Storage:468, GcsStore:1786)
+ mounting_utils (gcsfuse).
"""

from __future__ import annotations

from skypilot_tpu import exceptions


def mount_or_copy(handle, dst: str, src: str) -> None:
    raise exceptions.StorageError(
        f"bucket file mounts ({src} -> {dst}) require the storage layer; "
        f"not yet available in this build")
