"""Bucket storage lifecycle (GCS-first): create, sync-up, mount, delete.

Design: command construction is pure (offline-testable); execution is
injected — locally a subprocess runner, on-cluster the provisioner's
CommandRunners. MOUNT mode = gcsfuse (mounting_utils); COPY mode =
``gcloud storage rsync`` onto the host disk.

Reference parity: sky/data/storage.py (Storage:468 w/ StorageMode
MOUNT|COPY :237, AbstractStore:242, GcsStore:1786 — bucket lifecycle,
sync via gsutil rsync, persistent vs ephemeral) and
sky/data/data_utils.py (bucket URL parsing).
"""

from __future__ import annotations

import enum
import os
import shlex
import subprocess
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.data import mounting_utils, storage_utils

RunFn = Callable[[str], Tuple[int, str]]


def _local_run(cmd: str) -> Tuple[int, str]:
    proc = subprocess.run(["bash", "-c", cmd], capture_output=True,
                          text=True)
    return proc.returncode, (proc.stdout or "") + (proc.stderr or "")


class StorageMode(enum.Enum):
    MOUNT = "MOUNT"
    COPY = "COPY"


def split_bucket_url(url: str) -> Tuple[str, str]:
    """'gs://bucket/sub/path' -> ('bucket', 'sub/path')."""
    if "://" not in url:
        raise ValueError(f"not a bucket URL: {url!r}")
    rest = url.split("://", 1)[1]
    bucket, _, sub = rest.partition("/")
    if not bucket:
        raise ValueError(f"no bucket name in {url!r}")
    return bucket, sub


class AbstractStore:
    """One bucket (optionally a prefix within it) on one provider."""

    SCHEME = ""

    def __init__(self, name: str, run: RunFn = _local_run,
                 subpath: str = ""):
        self.name = name
        self.subpath = subpath.strip("/")
        self._run = run

    @property
    def url(self) -> str:
        base = f"{self.SCHEME}://{self.name}"
        return f"{base}/{self.subpath}" if self.subpath else base

    @classmethod
    def from_url(cls, bucket: str, sub: str,
                 run: RunFn = _local_run) -> "AbstractStore":
        """Build from the generic <scheme>://<bucket>/<sub> split.
        Region-qualified schemes (cos://<region>/<bucket>) override."""
        return cls(bucket, run, subpath=sub)

    def exists(self) -> bool:
        raise NotImplementedError

    def create(self, region: Optional[str] = None) -> None:
        raise NotImplementedError

    def upload(self, source: str, subpath: str = "") -> None:
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    def mount_command(self, mount_path: str) -> str:
        raise NotImplementedError

    def copy_down_command(self, destination: str) -> str:
        raise NotImplementedError


class GcsStore(AbstractStore):
    """GCS bucket via the gcloud storage CLI."""

    SCHEME = "gs"

    def exists(self) -> bool:
        rc, _ = self._run(
            f"gcloud storage buckets describe gs://{self.name} "
            f"--format='value(name)'")
        return rc == 0

    def create(self, region: Optional[str] = None) -> None:
        loc = f" --location={shlex.quote(region)}" if region else ""
        rc, out = self._run(
            f"gcloud storage buckets create gs://{self.name}{loc} "
            f"--uniform-bucket-level-access")
        if rc != 0 and "already" not in out.lower():
            raise exceptions.StorageError(
                f"creating gs://{self.name} failed: {out.strip()}")

    def upload(self, source: str, subpath: str = "") -> None:
        dst = f"gs://{self.name}/{subpath}" if subpath else f"gs://{self.name}"
        if os.path.isfile(os.path.expanduser(source)):
            # rsync requires directory sources; a single-file mount
            # (file_mounts: {~/cfg.json: ./cfg.json}) goes via cp, the
            # object keeping its basename under the subpath.
            rc, out = self._run(
                f"gcloud storage cp {shlex.quote(source)} {dst}/")
        else:
            excl = storage_utils.gsutil_exclude_regex(source)
            xflag = f" -x {shlex.quote(excl)}" if excl else ""
            rc, out = self._run(
                f"gcloud storage rsync -r{xflag} {shlex.quote(source)} "
                f"{dst}")
        if rc != 0:
            raise exceptions.StorageError(
                f"upload {source} -> {dst} failed: {out.strip()}")

    def delete(self) -> None:
        rc, out = self._run(f"gcloud storage rm -r gs://{self.name}")
        if rc != 0 and "not found" not in out.lower():
            raise exceptions.StorageError(
                f"deleting gs://{self.name} failed: {out.strip()}")

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_mount_with_install_cmd(
            self.name, mount_path, only_dir=self.subpath or None)

    def copy_down_command(self, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p {dst} && "
                f"gcloud storage rsync -r {self.url} {dst}")


class S3Store(AbstractStore):
    """S3 bucket via the aws CLI (reference: S3Store,
    sky/data/storage.py:1284). Under TPU scope, S3 is mostly a data
    SOURCE (file_mounts: s3://...), but the full lifecycle is supported
    for parity; MOUNT uses goofys like the reference."""

    SCHEME = "s3"

    def _aws(self) -> str:
        """The aws CLI invocation prefix (R2 adds endpoint/profile)."""
        return "aws"

    @property
    def cli_url(self) -> str:
        """The URL the aws CLI understands (always s3:// — the r2://
        scheme is this framework's naming, not the CLI's)."""
        base = f"s3://{self.name}"
        return f"{base}/{self.subpath}" if self.subpath else base

    def exists(self) -> bool:
        rc, _ = self._run(
            f"{self._aws()} s3api head-bucket --bucket {self.name}")
        return rc == 0

    def create(self, region: Optional[str] = None) -> None:
        loc = (f" --create-bucket-configuration "
               f"LocationConstraint={shlex.quote(region)}"
               if region and region != "us-east-1" else "")
        rc, out = self._run(
            f"{self._aws()} s3api create-bucket --bucket {self.name}{loc}")
        if rc != 0 and "alreadyownedbyyou" not in out.lower().replace(
                " ", ""):
            raise exceptions.StorageError(
                f"creating {self.url} failed: {out.strip()}")

    def upload(self, source: str, subpath: str = "") -> None:
        dst = (f"s3://{self.name}/{subpath}" if subpath
               else f"s3://{self.name}")
        if os.path.isfile(os.path.expanduser(source)):
            # s3 sync requires directory sources (see GcsStore.upload).
            rc, out = self._run(
                f"{self._aws()} s3 cp {shlex.quote(source)} {dst}/")
        else:
            excl = storage_utils.aws_exclude_args(source)
            rc, out = self._run(
                f"{self._aws()} s3 sync {excl}{shlex.quote(source)} {dst}")
        if rc != 0:
            # Report the store's own scheme (r2:// for R2) even though
            # the CLI destination is the s3:// form.
            shown = dst.replace("s3://", f"{self.SCHEME}://", 1)
            raise exceptions.StorageError(
                f"upload {source} -> {shown} failed: {out.strip()}")

    def delete(self) -> None:
        rc, out = self._run(
            f"{self._aws()} s3 rb s3://{self.name} --force")
        if rc != 0 and "nosuchbucket" not in out.lower().replace(" ", ""):
            raise exceptions.StorageError(
                f"deleting {self.url} failed: {out.strip()}")

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_s3_mount_cmd(
            self.name, mount_path, only_dir=self.subpath or None)

    def copy_down_command(self, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p {dst} && "
                f"{self._aws()} s3 sync {self.cli_url} {dst}")


def r2_endpoint() -> str:
    """Cloudflare R2 endpoint from env R2_ENDPOINT or config
    ``r2.endpoint`` (https://<account_id>.r2.cloudflarestorage.com)."""
    from skypilot_tpu import config as config_lib
    ep = (os.environ.get("R2_ENDPOINT")
          or config_lib.get_nested(("r2", "endpoint")))
    if not ep:
        raise exceptions.StorageError(
            "r2 storage needs the account endpoint: set R2_ENDPOINT or "
            "`r2.endpoint` in config "
            "(https://<account_id>.r2.cloudflarestorage.com)")
    return ep


def r2_profile() -> str:
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(("r2", "profile"), "r2")


def r2_aws_prefix() -> str:
    """The aws-CLI invocation prefix for R2 — single definition shared
    by the store lifecycle and the host-side fetch command builders."""
    return (f"aws --endpoint-url {shlex.quote(r2_endpoint())} "
            f"--profile {shlex.quote(r2_profile())}")


class R2Store(S3Store):
    """Cloudflare R2 bucket: the S3 API behind an account endpoint
    (reference: R2Store, sky/data/storage.py:3584 — aws CLI with
    --endpoint-url + a dedicated credentials profile). Hosts that pull
    r2:// sources need the same profile configured."""

    SCHEME = "r2"

    def _aws(self) -> str:
        return r2_aws_prefix()

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_s3_mount_cmd(
            self.name, mount_path, only_dir=self.subpath or None,
            endpoint=r2_endpoint(), profile=r2_profile())


def az_storage_prefix(sub: str, auth: bool = True) -> str:
    """``az storage <sub>`` invocation prefix — single definition
    shared by the store lifecycle and the host-side fetch builders.
    ``auth=False`` for the azcopy-backed data-plane commands (``blob
    sync``) that reject --auth-mode."""
    base = (f"az storage {sub} --account-name "
            f"{shlex.quote(azure_storage_account())}")
    return base + (" --auth-mode login" if auth else "")


def az_download_prefix_command(container: str, subpath: Optional[str],
                               destination: str) -> str:
    """Materialize a container (or a prefix of it) at ``destination``.

    ``blob download-batch`` recreates the blob's FULL virtual path
    under --destination (unlike gcloud/aws prefix syncs, which copy
    the prefix's contents) — so a subpath downloads into a temp dir
    and its contents move to the destination, keeping az:// COPY
    semantics identical to gs://s3://r2:// and to the blobfuse2
    --subdirectory MOUNT of the same URL.
    """
    dst = shlex.quote(destination)
    if not subpath:
        return (f"mkdir -p {dst} && "
                + az_storage_prefix("blob download-batch")
                + f" --source {shlex.quote(container)}"
                  f" --destination {dst}")
    sub = subpath.rstrip("/")
    # The pre-created $tmp/<sub> keeps an empty/missing prefix an empty
    # destination dir (gs/s3/r2 semantics) instead of a cp error —
    # download-batch exits 0 having matched nothing.
    return ("skytpu_tmp=$(mktemp -d) && "
            f"mkdir -p \"$skytpu_tmp\"/{shlex.quote(sub)} && "
            + az_storage_prefix("blob download-batch")
            + f" --source {shlex.quote(container)}"
              f" --destination \"$skytpu_tmp\""
              f" --pattern {shlex.quote(sub + '/*')} && "
              f"mkdir -p {dst} && "
              f"cp -a \"$skytpu_tmp\"/{shlex.quote(sub)}/. {dst}/ && "
              f"rm -rf \"$skytpu_tmp\"")


def azure_storage_account() -> str:
    """Azure storage account from env AZURE_STORAGE_ACCOUNT or config
    ``azure.storage_account`` (account names are globally unique; the
    reference derives one per user+region+subscription hash,
    sky/data/storage.py:2302 — here it is explicit config, like R2's
    endpoint)."""
    from skypilot_tpu import config as config_lib
    acct = (os.environ.get("AZURE_STORAGE_ACCOUNT")
            or config_lib.get_nested(("azure", "storage_account")))
    if not acct:
        raise exceptions.StorageError(
            "az:// storage needs the storage account: set "
            "AZURE_STORAGE_ACCOUNT or `azure.storage_account` in config")
    return acct


class AzureBlobStore(AbstractStore):
    """Azure Blob container via the az CLI (reference: AzureBlobStore,
    sky/data/storage.py:2293 — az/azcopy + blobfuse2 MOUNT). URLs are
    container-centric (``az://<container>[/subpath]``); the storage
    account comes from config. Hosts pulling az:// sources need an
    authenticated az CLI."""

    SCHEME = "az"

    def _cmd(self, sub: str, rest: str, auth: bool = True) -> str:
        return az_storage_prefix(sub, auth) + " " + rest

    def exists(self) -> bool:
        rc, out = self._run(self._cmd(
            "container exists", f"--name {self.name} -o tsv "
            f"--query exists"))
        return rc == 0 and "true" in out.lower()

    def create(self, region: Optional[str] = None) -> None:
        # Containers live in the (pre-existing) storage account; the
        # account pins the region, so ``region`` is advisory here.
        rc, out = self._run(self._cmd("container create",
                                      f"--name {self.name}"))
        if rc != 0 and "alreadyexists" not in out.lower().replace(" ", ""):
            raise exceptions.StorageError(
                f"creating {self.url} failed: {out.strip()}")

    def upload(self, source: str, subpath: str = "") -> None:
        if os.path.isfile(os.path.expanduser(source)):
            blob = (f"{subpath}/{os.path.basename(source.rstrip('/'))}"
                    if subpath else os.path.basename(source.rstrip("/")))
            rc, out = self._run(self._cmd(
                "blob upload", f"--container-name {self.name} "
                f"--file {shlex.quote(source)} "
                f"--name {shlex.quote(blob)} --overwrite"))
        else:
            # azcopy-backed `blob sync`: destination flag is -d, and
            # --auth-mode is not accepted.
            dest = (f" -d {shlex.quote(subpath)}" if subpath else "")
            rc, out = self._run(self._cmd(
                "blob sync", f"--container {self.name} "
                f"--source {shlex.quote(source)}{dest}", auth=False))
        if rc != 0:
            raise exceptions.StorageError(
                f"upload {source} -> {self.url} failed: {out.strip()}")

    def delete(self) -> None:
        rc, out = self._run(self._cmd("container delete",
                                      f"--name {self.name}"))
        if rc != 0 and "notfound" not in out.lower().replace(" ", ""):
            raise exceptions.StorageError(
                f"deleting {self.url} failed: {out.strip()}")

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_az_mount_cmd(
            azure_storage_account(), self.name, mount_path,
            only_dir=self.subpath or None)

    def copy_down_command(self, destination: str) -> str:
        return az_download_prefix_command(self.name, self.subpath,
                                          destination)


def cos_profile() -> str:
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(("cos", "profile"), "ibm")


def cos_endpoint(region: str) -> str:
    """IBM COS regional public endpoint (the S3-compatible API host)."""
    return f"https://s3.{region}.cloud-object-storage.appdomain.cloud"


def cos_aws_prefix(region: str) -> str:
    return (f"aws --endpoint-url {shlex.quote(cos_endpoint(region))} "
            f"--profile {shlex.quote(cos_profile())}")


class IbmCosStore(S3Store):
    """IBM Cloud Object Storage via its S3-compatible API (reference:
    IBMCosStore, sky/data/storage.py:3584 — ibm_boto3 + rclone; here
    the same aws-CLI-with-endpoint pattern as R2, with HMAC credentials
    in a dedicated profile). URLs are region-qualified like the
    reference's: ``cos://<region>/<bucket>[/subpath]``."""

    SCHEME = "cos"

    def __init__(self, name: str, run: RunFn = _local_run,
                 subpath: str = "", region: str = "us-south"):
        super().__init__(name, run, subpath=subpath)
        self.region = region

    @classmethod
    def from_url(cls, bucket: str, sub: str,
                 run: RunFn = _local_run) -> "IbmCosStore":
        # cos:// URLs carry the region first: the generic split put it
        # in ``bucket`` and the real bucket at the head of ``sub``.
        real_bucket, _, subpath = sub.partition("/")
        if not real_bucket:
            raise exceptions.StorageError(
                f"cos URLs are cos://<region>/<bucket>[/path] "
                f"(got cos://{bucket})")
        return cls(real_bucket, run, subpath=subpath, region=bucket)

    @property
    def url(self) -> str:
        base = f"cos://{self.region}/{self.name}"
        return f"{base}/{self.subpath}" if self.subpath else base

    def _aws(self) -> str:
        return cos_aws_prefix(self.region)

    def create(self, region: Optional[str] = None) -> None:
        # COS regions ride the ENDPOINT (s3.<region>....); re-pin the
        # store's region so a named store created via
        # sync_up(region=...) doesn't create against the default
        # endpoint with a mismatched LocationConstraint.
        if region:
            self.region = region
        rc, out = self._run(
            f"{self._aws()} s3api create-bucket --bucket {self.name}")
        if rc != 0 and "alreadyownedbyyou" not in out.lower().replace(
                " ", ""):
            raise exceptions.StorageError(
                f"creating {self.url} failed: {out.strip()}")

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_s3_mount_cmd(
            self.name, mount_path, only_dir=self.subpath or None,
            endpoint=cos_endpoint(self.region), profile=cos_profile())


def oci_namespace_region() -> Tuple[str, str]:
    """OCI Object Storage (namespace, region) from env OCI_NAMESPACE/
    OCI_REGION or config ``oci.namespace``/``oci.region`` — both are
    needed to form the S3-compatibility endpoint."""
    from skypilot_tpu import config as config_lib
    ns = (os.environ.get("OCI_NAMESPACE")
          or config_lib.get_nested(("oci", "namespace")))
    region = (os.environ.get("OCI_REGION")
              or config_lib.get_nested(("oci", "region")))
    if not (ns and region):
        raise exceptions.StorageError(
            "oci:// storage needs the tenancy namespace and region: set "
            "OCI_NAMESPACE/OCI_REGION or `oci.namespace`/`oci.region` "
            "in config")
    return ns, region


def oci_profile() -> str:
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(("oci", "profile"), "oci")


def oci_endpoint() -> str:
    ns, region = oci_namespace_region()
    return f"https://{ns}.compat.objectstorage.{region}.oraclecloud.com"


def oci_aws_prefix() -> str:
    return (f"aws --endpoint-url {shlex.quote(oci_endpoint())} "
            f"--profile {shlex.quote(oci_profile())}")


class OciStore(S3Store):
    """OCI Object Storage via its S3 Compatibility API (reference:
    OciStore, sky/data/storage.py:4037 — oci SDK; here the namespace-
    qualified compat endpoint with the aws CLI, zero-SDK like every
    other store). URLs: ``oci://<bucket>[/subpath]``."""

    SCHEME = "oci"

    def _aws(self) -> str:
        return oci_aws_prefix()

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.get_s3_mount_cmd(
            self.name, mount_path, only_dir=self.subpath or None,
            endpoint=oci_endpoint(), profile=oci_profile())


_STORE_TYPES: Dict[str, type] = {"gs": GcsStore, "s3": S3Store,
                                 "r2": R2Store, "az": AzureBlobStore,
                                 "cos": IbmCosStore, "oci": OciStore}


class Storage:
    """A named storage object: optional local source + bucket store(s).

    YAML form (under ``storage_mounts``)::

        /outputs:
          name: my-train-outputs
          store: gs
          mode: MOUNT
          persistent: true
        /data:
          source: gs://my-dataset     # pre-existing bucket
          mode: COPY
    """

    def __init__(self, name: Optional[str] = None,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 store: str = "gs", persistent: bool = True,
                 run: Optional[RunFn] = None):
        run = run if run is not None else _local_run
        if name is None and source is None:
            raise exceptions.StorageError(
                "storage needs a `name` (new bucket) or `source` "
                "(existing bucket or local path)")
        self.mode = mode
        self.persistent = persistent
        self.source = source
        self._run = run
        if source and "://" in source:
            bucket, sub = split_bucket_url(source)
            scheme = source.split("://", 1)[0]
            if scheme not in _STORE_TYPES:
                raise exceptions.StorageError(
                    f"unsupported store scheme {scheme!r}")
            self.store: AbstractStore = _STORE_TYPES[scheme].from_url(
                bucket, sub, run)
            self.name = name or self.store.name
            self._external = True
        else:
            if name is None:
                raise exceptions.StorageError(
                    f"local source {source!r} needs a bucket `name`")
            self.name = name
            self.store = _STORE_TYPES[store](name, run)
            self._external = False

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         run: Optional[RunFn] = None) -> "Storage":
        config = dict(config or {})
        mode = StorageMode(config.pop("mode", "MOUNT").upper())
        obj = cls(name=config.pop("name", None),
                  source=config.pop("source", None), mode=mode,
                  store=config.pop("store", "gs"),
                  persistent=config.pop("persistent", True), run=run)
        if config:
            raise exceptions.StorageError(
                f"unknown storage fields: {sorted(config)}")
        return obj

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"mode": self.mode.value,
                               "persistent": self.persistent}
        if self._external:
            out["source"] = self.source
        else:
            out["name"] = self.name
            if self.store.SCHEME != "gs":
                out["store"] = self.store.SCHEME
            if self.source:
                out["source"] = self.source
        return out

    # -- lifecycle ---------------------------------------------------------

    def sync_up(self, region: Optional[str] = None) -> None:
        """Ensure the bucket exists; upload the local source if any."""
        if self._external:
            return
        if not self.store.exists():
            self.store.create(region)
        if self.source:
            self.store.upload(self.source)

    def upload_subpath(self, source: str, subpath: str) -> None:
        """Upload one local dir/file under a bucket subpath (controller
        file-mount translation, reference controller_utils.py:696)."""
        if not self.store.exists():
            self.store.create()
        self.store.upload(source, subpath)

    def attach_commands(self, mount_path: str) -> List[str]:
        """Commands to run on every cluster host to make this storage
        visible at ``mount_path``."""
        if self.mode == StorageMode.MOUNT:
            return [self.store.mount_command(mount_path)]
        return [self.store.copy_down_command(mount_path)]

    def delete(self) -> None:
        if self.persistent or self._external:
            return
        self.store.delete()


# ---------------------------------------------------------------------------
# Backend hook: bucket-URL file mounts (COPY semantics, like the
# reference's file_mounts with a bucket source).
# ---------------------------------------------------------------------------

def mount_or_copy(handle, dst: str, src: str) -> None:
    from skypilot_tpu import provision
    from skypilot_tpu.data import cloud_stores
    info = provision.get_cluster_info(handle.provider, handle.cluster_name,
                                      handle.zone)
    store = cloud_stores.get_storage_from_path(src)
    scheme = src.split("://", 1)[0]
    if scheme in ("http", "https"):
        # An http(s) source is always a single file.
        cmd = store.make_sync_file_command(src, dst)
    else:
        # Bucket roots are always directories; for subpaths the
        # object-vs-prefix decision is made authoritatively on the
        # cluster host (URL guessing silently produced empty dirs for
        # extensionless single files).
        _, sub = split_bucket_url(src)
        cmd = (store.make_sync_auto_command(src.rstrip("/"), dst) if sub
               else store.make_sync_dir_command(src, dst))
    for runner in provision.get_command_runners(info):
        rc, out, err = runner.run(cmd)
        if rc != 0:
            raise exceptions.StorageError(
                f"materializing {src} -> {dst} failed on host "
                f"{runner.host_id}: {out}{err}")
