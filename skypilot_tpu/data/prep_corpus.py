"""Corpus prep CLI: tokenized documents -> packed train shards.

The data-prep step of a training pipeline (llm/pipeline-qlora-serve.
yaml): reads token documents, packs them into fixed [rows, seq]
buffers with segment ids (native packer when built — see
input_pipeline.pack), and writes .npz shards that train/eval consume
with --packed.

Input formats:
  * ``.jsonl``  — one JSON array of token ids per line
  * ``.npy``    — object array of int arrays
  * ``synthetic:N`` — N synthetic documents (demos/tests)

Run:  python -m skypilot_tpu.data.prep_corpus \
          --input corpus.jsonl --seq 2048 --rows 64 --out /artifacts/packed
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

from skypilot_tpu.data import input_pipeline


def _read_docs(path: str, vocab_size: int, seq: int):
    if path.startswith("synthetic:"):
        n = int(path.split(":", 1)[1])
        yield from input_pipeline.synthetic_doc_stream(
            n, vocab_size, mean_len=max(seq // 3, 16), seed=0)
        return
    if path.endswith(".npy"):
        for doc in np.load(path, allow_pickle=True):
            yield np.asarray(doc, np.int32)
        return
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield np.asarray(json.loads(line), np.int32)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True,
                    help=".jsonl / .npy of token docs, or synthetic:N")
    ap.add_argument("--out", required=True,
                    help="output directory for packed-XXXXX.npz shards")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--rows", type=int, default=64,
                    help="rows per shard (one shard = one .npz)")
    ap.add_argument("--vocab-size", type=int, default=128256,
                    help="only used by synthetic: inputs")
    ap.add_argument("--pad-id", type=int, default=0)
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    docs = _read_docs(args.input, args.vocab_size, args.seq)
    n_shards = 0
    n_tokens = 0
    for batch in input_pipeline.packed_batches(
            docs, args.rows, args.seq, pad_id=args.pad_id):
        shard = os.path.join(args.out, f"packed-{n_shards:05d}.npz")
        np.savez(shard, **batch)
        n_tokens += int(batch["mask"].sum())
        n_shards += 1
    meta = {"shards": n_shards, "tokens": n_tokens, "seq": args.seq,
            "rows": args.rows,
            "native_packer": input_pipeline._load_native() is not None}
    with open(os.path.join(args.out, "META.json"), "w") as f:
        json.dump(meta, f)
    print(json.dumps(meta), file=sys.stdout, flush=True)


if __name__ == "__main__":
    main()
