"""CloudStorage adapters: scheme -> fetch/sync command builders.

Used by the backend to materialize ``gs://`` / ``https://`` file-mount
sources on cluster hosts. Pure command construction (offline-testable);
execution goes through CommandRunners.

Reference parity: sky/cloud_stores.py (CloudStorage adapters for
file_mounts from s3://, gs://, https://).
"""

from __future__ import annotations

import shlex
from typing import Dict


def _probe_then_dispatch(probe_cmd: str, not_found_regex: str,
                         file_cmd: str, dir_cmd: str) -> str:
    """Shared probe scaffolding for make_sync_auto_command: object
    exists -> file copy; definitive not-found -> prefix sync (which
    exits 0 even for an empty prefix); any other probe failure — auth
    hiccup, metadata-server timeout — fails loudly, or a single-file
    mount would silently materialize as an empty directory."""
    return (f"skytpu_probe=$({probe_cmd} 2>&1); skytpu_rc=$?; "
            f"if [ $skytpu_rc -eq 0 ]; then {file_cmd}; "
            f"elif printf %s \"$skytpu_probe\" | "
            f"grep -qiE '{not_found_regex}'; then {dir_cmd}; "
            f"else printf %s \"$skytpu_probe\" >&2; exit 1; fi")


class CloudStorage:
    """Command builders for one URL scheme."""

    def is_directory(self, url: str) -> bool:
        raise NotImplementedError

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        raise NotImplementedError

    def make_sync_file_command(self, source: str, destination: str) -> str:
        raise NotImplementedError

    def make_sync_auto_command(self, source: str, destination: str) -> str:
        """Single file OR directory prefix, decided host-side.

        A URL alone cannot say whether ``gs://b/sub/name`` is one object
        or a prefix (dot-in-basename guessing silently materializes an
        empty dir for extensionless files), so the generated command
        probes the object authoritatively on the cluster and picks
        cp vs rsync there.
        """
        raise NotImplementedError


class GcsCloudStorage(CloudStorage):
    """gs:// via the gcloud storage CLI (preinstalled on TPU-VMs)."""

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p {dst} && "
                f"gcloud storage rsync -r {shlex.quote(source)} {dst}")

    def make_sync_file_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p $(dirname {dst}) && "
                f"gcloud storage cp {shlex.quote(source)} {dst}")

    def make_sync_auto_command(self, source: str, destination: str) -> str:
        return _probe_then_dispatch(
            f"gcloud storage objects describe {shlex.quote(source)}",
            "not found|no such object|404",
            self.make_sync_file_command(source, destination),
            self.make_sync_dir_command(source, destination))


class S3CloudStorage(CloudStorage):
    """s3:// via the aws CLI (file_mounts with S3 sources pull directly
    on the host; reference: sky/cloud_stores.py S3CloudStorage).
    S3-compatible stores (R2) override the two hooks below."""

    SCHEME = "s3"

    def _aws(self) -> str:
        return "aws"

    def _cli_url(self, url: str) -> str:
        """The URL the aws CLI understands (s3:// always)."""
        return "s3://" + url.removeprefix(f"{self.SCHEME}://")

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p {dst} && {self._aws()} s3 sync "
                f"{shlex.quote(self._cli_url(source))} {dst}")

    def make_sync_file_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p $(dirname {dst}) && {self._aws()} s3 cp "
                f"{shlex.quote(self._cli_url(source))} {dst}")

    def make_sync_auto_command(self, source: str, destination: str) -> str:
        bucket, _, key = source[len(f"{self.SCHEME}://"):].partition("/")
        if not key:
            # A bucket root is always a directory; probing it would run
            # head-object with an empty --key (parameter error).
            return self.make_sync_dir_command(source, destination)
        return _probe_then_dispatch(
            f"{self._aws()} s3api head-object "
            f"--bucket {shlex.quote(bucket)} --key {shlex.quote(key)}",
            "not found|404",
            self.make_sync_file_command(source, destination),
            self.make_sync_dir_command(source, destination))


class R2CloudStorage(S3CloudStorage):
    """r2:// via the aws CLI against the Cloudflare R2 endpoint.

    The endpoint/profile are baked into the generated command (built
    client-side from config); the executing host needs the same aws
    credentials profile. URLs are rewritten r2:// -> s3:// for the CLI
    (the _cli_url hook); everything else is the S3 builders.
    """

    SCHEME = "r2"

    def _aws(self) -> str:
        from skypilot_tpu.data import storage as storage_lib
        return storage_lib.r2_aws_prefix()


class AzureCloudStorage(CloudStorage):
    """az:// (container-centric) via the az CLI. The storage account is
    baked into the generated command from config; executing hosts need
    an authenticated az CLI (managed identity or az login)."""

    @staticmethod
    def _split(url: str):
        container, _, key = url[len("az://"):].partition("/")
        return container, key

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        from skypilot_tpu.data import storage as storage_lib
        container, key = self._split(source)
        return storage_lib.az_download_prefix_command(
            container, key or None, destination)

    def make_sync_file_command(self, source: str, destination: str) -> str:
        from skypilot_tpu.data import storage as storage_lib
        container, key = self._split(source)
        dst = shlex.quote(destination)
        return (f"mkdir -p $(dirname {dst}) && "
                + storage_lib.az_storage_prefix("blob download")
                + f" --container-name {shlex.quote(container)} "
                  f"--name {shlex.quote(key)} --file {dst}")

    def make_sync_auto_command(self, source: str, destination: str) -> str:
        # `az storage blob exists` exits 0 either way and answers on
        # stdout — the dispatch reads the answer, and any CLI failure
        # (auth, network) stays a loud non-zero exit.
        from skypilot_tpu.data import storage as storage_lib
        container, key = self._split(source)
        probe = (storage_lib.az_storage_prefix("blob exists")
                 + f" --container-name {shlex.quote(container)} "
                   f"--name {shlex.quote(key)} --query exists -o tsv")
        return (f"skytpu_probe=$({probe} 2>&1); skytpu_rc=$?; "
                f"if [ $skytpu_rc -ne 0 ]; then "
                f"printf %s \"$skytpu_probe\" >&2; exit 1; "
                f"elif printf %s \"$skytpu_probe\" | grep -qi true; then "
                f"{self.make_sync_file_command(source, destination)}; "
                f"else {self.make_sync_dir_command(source, destination)};"
                f" fi")


class HttpCloudStorage(CloudStorage):
    """https:// single-file fetch via curl."""

    def make_sync_file_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p $(dirname {dst}) && "
                f"curl -fsSL {shlex.quote(source)} -o {dst}")

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        raise ValueError(f"https source {source} must be a single file")


class IbmCosCloudStorage(S3CloudStorage):
    """cos://<region>/<bucket>/... via the aws CLI against the IBM COS
    regional S3-compatibility endpoint (region rides in the URL)."""

    SCHEME = "cos"

    def _split(self, url: str):
        rest = url.removeprefix("cos://")
        region, _, bucket_path = rest.partition("/")
        return region, bucket_path

    def _aws_for(self, url: str) -> str:
        from skypilot_tpu.data import storage as storage_lib
        return storage_lib.cos_aws_prefix(self._split(url)[0])

    # The region-qualified URL makes the prefix source-dependent, so
    # the builders re-dispatch through _aws_for instead of _aws().
    def make_sync_dir_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p {dst} && {self._aws_for(source)} s3 sync "
                f"{shlex.quote(self._cli_url(source))} {dst}")

    def make_sync_file_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f"mkdir -p $(dirname {dst}) && {self._aws_for(source)} "
                f"s3 cp {shlex.quote(self._cli_url(source))} {dst}")

    def make_sync_auto_command(self, source: str, destination: str) -> str:
        bucket, _, key = self._split(source)[1].partition("/")
        if not key:   # cos://<region>/<bucket> — a bucket root is a dir
            return self.make_sync_dir_command(source, destination)
        return _probe_then_dispatch(
            f"{self._aws_for(source)} s3api head-object "
            f"--bucket {shlex.quote(bucket)} --key {shlex.quote(key)}",
            "not found|404",
            self.make_sync_file_command(source, destination),
            self.make_sync_dir_command(source, destination))

    def _cli_url(self, url: str) -> str:
        return "s3://" + self._split(url)[1]


class OciCloudStorage(S3CloudStorage):
    """oci:// via the aws CLI against the OCI S3-compatibility endpoint
    (namespace + region from config, like R2's account endpoint)."""

    SCHEME = "oci"

    def _aws(self) -> str:
        from skypilot_tpu.data import storage as storage_lib
        return storage_lib.oci_aws_prefix()


_REGISTRY: Dict[str, CloudStorage] = {
    "gs": GcsCloudStorage(),
    "s3": S3CloudStorage(),
    "r2": R2CloudStorage(),
    "az": AzureCloudStorage(),
    "cos": IbmCosCloudStorage(),
    "oci": OciCloudStorage(),
    "https": HttpCloudStorage(),
    "http": HttpCloudStorage(),
}


# "<scheme>://" prefixes that mark a source as remote (vs a local path
# to upload) — DERIVED from the registry, so a newly registered scheme
# can never silently be treated as a local file by callers.
REMOTE_URL_PREFIXES = tuple(f"{s}://" for s in _REGISTRY)

# Bucket-store subset (excludes plain http(s) file fetches): what the
# backend can FUSE-mount / prefix-sync.
BUCKET_URL_PREFIXES = tuple(f"{s}://" for s in _REGISTRY
                            if s not in ("http", "https"))


def get_storage_from_path(url: str) -> CloudStorage:
    scheme = url.split("://", 1)[0]
    if scheme not in _REGISTRY:
        raise ValueError(
            f"unsupported storage scheme {scheme!r} in {url!r}; "
            f"supported: {sorted(_REGISTRY)}")
    return _REGISTRY[scheme]
