"""FUSE mount command generation (gcsfuse-first).

Pure command-string construction — execution happens on cluster hosts
via CommandRunners, so everything here is offline-testable.

Reference parity: sky/data/mounting_utils.py (goofys/gcsfuse/blobfuse2
command builders, :26-45). GCS is the TPU-native first-class store;
other protocols raise until their stores land.
"""

from __future__ import annotations

import shlex

GCSFUSE_VERSION = "2.4.0"

# Installed on TPU-VM images already in most cases; this is the fallback.
GCSFUSE_INSTALL_CMD = (
    "which gcsfuse >/dev/null 2>&1 || ("
    "curl -fsSL https://github.com/GoogleCloudPlatform/gcsfuse/releases/"
    f"download/v{GCSFUSE_VERSION}/gcsfuse_{GCSFUSE_VERSION}_amd64.deb "
    "-o /tmp/gcsfuse.deb && sudo dpkg -i /tmp/gcsfuse.deb)")


def get_mount_cmd(bucket: str, mount_path: str,
                  readonly: bool = False,
                  only_dir: str | None = None) -> str:
    """gcsfuse mount command for ``gs://bucket`` at ``mount_path``.

    ``only_dir`` restricts the mount to a prefix within the bucket
    (gs://bucket/sub -> pass only_dir='sub')."""
    bucket = bucket.removeprefix("gs://").split("/", 1)[0]
    opts = [
        "--implicit-dirs",
        # Checkpoint-oriented tuning: large sequential writes (Orbax
        # shard streams) want big write buffers and no type caching.
        "--stat-cache-ttl 10s",
        "--type-cache-ttl 10s",
        "--rename-dir-limit 10000",
    ]
    if only_dir:
        opts.append(f"--only-dir {shlex.quote(only_dir)}")
    if readonly:
        opts.append("-o ro")
    return (f"mkdir -p {shlex.quote(mount_path)} && "
            f"gcsfuse {' '.join(opts)} {shlex.quote(bucket)} "
            f"{shlex.quote(mount_path)}")


def get_umount_cmd(mount_path: str) -> str:
    return (f"fusermount -u {shlex.quote(mount_path)} 2>/dev/null || "
            f"sudo umount -l {shlex.quote(mount_path)} 2>/dev/null || true")


def get_mount_with_install_cmd(bucket: str, mount_path: str,
                               readonly: bool = False,
                               only_dir: str | None = None) -> str:
    return (f"({GCSFUSE_INSTALL_CMD}) && "
            f"{get_mount_cmd(bucket, mount_path, readonly, only_dir)}")


# goofys: the reference's S3 FUSE tool (sky/data/mounting_utils.py:26).
GOOFYS_INSTALL_CMD = (
    "command -v goofys >/dev/null || "
    "(sudo wget -q https://github.com/kahing/goofys/releases/latest/"
    "download/goofys -O /usr/local/bin/goofys && "
    "sudo chmod +x /usr/local/bin/goofys)")


def get_s3_mount_cmd(bucket: str, mount_path: str,
                     only_dir: str | None = None,
                     endpoint: str | None = None,
                     profile: str | None = None) -> str:
    """Mount an S3-API bucket with goofys (install if missing).
    ``endpoint``/``profile`` cover S3-compatible stores (Cloudflare
    R2)."""
    bucket = bucket.removeprefix("s3://").split("/", 1)[0]
    target = f"{bucket}:{only_dir}" if only_dir else bucket
    flags = ""
    if endpoint:
        flags += f" --endpoint {shlex.quote(endpoint)}"
    if profile:
        flags += f" --profile {shlex.quote(profile)}"
    return (f"({GOOFYS_INSTALL_CMD}) && "
            f"mkdir -p {shlex.quote(mount_path)} && "
            f"goofys{flags} {shlex.quote(target)} "
            f"{shlex.quote(mount_path)}")


BLOBFUSE2_INSTALL_CMD = (
    "which blobfuse2 >/dev/null 2>&1 || ("
    "sudo apt-get update -qq && sudo apt-get install -y blobfuse2 "
    "2>/dev/null) || ("
    "sudo wget -q https://github.com/Azure/azure-storage-"
    "fuse/releases/download/blobfuse2-2.3.2/blobfuse2-2.3.2-Debian-11.0."
    "x86_64.deb -O /tmp/blobfuse2.deb && sudo dpkg -i /tmp/blobfuse2.deb)")


def get_az_mount_cmd(account: str, container: str, mount_path: str,
                     only_dir: str | None = None) -> str:
    """Mount an Azure Blob container with blobfuse2 (reference:
    sky/data/mounting_utils.py blobfuse2 builders). Auth rides the
    host's managed identity / az login (AZURE_STORAGE_ACCOUNT env)."""
    sub = (f" --subdirectory={shlex.quote(only_dir.rstrip('/') + '/')}"
           if only_dir else "")
    return (f"({BLOBFUSE2_INSTALL_CMD}) && "
            f"mkdir -p {shlex.quote(mount_path)} && "
            f"AZURE_STORAGE_ACCOUNT={shlex.quote(account)} "
            f"AZURE_STORAGE_AUTH_TYPE=azcli "
            f"blobfuse2 mount {shlex.quote(mount_path)} "
            f"--container-name={shlex.quote(container)}{sub} "
            f"--tmp-path=/tmp/blobfuse2-{shlex.quote(container)}")
