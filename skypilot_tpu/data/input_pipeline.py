"""Training input pipeline: sequence packing + host-side prefetch.

Tokenized documents are packed into fixed ``[batch, seq]`` buffers with
*segment ids* (1-based per document, 0 = padding) and *restart
positions* (rope positions reset per document), so short documents
never waste MXU cycles on padding and packed documents cannot attend
across boundaries (ops.attention masks on segment ids).

The packing hot loop is native C++ (native/packer.cc, loaded via
ctypes, built on demand with g++) with a pure-numpy fallback — same
split the reference makes for its performance-critical host paths
(reference: SURVEY.md §0, third-party native data movers).

``prefetch`` overlaps host packing with device compute via a
double-buffered background thread (the standard TPU input recipe).
"""

from __future__ import annotations

import ctypes
import os
import queue
import subprocess
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build",
                         "libskytpu_packer.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native packer; None on failure."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not os.path.exists(_LIB_PATH):
            subprocess.run(["make", "-C",
                            os.path.join(_REPO_ROOT, "native")],
                           capture_output=True, timeout=120, check=True)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.pack_documents.restype = ctypes.c_int64
        lib.pack_documents.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ]
        _lib = lib
    except Exception:  # noqa: BLE001 — fall back to numpy
        _lib = None
    return _lib


def _pack_numpy(docs: List[np.ndarray], rows: int, cols: int,
                pad_id: int):
    tokens = np.full((rows, cols), pad_id, np.int32)
    segments = np.zeros((rows, cols), np.int32)
    positions = np.zeros((rows, cols), np.int32)
    used = [0] * rows
    next_seg = [1] * rows
    placed = 0
    for doc in docs:
        n = len(doc)
        if n > cols:
            placed += 1        # consumed (caller should pre-chunk)
            continue
        row = next((r for r in range(rows) if cols - used[r] >= n), -1)
        if row < 0:
            break
        tokens[row, used[row]:used[row] + n] = doc
        segments[row, used[row]:used[row] + n] = next_seg[row]
        positions[row, used[row]:used[row] + n] = np.arange(n)
        next_seg[row] += 1
        used[row] += n
        placed += 1
    return tokens, segments, positions, placed


def pack(docs: Sequence[Sequence[int]], rows: int, cols: int,
         pad_id: int = 0, force_numpy: bool = False):
    """Pack documents -> (tokens, segment_ids, positions, n_placed).

    ``n_placed`` counts consumed documents; the caller carries
    ``docs[n_placed:]`` into the next batch.
    """
    np_docs = [np.asarray(d, np.int32) for d in docs]
    lib = None if force_numpy else _load_native()
    if lib is None:
        return _pack_numpy(np_docs, rows, cols, pad_id)
    flat = (np.concatenate(np_docs) if np_docs
            else np.zeros((0,), np.int32))
    lens = np.asarray([len(d) for d in np_docs], np.int64)
    tokens = np.full((rows, cols), pad_id, np.int32)
    segments = np.zeros((rows, cols), np.int32)
    positions = np.zeros((rows, cols), np.int32)
    placed = lib.pack_documents(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(np_docs),
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        segments.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        positions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rows, cols, pad_id)
    return tokens, segments, positions, int(placed)


def packed_batches(doc_stream: Iterable[Sequence[int]], batch: int,
                   seq: int, pad_id: int = 0,
                   force_numpy: bool = False) -> Iterator[Dict[str, np.ndarray]]:
    """Stream documents -> packed train batches.

    Yields {"tokens", "segment_ids", "positions", "mask"} with
    mask = (segment_ids > 0) as the loss mask.
    """
    pending: List[Sequence[int]] = []
    it = iter(doc_stream)
    exhausted = False
    while not exhausted or pending:
        # Greedy fill: pull enough docs to plausibly fill the buffer.
        budget = batch * seq
        have = sum(min(len(d), seq) for d in pending)
        while not exhausted and have < budget * 2:
            try:
                d = next(it)
            except StopIteration:
                exhausted = True
                break
            if len(d) > seq:   # chunk oversized docs
                for i in range(0, len(d), seq):
                    pending.append(d[i:i + seq])
                    have += len(d[i:i + seq])
            else:
                pending.append(d)
                have += len(d)
        if not pending:
            break
        tokens, segments, positions, placed = pack(
            pending, batch, seq, pad_id, force_numpy)
        if placed == 0:
            break
        pending = pending[placed:]
        yield {
            "tokens": tokens,
            "segment_ids": segments,
            "positions": positions,
            "mask": (segments > 0).astype(np.float32),
        }


def prefetch(batches: Iterable[Dict[str, np.ndarray]], size: int = 2,
             device_put=None) -> Iterator:
    """Double-buffered background prefetch (optionally device_put)."""
    q: "queue.Queue" = queue.Queue(maxsize=size)
    _END = object()

    def worker():
        try:
            for b in batches:
                if device_put is not None:
                    b = device_put(b)
                q.put(b)
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            q.put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def synthetic_doc_stream(n_docs: int, vocab_size: int, mean_len: int,
                         seed: int = 0) -> Iterator[List[int]]:
    """Length-varied synthetic documents (for benchmarks/tests)."""
    rng = np.random.default_rng(seed)
    for _ in range(n_docs):
        n = max(int(rng.poisson(mean_len)), 1)
        yield rng.integers(1, vocab_size, n).astype(np.int32).tolist()
