"""Sync exclusion lists: .skyignore / .gitignore handling.

Reference parity: sky/utils/... storage_utils (`.skyignore`/gitignore
exclusion lists for workdir + file-mount uploads).
"""

from __future__ import annotations

import os
import sys
from typing import List

SKY_IGNORE_FILE = ".skyignore"
GIT_IGNORE_FILE = ".gitignore"


def read_ignore_patterns(src_dir: str) -> List[str]:
    """Patterns from .skyignore (preferred) else .gitignore, rsync-style.

    Comments and blank lines dropped; leading ``/`` anchors are kept
    (rsync interprets them relative to the transfer root, same as git).
    """
    for fname in (SKY_IGNORE_FILE, GIT_IGNORE_FILE):
        path = os.path.join(src_dir, fname)
        if os.path.isfile(path):
            patterns = []
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    if line.startswith("!"):
                        # Negation is unsupported: the preceding exclude
                        # still applies, so the re-included file would be
                        # skipped. Warn so the user knows to adjust.
                        print(f"WARNING: {path}: negation pattern "
                              f"{line!r} is not supported and will be "
                              f"ignored; matching files stay excluded "
                              f"by earlier patterns.", file=sys.stderr)
                        continue
                    patterns.append(line)
            return patterns
    return []


def rsync_exclude_args(src_dir: str) -> List[str]:
    """['--exclude', pat, ...] for every ignore pattern + VCS dirs."""
    args = ["--exclude", ".git"]
    for pat in read_ignore_patterns(src_dir):
        args += ["--exclude", pat]
    return args


def gsutil_exclude_regex(src_dir: str) -> str:
    """A single regex for ``gcloud storage rsync -x`` built from the
    ignore patterns (glob -> regex, anchored like rsync's)."""
    import re as _re
    parts = [r"\.git(/.*)?$"]
    for pat in read_ignore_patterns(src_dir):
        anchored = pat.startswith("/")
        pat = pat.strip("/")
        rx = _re.escape(pat).replace(r"\*\*", ".*").replace(r"\*", "[^/]*")
        rx = rx.replace(r"\?", "[^/]")
        prefix = "^" if anchored else "(^|.*/)"
        parts.append(f"{prefix}{rx}(/.*)?$")
    return "|".join(parts)


def aws_exclude_args(src_dir: str) -> str:
    """``--exclude P `` args for ``aws s3 sync`` from the ignore
    patterns (aws globs are close enough to rsync's for these). Each
    pattern is shell-quoted — ignore-file content is untrusted input to
    a ``bash -c`` command line."""
    import shlex as _shlex
    out = ["--exclude " + _shlex.quote(".git/*")]
    for pat in read_ignore_patterns(src_dir):
        pat = pat.strip("/")
        out.append(f"--exclude {_shlex.quote(pat)} "
                   f"--exclude {_shlex.quote(pat + '/*')}")
    return " ".join(out) + " "
