"""Server-side implementations of non-launch operations.

Reference parity: sky/core.py (status:48, start:349, down:421, stop:456,
autostop:516, queue:625, cancel:688, tail_logs:783, storage_ls:910).
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import (ClusterHandle, TpuVmBackend,
                                  check_owner_identity)


def _handle(cluster_name: str) -> ClusterHandle:
    rec = state.get_cluster(cluster_name)
    if rec is None:
        raise exceptions.ClusterNotUpError(
            f"cluster {cluster_name!r} not found")
    return ClusterHandle(rec["handle"])


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    backend = TpuVmBackend()
    records = state.list_clusters()
    if cluster_names:
        records = [r for r in records if r["name"] in cluster_names]
    if refresh:
        for r in records:
            backend.refresh_status(r["name"])
        records = [r2 for r in records
                   if (r2 := state.get_cluster(r["name"])) is not None]
    return records


def start(cluster_name: str) -> None:
    check_owner_identity(cluster_name)
    TpuVmBackend().start(cluster_name)


def stop(cluster_name: str) -> None:
    check_owner_identity(cluster_name)
    TpuVmBackend().stop(_handle(cluster_name))


def down(cluster_name: str, purge: bool = False) -> None:
    check_owner_identity(cluster_name)
    try:
        TpuVmBackend().teardown(_handle(cluster_name))
    except exceptions.ClusterNotUpError:
        if not purge:
            raise
        state.remove_cluster(cluster_name)


def autostop(cluster_name: str, idle_minutes: int, down_: bool = False) -> None:
    check_owner_identity(cluster_name)
    handle = _handle(cluster_name)
    # Arm the cluster-side skylet (survives this client); the state-DB
    # record is kept for `status` display only.
    TpuVmBackend().set_autostop(handle, idle_minutes, down_)
    state.set_autostop(cluster_name, idle_minutes, down_)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    return TpuVmBackend().queue(_handle(cluster_name))


def cancel(cluster_name: str, job_id: int) -> None:
    check_owner_identity(cluster_name)
    TpuVmBackend().cancel(_handle(cluster_name), job_id)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = False, out=None) -> None:
    backend = TpuVmBackend()
    handle = _handle(cluster_name)
    if job_id is None:
        jobs = backend.queue(handle)
        if not jobs:
            raise exceptions.JobNotFoundError("no jobs on cluster")
        job_id = jobs[0]["job_id"]
    backend.tail_logs(handle, job_id, follow=follow, out=out)


def job_status(cluster_name: str, job_id: int):
    jobs = TpuVmBackend().queue(_handle(cluster_name))
    for j in jobs:
        if j["job_id"] == job_id:
            return j["status"]
    raise exceptions.JobNotFoundError(f"no job {job_id} on {cluster_name}")


def cost_report() -> List[Dict[str, Any]]:
    return state.cost_report()


# -- local kubernetes (kind) ------------------------------------------------

LOCAL_KIND_CLUSTER = "skytpu-local"


def local_up(name: str = LOCAL_KIND_CLUSTER) -> str:
    """Create a local kind (Kubernetes-in-Docker) cluster and enable
    the kubernetes cloud against it — the no-cloud-credentials way to
    exercise the REAL kubernetes provider end to end.

    Reference parity: sky/core.py:1010 local_up (creates a kind
    cluster via sky/utils/kubernetes/create_cluster.sh and marks
    kubernetes enabled). Idempotent: an existing cluster of the same
    name is reused. Returns the kubectl context name.
    """
    import shutil
    import subprocess

    from skypilot_tpu import check as check_mod

    if shutil.which("docker") is None:
        raise exceptions.NotSupportedError(
            "docker is required for `local up` (kind runs kubernetes "
            "inside a docker container) — install docker first")
    if shutil.which("kind") is None:
        raise exceptions.NotSupportedError(
            "kind is required for `local up` — install it from "
            "https://kind.sigs.k8s.io/ (a single static binary)")
    if shutil.which("kubectl") is None:
        raise exceptions.NotSupportedError(
            "kubectl is required for `local up` — install it from "
            "https://kubernetes.io/docs/tasks/tools/")
    existing = subprocess.run(["kind", "get", "clusters"],
                              capture_output=True, text=True, timeout=60)
    if name not in existing.stdout.split():
        create = subprocess.run(
            ["kind", "create", "cluster", "--name", name,
             "--wait", "120s"],
            capture_output=True, text=True, timeout=600)
        if create.returncode != 0:
            raise exceptions.ProvisionError(
                f"kind create cluster failed:\n{create.stderr[-2000:]}")
    context = f"kind-{name}"
    # kind switches kubectl's current-context itself; make it explicit
    # so a user mid-way into another cluster is not silently retargeted
    # without record. A FAILED switch must abort: the credential check
    # below validates the CURRENT context, and launches would otherwise
    # land on whatever cluster (possibly production) it points at.
    switched = subprocess.run(["kubectl", "config", "use-context",
                               context],
                              capture_output=True, text=True, timeout=60)
    if switched.returncode != 0:
        raise exceptions.ProvisionError(
            f"kubectl could not switch to context {context!r} "
            f"(is KUBECONFIG pointing somewhere kind did not write?):\n"
            f"{switched.stderr[-500:]}")
    # check() raises NoCloudAccessError (with cloud-credential
    # remediation advice) when NOTHING is enabled — wrong message for
    # a local-kind user; convert to the kind-specific error either way.
    try:
        enabled = check_mod.check(quiet=True, clouds=["kubernetes"])
    except exceptions.SkyTpuError:
        enabled = []
    if "kubernetes" not in enabled:
        raise exceptions.ProvisionError(
            f"kind cluster {name} is up but the kubernetes provider "
            "failed its credential check — does `kubectl "
            f"--context {context} get nodes` work?")
    return context


def local_down(name: str = LOCAL_KIND_CLUSTER) -> None:
    """Delete the local kind cluster created by :func:`local_up`."""
    import shutil
    import subprocess
    if shutil.which("kind") is None:
        raise exceptions.NotSupportedError("kind is not installed")
    out = subprocess.run(["kind", "delete", "cluster", "--name", name],
                         capture_output=True, text=True, timeout=300)
    if out.returncode != 0:
        raise exceptions.ProvisionError(
            f"kind delete cluster failed:\n{out.stderr[-2000:]}")
