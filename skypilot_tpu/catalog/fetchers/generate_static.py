"""Generate the static GCP TPU/GPU catalog CSV.

Mirrors the reference's data-fetcher pattern (reference:
sky/clouds/service_catalog/data_fetchers/fetch_gcp.py — pulls SKU prices
into CSVs consumed by a pandas query layer). This environment has zero
egress, so the fetcher emits a checked-in snapshot of public GCP pricing
(approximate, 2025) instead of calling the SKUs API; the query layer is
identical either way.

Run:  python -m skypilot_tpu.catalog.fetchers.generate_static
"""

from __future__ import annotations

import csv
import os

# (generation, $/chip/hr on-demand, chips_per_host, cores_per_chip,
#  slice sizes in *chips*, zones)
TPU_GENERATIONS = {
    "v2": (1.125, 4, 2, [8 // 2 * s for s in (1, 4, 8, 16, 32, 64)],
           ["us-central1-b", "us-central1-c", "europe-west4-a",
            "asia-east1-c"]),
    "v3": (1.00, 4, 2, [4, 16, 32, 64, 128, 256, 512],
           ["us-central1-a", "europe-west4-a"]),
    "v4": (3.22, 4, 2, [4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048],
           ["us-central2-b"]),
    "v5e": (1.20, 8, 1, [1, 4, 8, 16, 32, 64, 128, 256],
            ["us-central1-a", "us-west4-a", "us-west4-b", "us-east1-c",
             "us-east5-b", "europe-west4-b", "asia-southeast1-b"]),
    "v5p": (4.20, 4, 2, [4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 3072],
            ["us-east5-a", "europe-west4-b"]),
    "v6e": (2.70, 8, 1, [1, 4, 8, 16, 32, 64, 128, 256],
            ["us-east1-d", "us-east5-a", "us-east5-b", "europe-west4-a",
             "asia-northeast1-b", "us-south1-a"]),
}

# Suffix convention follows GCP acceleratorType: chip count for v5e/v6e
# (v5litepod-N / v6e-N), TensorCore count for v2-v4 and v5p (v5p-N).
CORE_SUFFIX = {"v2", "v3", "v4", "v5p"}

SPOT_DISCOUNT = 0.40  # spot price = on-demand * 0.40

REGION_MULT = {"us": 1.0, "europe": 1.10, "asia": 1.15}

# name, accel per VM, $/hr per VM on-demand, vcpus, mem GB, zones
GPU_VMS = [
    ("A100", 8, "a2-highgpu-8g", 29.39, 96, 680,
     ["us-central1-a", "us-central1-c", "europe-west4-a", "asia-northeast1-a"]),
    ("A100", 1, "a2-highgpu-1g", 3.67, 12, 85,
     ["us-central1-a", "us-central1-c", "europe-west4-a"]),
    ("A100-80GB", 8, "a2-ultragpu-8g", 40.55, 96, 1360,
     ["us-central1-a", "us-east4-c", "europe-west4-a"]),
    ("H100", 8, "a3-highgpu-8g", 88.25, 208, 1872,
     ["us-central1-a", "us-east5-a", "europe-west4-b"]),
    ("L4", 1, "g2-standard-8", 0.85, 8, 32,
     ["us-central1-a", "us-east1-b", "europe-west4-a"]),
    ("V100", 4, "n1-highmem-32+v100x4", 10.22, 32, 208,
     ["us-central1-a", "europe-west4-a"]),
    ("T4", 1, "n1-standard-8+t4", 0.73, 8, 30,
     ["us-central1-a", "us-east1-c", "asia-east1-c"]),
]

# CPU-only types (controllers, data prep).
CPU_VMS = [
    ("n2-standard-4", 0.194, 4, 16),
    ("n2-standard-8", 0.389, 8, 32),
    ("n2-standard-16", 0.777, 16, 64),
    ("n2-standard-32", 1.554, 32, 128),
    ("n2-highmem-8", 0.524, 8, 64),
]
CPU_ZONES = ["us-central1-a", "us-central1-b", "us-east1-c", "us-east5-a",
             "europe-west4-a", "asia-northeast1-b"]

HEADER = ["accelerator", "accelerator_count", "cloud", "instance_type",
          "chips", "hosts", "region", "zone", "price", "spot_price",
          "vcpus", "memory_gb"]


def _mult(zone: str) -> float:
    for prefix, m in REGION_MULT.items():
        if zone.startswith(prefix):
            return m
    return 1.0


def rows():
    for gen, (chip_price, chips_per_host, cores_per_chip, sizes,
              zones) in TPU_GENERATIONS.items():
        for chips in sizes:
            hosts = max(1, chips // chips_per_host)
            suffix = (chips * cores_per_chip if gen in CORE_SUFFIX else chips)
            name = f"tpu-{gen}-{suffix}"
            for zone in zones:
                price = chip_price * chips * _mult(zone)
                # Per-host vCPU/mem: TPU-VMs are beefy fixed shapes.
                vcpus, mem = (96, 192) if chips_per_host == 8 else (208, 400)
                yield [name, 1, "gcp", f"tpu-{gen}", chips, hosts,
                       zone.rsplit("-", 1)[0], zone, round(price, 2),
                       round(price * SPOT_DISCOUNT, 2), vcpus * hosts,
                       mem * hosts]
    for accel, count, itype, price, vcpus, mem, zones in GPU_VMS:
        for zone in zones:
            p = price * _mult(zone)
            yield [accel, count, "gcp", itype, 0, 1,
                   zone.rsplit("-", 1)[0], zone, round(p, 2),
                   round(p * SPOT_DISCOUNT, 2), vcpus, mem]
    for itype, price, vcpus, mem in CPU_VMS:
        for zone in CPU_ZONES:
            p = price * _mult(zone)
            yield ["", 0, "gcp", itype, 0, 1, zone.rsplit("-", 1)[0], zone,
                   round(p, 2), round(p * SPOT_DISCOUNT, 2), vcpus, mem]


def main(out_path: str | None = None) -> str:
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "data", "gcp.csv")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(HEADER)
        for r in rows():
            w.writerow(r)
    return out_path


if __name__ == "__main__":
    print(main())
