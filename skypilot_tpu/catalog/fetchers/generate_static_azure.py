"""Generate the static Azure GPU/CPU catalog CSV.

Counterpart of ``generate_static_aws.py`` for the Azure cloud,
mirroring the reference's per-cloud data-fetcher pattern (reference:
sky/clouds/service_catalog/data_fetchers/fetch_azure.py — enumerates
VM SKUs + retail prices into CSVs consumed by one pandas query layer).
Zero-egress environment: emits a checked-in snapshot of public Azure
pay-as-you-go pricing (approximate, 2025) rather than calling the
Retail Prices API; the query layer is identical either way.

Azure has no TPUs — its rows are GPU (NC A100/H100 v4/v5, NCasT4) and
CPU (D-series) instances, the third leg of the cross-cloud arbitrage:
a GPU task can land on GCP, AWS, or Azure, whichever is cheapest and
unblocked.

Run:  python -m skypilot_tpu.catalog.fetchers.generate_static_azure
"""

from __future__ import annotations

import csv
import os

from skypilot_tpu.catalog.fetchers.generate_static import HEADER

# accel, count/VM, VM size, eastus $/hr, vcpus, mem GB, regions
GPU_VMS = [
    ("A100-80GB", 8, "Standard_ND96amsr_A100_v4", 32.77, 96, 1924,
     ["eastus", "westus2", "westeurope"]),
    ("A100-80GB", 4, "Standard_NC96ads_A100_v4", 14.69, 96, 880,
     ["eastus", "westus2", "westeurope", "japaneast"]),
    ("A100-80GB", 2, "Standard_NC48ads_A100_v4", 7.35, 48, 440,
     ["eastus", "westus2", "westeurope"]),
    ("A100-80GB", 1, "Standard_NC24ads_A100_v4", 3.67, 24, 220,
     ["eastus", "westus2", "westeurope", "japaneast"]),
    ("H100", 8, "Standard_ND96isr_H100_v5", 98.32, 96, 1900,
     ["eastus", "westus3"]),
    ("T4", 1, "Standard_NC4as_T4_v3", 0.526, 4, 28,
     ["eastus", "westus2", "westeurope", "japaneast"]),
    ("T4", 4, "Standard_NC64as_T4_v3", 4.352, 64, 440,
     ["eastus", "westus2"]),
    ("V100", 1, "Standard_NC6s_v3", 3.06, 6, 112,
     ["eastus", "westus2", "westeurope"]),
    ("V100", 4, "Standard_NC24s_v3", 12.24, 24, 448,
     ["eastus", "westus2"]),
]

# CPU-only (controllers, data prep) — D-series v5.
CPU_VMS = [
    ("Standard_D2s_v5", 0.096, 2, 8),
    ("Standard_D4s_v5", 0.192, 4, 16),
    ("Standard_D8s_v5", 0.384, 8, 32),
    ("Standard_D16s_v5", 0.768, 16, 64),
    ("Standard_D32s_v5", 1.536, 32, 128),
    ("Standard_E8s_v5", 0.504, 8, 64),
]
CPU_REGIONS = ["eastus", "westus2", "westeurope", "japaneast"]

# eastus anchors; other regions carry a flat multiplier.
REGION_MULT = {"eastus": 1.0, "westus2": 1.0, "westus3": 1.0,
               "westeurope": 1.08, "japaneast": 1.18}

# Availability zones: Azure zonal regions expose zones 1-3; two are
# emitted per region so same-region zonal failover has somewhere to go.
ZONES = ("1", "2")

SPOT_DISCOUNT = 0.35


def rows():
    for accel, count, size, base, vcpus, mem, regions in GPU_VMS:
        for region in regions:
            price = base * REGION_MULT.get(region, 1.1)
            for z in ZONES:
                yield [accel, count, "azure", size, 0, 1, region,
                       f"{region}-{z}", round(price, 3),
                       round(price * SPOT_DISCOUNT, 3), vcpus, mem]
    for size, base, vcpus, mem in CPU_VMS:
        for region in CPU_REGIONS:
            price = base * REGION_MULT.get(region, 1.1)
            for z in ZONES:
                yield ["", 0, "azure", size, 0, 1, region,
                       f"{region}-{z}", round(price, 3),
                       round(price * SPOT_DISCOUNT, 3), vcpus, mem]


def main(path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(HEADER)
        for row in rows():
            w.writerow(row)


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "data", "azure.csv")
    main(out)
    print(f"wrote {out}")
