"""Generate the static AWS GPU/CPU catalog CSV.

Counterpart of ``generate_static.py`` (GCP) for the AWS cloud, mirroring
the reference's per-cloud data-fetcher pattern (reference:
sky/clouds/service_catalog/data_fetchers/fetch_aws.py — enumerates EC2
instance offerings + pricing into CSVs consumed by one pandas query
layer). Zero-egress environment: emits a checked-in snapshot of public
EC2 on-demand pricing (approximate, 2025) rather than calling the
Pricing API; the query layer is identical either way.

AWS has no TPUs — its catalog rows are GPU and CPU instances, which is
exactly what makes the cross-cloud optimizer story real: a GPU task can
be arbitraged between GCP A100s and EC2 p4d, while TPU tasks stay on
GCP.

Run:  python -m skypilot_tpu.catalog.fetchers.generate_static_aws
"""

from __future__ import annotations

import csv
import os

from skypilot_tpu.catalog.fetchers.generate_static import HEADER

# accel, count/VM, instance type, us-east-1 $/hr, vcpus, mem GB, regions
GPU_VMS = [
    ("A100", 8, "p4d.24xlarge", 32.77, 96, 1152,
     ["us-east-1", "us-west-2", "eu-west-1", "ap-northeast-1"]),
    ("A100-80GB", 8, "p4de.24xlarge", 40.97, 96, 1152,
     ["us-east-1", "us-west-2"]),
    ("H100", 8, "p5.48xlarge", 98.32, 192, 2048,
     ["us-east-1", "us-west-2", "eu-north-1"]),
    ("V100", 8, "p3.16xlarge", 24.48, 64, 488,
     ["us-east-1", "us-west-2", "eu-west-1"]),
    ("V100", 1, "p3.2xlarge", 3.06, 8, 61,
     ["us-east-1", "us-west-2", "eu-west-1", "ap-northeast-1"]),
    ("A10G", 1, "g5.xlarge", 1.006, 4, 16,
     ["us-east-1", "us-west-2", "eu-west-1", "ap-northeast-1"]),
    ("A10G", 4, "g5.12xlarge", 5.672, 48, 192,
     ["us-east-1", "us-west-2", "eu-west-1"]),
    ("A10G", 8, "g5.48xlarge", 16.288, 192, 768,
     ["us-east-1", "us-west-2"]),
    ("L4", 1, "g6.xlarge", 0.805, 4, 16,
     ["us-east-1", "us-west-2", "eu-west-1"]),
    ("T4", 1, "g4dn.xlarge", 0.526, 4, 16,
     ["us-east-1", "us-west-2", "eu-west-1", "ap-northeast-1"]),
    ("T4", 4, "g4dn.12xlarge", 3.912, 48, 192,
     ["us-east-1", "us-west-2"]),
]

# CPU-only (controllers, data prep) — m6i family.
CPU_VMS = [
    ("m6i.large", 0.096, 2, 8),
    ("m6i.xlarge", 0.192, 4, 16),
    ("m6i.2xlarge", 0.384, 8, 32),
    ("m6i.4xlarge", 0.768, 16, 64),
    ("m6i.8xlarge", 1.536, 32, 128),
    ("r6i.2xlarge", 0.504, 8, 64),
]
CPU_REGIONS = ["us-east-1", "us-west-2", "eu-west-1", "ap-northeast-1"]

# us-east-1 is the price anchor; other regions carry a flat multiplier
# (same approximation style as the GCP fetcher's REGION_MULT).
REGION_MULT = {"us-east-1": 1.0, "us-west-2": 1.0, "eu-west-1": 1.06,
               "eu-north-1": 1.02, "ap-northeast-1": 1.20}

# Default AZ suffixes emitted per region. Real AZ ids are
# account-specific mappings; two suffixes give failover tests a
# same-region second zone, matching the reference's checked-in AZ
# mapping approach (tests/default_aws_az_mappings.csv).
AZ_SUFFIXES = ("a", "b")

SPOT_DISCOUNT = 0.32  # EC2 spot discounts run deeper than GCP's


def rows():
    for accel, count, itype, base, vcpus, mem, regions in GPU_VMS:
        for region in regions:
            price = base * REGION_MULT.get(region, 1.1)
            for suffix in AZ_SUFFIXES:
                yield [accel, count, "aws", itype, 0, 1, region,
                       f"{region}{suffix}", round(price, 3),
                       round(price * SPOT_DISCOUNT, 3), vcpus, mem]
    for itype, base, vcpus, mem in CPU_VMS:
        for region in CPU_REGIONS:
            price = base * REGION_MULT.get(region, 1.1)
            for suffix in AZ_SUFFIXES:
                yield ["", 0, "aws", itype, 0, 1, region,
                       f"{region}{suffix}", round(price, 3),
                       round(price * SPOT_DISCOUNT, 3), vcpus, mem]


def main(out_path: str | None = None) -> str:
    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "data", "aws.csv")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(HEADER)
        for r in rows():
            w.writerow(r)
    return out_path


if __name__ == "__main__":
    print(main())
