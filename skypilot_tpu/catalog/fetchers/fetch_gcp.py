"""Live GCP pricing fetcher: Cloud Billing SKUs API -> catalog CSV.

Reference parity: sky/clouds/service_catalog/data_fetchers/fetch_gcp.py
(get_skus:209 pulls services/<id>/skus pages; TPU prices are matched by
SKU description, get_tpu_df:616). Zero-SDK by design: plain REST via
urllib with a `gcloud auth print-access-token` bearer token, so it runs
on any TPU-VM without the google-api-python-client stack.

Design split: the static table (generate_static.py) owns *topology* —
generations, slice sizes, host shapes, zones — which changes rarely and
needs no API; this fetcher owns *prices*, overlaying live on-demand /
spot rates onto the static rows. Offline (zero-egress) environments
keep the static snapshot; `skytpu catalog fetch` refreshes prices where
the billing API is reachable.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_gcp [--out PATH]
"""

from __future__ import annotations

import argparse
import csv
import io
import json
import subprocess
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

BILLING_URL = "https://cloudbilling.googleapis.com/v1"
# Public, stable service ids (the billing catalog's name for a product).
COMPUTE_SERVICE_ID = "6F81-5844-456A"   # Compute Engine (v5e/v5p/v6e TPUs)
TPU_SERVICE_ID = "E000-3F24-B8AA"       # Cloud TPU (v2-v4)

Fetch = Callable[[str], Dict[str, Any]]


def _default_fetch(url: str) -> Dict[str, Any]:
    token = subprocess.run(
        ["gcloud", "auth", "print-access-token"], capture_output=True,
        text=True, check=True).stdout.strip()
    req = urllib.request.Request(
        url, headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def get_skus(service_id: str, fetch: Fetch = _default_fetch,
             page_size: int = 500) -> List[Dict[str, Any]]:
    """All SKUs of one billing service (paginated skus.list)."""
    skus: List[Dict[str, Any]] = []
    page_token = ""
    while True:
        q = {"pageSize": str(page_size)}
        if page_token:
            q["pageToken"] = page_token
        url = (f"{BILLING_URL}/services/{service_id}/skus?"
               f"{urllib.parse.urlencode(q)}")
        resp = fetch(url)
        skus.extend(resp.get("skus", []))
        page_token = resp.get("nextPageToken", "")
        if not page_token:
            return skus


def unit_price(sku: Dict[str, Any]) -> Optional[float]:
    """$/unit/hr from the SKU's first pricing tier (units + nanos)."""
    try:
        rate = (sku["pricingInfo"][0]["pricingExpression"]
                ["tieredRates"][0]["unitPrice"])
        return int(rate.get("units") or 0) + rate.get("nanos", 0) / 1e9
    except (KeyError, IndexError, TypeError):
        return None


# -- TPU price matching ------------------------------------------------------
#
# Billing SKU descriptions name TPUs as e.g.
#   "Tpu-v2 Pod accelerator core running in Americas"      (TPU service)
#   "TpuV5e chip hour in us-west4" / "Preemptible TpuV6e..." (Compute)
# Per-chip-hour generations (v5e/v5p/v6e) price one chip; v2-v4 price
# one *core*, with separate device vs Pod SKUs.

_DESC_TOKEN = {
    "v2": "Tpu-v2", "v3": "Tpu-v3", "v4": "Tpu-v4",
    "v5e": "TpuV5e", "v5p": "TpuV5p", "v6e": "TpuV6e",
}
# Units the SKU price is quoted per, in chips.
_CHIPS_PER_SKU_UNIT = {
    "v2": 0.5, "v3": 0.5, "v4": 0.5,   # priced per core (2 cores/chip)
    "v5e": 1.0, "v6e": 1.0,            # per chip
    "v5p": 1.0,                        # per chip (quoted per 2-core chip)
}


def tpu_chip_price(skus: Iterable[Dict[str, Any]], gen: str, region: str,
                   spot: bool, is_pod: bool) -> Optional[float]:
    """$/chip/hr for one TPU generation in one region, or None."""
    token = _DESC_TOKEN[gen]
    per_unit_chips = _CHIPS_PER_SKU_UNIT[gen]
    pod_aware = gen in ("v2", "v3")   # v4+ SKUs carry no Pod split
    for sku in skus:
        desc = sku.get("description", "")
        if token not in desc:
            continue
        if region not in sku.get("serviceRegions", []):
            continue
        # NOTE (matches the billing catalog, not intuition): preemptible
        # TPU SKUs say "Preemptible" in the description while usageType
        # can still read OnDemand.
        if spot != ("Preemptible" in desc):
            continue
        if pod_aware and is_pod != ("Pod" in desc):
            continue
        price = unit_price(sku)
        if price is not None:
            return price / per_unit_chips
    return None


def merge_live_prices(rows: List[List[Any]], header: List[str],
                      compute_skus: List[Dict[str, Any]],
                      tpu_skus: List[Dict[str, Any]]) -> Tuple[int, int]:
    """Overlay live TPU prices onto static catalog rows, in place.

    Returns (updated, total_tpu_rows). Rows whose price the API doesn't
    carry keep their static snapshot — the catalog never loses offerings
    because a SKU page changed shape.
    """
    col = {name: i for i, name in enumerate(header)}
    updated = total = 0
    all_skus = list(compute_skus) + list(tpu_skus)
    # Every slice size of one (gen, region) shares a chip price; the
    # real SKU list is tens of thousands of entries, so resolve each
    # (gen, region, spot, is_pod) once.
    cache: Dict[Tuple[str, str, bool, bool], Optional[float]] = {}

    def chip_price(gen, region, spot, is_pod):
        key = (gen, region, spot, is_pod)
        if key not in cache:
            cache[key] = tpu_chip_price(all_skus, gen, region, spot=spot,
                                        is_pod=is_pod)
        return cache[key]

    for row in rows:
        itype = row[col["instance_type"]]
        if not str(itype).startswith("tpu-"):
            continue
        total += 1
        gen = str(itype)[len("tpu-"):]
        if gen not in _DESC_TOKEN:
            continue
        chips = int(row[col["chips"]])
        region = row[col["region"]]
        is_pod = chips > 4
        od = chip_price(gen, region, False, is_pod)
        sp = chip_price(gen, region, True, is_pod)
        if od is not None:
            row[col["price"]] = round(od * chips, 2)
        if sp is not None:
            row[col["spot_price"]] = round(sp * chips, 2)
        if od is not None or sp is not None:
            updated += 1
    return updated, total


def fetch_and_write(out_path: Optional[str] = None,
                    fetch: Fetch = _default_fetch) -> Tuple[str, int, int]:
    """Regenerate the static CSV, then overlay live prices onto it.

    SKUs are fetched BEFORE the CSV is touched, so a network/auth
    failure leaves the existing catalog byte-identical.
    """
    from skypilot_tpu.catalog.fetchers import generate_static
    compute_skus = get_skus(COMPUTE_SERVICE_ID, fetch)
    tpu_skus = get_skus(TPU_SERVICE_ID, fetch)
    out_path = generate_static.main(out_path)
    with open(out_path, newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        rows = [r for r in reader]
    updated, total = merge_live_prices(rows, header, compute_skus, tpu_skus)
    buf = io.StringIO()
    w = csv.writer(buf)
    w.writerow(header)
    w.writerows(rows)
    with open(out_path, "w", newline="") as f:
        f.write(buf.getvalue())
    return out_path, updated, total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    path, updated, total = fetch_and_write(args.out)
    print(f"{path}: live prices on {updated}/{total} TPU rows")


if __name__ == "__main__":
    main()
