"""Catalog query layer: pandas over checked-in CSVs.

Mirrors the reference's service_catalog (reference:
sky/clouds/service_catalog/common.py:122 LazyDataFrame + filter fns at
:239-560) with a TPU-first schema: every row knows its chip count, host
count and whole-slice price, so the optimizer can rank a v5p-128 slice
against 8x A100 nodes directly.
"""

from __future__ import annotations

import functools
import os
import re
from typing import Dict, List, Optional

import pandas as pd

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

# Clouds with a priced offerings catalog. 'kubernetes' and 'local' have
# none by design: their capacity is whatever the cluster/machine has, so
# they take the synthetic-candidate path in Resources.launchables.
CATALOG_CLOUDS = ("gcp", "aws", "azure")


@functools.lru_cache(maxsize=None)
def _df(cloud: str = "gcp") -> pd.DataFrame:
    if cloud is None or cloud == "all":
        return pd.concat([_df(c) for c in CATALOG_CLOUDS],
                         ignore_index=True)
    path = os.path.join(_DATA_DIR, f"{cloud}.csv")
    if not os.path.exists(path):
        if cloud == "gcp":
            from skypilot_tpu.catalog.fetchers import generate_static
            generate_static.main(path)
        elif cloud == "aws":
            from skypilot_tpu.catalog.fetchers import generate_static_aws
            generate_static_aws.main(path)
        elif cloud == "azure":
            from skypilot_tpu.catalog.fetchers import (
                generate_static_azure)
            generate_static_azure.main(path)
        else:
            raise ValueError(f"no catalog for cloud {cloud!r}")
    df = pd.read_csv(path, keep_default_na=False)
    return df


def reload() -> None:
    _df.cache_clear()


def is_tpu(accelerator: Optional[str]) -> bool:
    return bool(accelerator) and accelerator.lower().startswith("tpu-")


_ACCEL_RE = re.compile(r"^(?P<name>[A-Za-z0-9-]+?)(?::(?P<count>\d+))?$")


def parse_accelerator(spec: str) -> tuple[str, int]:
    """'A100:8' -> ('A100', 8); 'tpu-v5e-16' -> ('tpu-v5e-16', 1)."""
    m = _ACCEL_RE.match(spec.strip())
    if not m:
        raise ValueError(f"invalid accelerator spec: {spec!r}")
    name = m.group("name")
    count = int(m.group("count") or 1)
    return name, count


def list_accelerators(name_filter: Optional[str] = None,
                      cloud: Optional[str] = None) -> pd.DataFrame:
    df = _df(cloud)
    df = df[df["accelerator"] != ""]
    if name_filter:
        df = df[df["accelerator"].str.contains(name_filter, case=False,
                                               regex=False)]
    return df.reset_index(drop=True)


def offerings(accelerator: Optional[str] = None,
              accelerator_count: Optional[int] = None,
              instance_type: Optional[str] = None,
              region: Optional[str] = None,
              zone: Optional[str] = None,
              cloud: Optional[str] = None) -> pd.DataFrame:
    """All catalog rows matching the partial spec (case-insensitive)."""
    df = _df(cloud)
    if accelerator is not None:
        df = df[df["accelerator"].str.lower() == accelerator.lower()]
        if accelerator_count is not None and not is_tpu(accelerator):
            df = df[df["accelerator_count"] == accelerator_count]
    elif instance_type is not None:
        df = df[df["instance_type"] == instance_type]
    if region is not None:
        df = df[df["region"] == region]
    if zone is not None:
        df = df[df["zone"] == zone]
    return df.reset_index(drop=True)


def get_hourly_cost(accelerator: str, use_spot: bool = False,
                    region: Optional[str] = None, zone: Optional[str] = None,
                    cloud: Optional[str] = None) -> float:
    """Cheapest matching offering's whole-slice/VM hourly price."""
    df = offerings(accelerator, region=region, zone=zone, cloud=cloud)
    if df.empty:
        raise ValueError(f"no offering for {accelerator} "
                         f"(region={region}, zone={zone})")
    col = "spot_price" if use_spot else "price"
    return float(df[col].min())


def tpu_slice_info(accelerator: str, cloud: str = "gcp") -> Dict[str, int]:
    """{'chips': N, 'hosts': M} for a TPU slice accelerator name."""
    df = offerings(accelerator, cloud=cloud)
    if df.empty:
        raise ValueError(f"unknown TPU accelerator {accelerator!r}")
    row = df.iloc[0]
    return {"chips": int(row["chips"]), "hosts": int(row["hosts"])}


# Peak dense bf16 (fp16 for older parts) TFLOP/s per chip, used only for
# RELATIVE runtime scaling in the optimizer; 1.0 unit == one v5e chip.
_PEAK_TFLOPS = {
    "tpu-v2": 45, "tpu-v3": 123, "tpu-v4": 275, "tpu-v5e": 197,
    "tpu-v5p": 459, "tpu-v6e": 918,
    "A100": 312, "A100-80GB": 312, "H100": 989, "L4": 121,
    "T4": 65, "V100": 125, "P100": 21, "A10G": 70,
}
_V5E_TFLOPS = 197.0


def compute_units(accelerator: Optional[str],
                  accelerator_count: int = 0) -> float:
    """Relative compute of one node of this offering, in v5e-chip
    equivalents (chips x per-chip peak / v5e peak). Accelerator names
    are cloud-agnostic hardware specs, so no cloud parameter. CPU-only
    instance types count as one unit — runtime scaling across CPU VMs
    is not meaningful."""
    if not accelerator:
        return 1.0
    if is_tpu(accelerator):
        gen = accelerator.rsplit("-", 1)[0]  # tpu-v5e-16 -> tpu-v5e
        peak = _PEAK_TFLOPS.get(gen)
        if peak is None:
            return 1.0
        # Accelerator names are cloud-agnostic hardware specs: always
        # resolve chip counts from the gcp catalog (a 'local'/'k8s'
        # cloud has no catalog of its own — querying it would
        # misgenerate one).
        try:
            chips = tpu_slice_info(accelerator, "gcp")["chips"]
        except (ValueError, KeyError):
            return 1.0
        return chips * peak / _V5E_TFLOPS
    peak = _PEAK_TFLOPS.get(accelerator)
    if peak is None:
        return 1.0
    return max(accelerator_count, 1) * peak / _V5E_TFLOPS


def cpu_instance_types(min_cpus: float = 0, min_memory_gb: float = 0,
                       cloud: Optional[str] = None) -> pd.DataFrame:
    df = _df(cloud)
    df = df[(df["accelerator"] == "")
            & (df["vcpus"] >= min_cpus)
            & (df["memory_gb"] >= min_memory_gb)]
    return df.sort_values("price").reset_index(drop=True)


def validate_region_zone(region: Optional[str], zone: Optional[str],
                         cloud: str = "gcp") -> None:
    df = _df(cloud)
    if region is not None and region not in set(df["region"]):
        raise ValueError(f"unknown region {region!r} for {cloud}")
    if zone is not None and zone not in set(df["zone"]):
        raise ValueError(f"unknown zone {zone!r} for {cloud}")
