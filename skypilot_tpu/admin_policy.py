"""Admin policy plugin: org-level request mutation/validation.

Reference parity: sky/admin_policy.py (UserRequest / MutatedUserRequest /
AdminPolicy ABC) + sky/utils/admin_policy_utils.py (application at
execution.py:180). The policy class is named by the ``admin_policy``
key in config.yaml (``module.path.ClassName``), imported lazily, and
invoked with every launch/exec request before optimization.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions


@dataclasses.dataclass
class RequestOptions:
    """Context about the request, for the policy to inspect."""
    cluster_name: Optional[str] = None
    idle_minutes_to_autostop: Optional[int] = None
    down: bool = False
    dryrun: bool = False


@dataclasses.dataclass
class UserRequest:
    """What the policy sees: the task plus effective global config."""
    task: Any  # skypilot_tpu.task.Task
    skypilot_config: Dict[str, Any]
    request_options: Optional[RequestOptions] = None


@dataclasses.dataclass
class MutatedUserRequest:
    task: Any
    skypilot_config: Dict[str, Any]


class AdminPolicy:
    """Subclass and implement validate_and_mutate; reject by raising."""

    @classmethod
    def validate_and_mutate(cls, user_request: UserRequest
                            ) -> MutatedUserRequest:
        raise NotImplementedError


class PolicyError(exceptions.SkyTpuError):
    """Raised when the admin policy rejects a request."""


def _load_policy() -> Optional[type]:
    spec = config_lib.get_nested(("admin_policy",))
    if not spec:
        return None
    module_path, _, class_name = spec.rpartition(".")
    if not module_path:
        raise PolicyError(f"admin_policy '{spec}' is not a module.Class path")
    try:
        module = importlib.import_module(module_path)
        policy_cls = getattr(module, class_name)
    except (ImportError, AttributeError) as e:
        raise PolicyError(f"cannot import admin_policy '{spec}': {e}") from e
    if not issubclass(policy_cls, AdminPolicy):
        raise PolicyError(
            f"admin_policy '{spec}' must subclass "
            f"skypilot_tpu.admin_policy.AdminPolicy")
    return policy_cls


def apply(task, request_options: Optional[RequestOptions] = None):
    """Run the configured policy over the task.

    Returns ``(task, mutated_config_or_None)``. A non-None config is the
    policy's replacement for the effective global config and must be
    applied for the rest of the request (config_lib.replace_config).
    """
    policy_cls = _load_policy()
    if policy_cls is None:
        return task, None
    original_config = config_lib.to_dict()
    request = UserRequest(task=task,
                          skypilot_config=config_lib.to_dict(),
                          request_options=request_options)
    try:
        mutated = policy_cls.validate_and_mutate(request)
    except PolicyError:
        raise
    except exceptions.SkyTpuError:
        raise
    except Exception as e:  # noqa: BLE001 — policy bugs surface as rejection
        raise PolicyError(f"admin policy rejected request: {e}") from e
    if not isinstance(mutated, MutatedUserRequest):
        raise PolicyError(
            f"admin policy must return MutatedUserRequest, got "
            f"{type(mutated).__name__}")
    mutated_config = (mutated.skypilot_config
                      if mutated.skypilot_config != original_config else None)
    return mutated.task, mutated_config
