"""Sparse Mixture-of-Experts decoder (Mixtral-family proportions), pure JAX.

TPU-first design notes
----------------------
* Routing is *capacity-based dispatch via one-hot einsums* (the
  Switch/flaxformer formulation): every shape is static, so the whole
  layer compiles to dense MXU einsums plus an ep-axis all-to-all that XLA
  derives from the shardings — no gather/scatter, no dynamic shapes, no
  host round trips. Tokens over capacity are dropped (standard capacity
  -factor semantics); the combine tensor simply carries zero weight.
* Expert weights carry a leading ``expert`` logical axis mapped to the
  ``ep`` mesh axis (parallel.sharding.DEFAULT_RULES), composing freely
  with fsdp (embed dim) and tp (mlp dim) on the *same* weights.
* The router runs in float32 (softmax over tiny E-dim — numerics matter,
  FLOPs don't), everything else in bfloat16.
* Per-layer weights are stacked on a leading ``layer`` axis and the body
  runs under one ``lax.scan``, like models.llama; the scan carry threads
  the accumulated aux load-balancing loss.

Attention / norms / rope are reused from models.llama — an MoE block is
a Llama block with the dense FFN swapped for the expert FFN.

Reference parity: the reference ships MoE serving only as external
recipes (reference: llm/mixtral/README.md, llm/deepseek-r1/ — vLLM/SGLang
expert parallelism inside the engine). In-tree MoE + ep mesh axis is the
TPU-native equivalent of that capability (SURVEY.md §2.11).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models import llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama.LlamaConfig):
    """Llama-style decoder where every FFN is a sparse top-k MoE."""

    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    def num_params(self) -> int:
        d, ff, v = self.d_model, self.d_ff, self.vocab_size
        hd = self.head_dim
        attn = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                + self.n_heads * hd * d)
        moe = self.n_experts * 3 * d * ff + d * self.n_experts
        norms = 2 * d
        per_layer = attn + moe + norms
        emb = v * d if self.tie_embeddings else 2 * v * d
        return self.n_layers * per_layer + emb + d

    def num_active_params(self) -> int:
        """Params touched per token (for MFU accounting of sparse FLOPs)."""
        d, ff = self.d_model, self.d_ff
        dense = super().num_params() - self.n_layers * 3 * d * ff
        return dense + self.n_layers * (self.top_k * 3 * d * ff
                                        + d * self.n_experts)


CONFIGS: Dict[str, MoEConfig] = {
    # Mixtral-8x7B proportions (32 layers, 8 experts, top-2).
    "mixtral-8x7b": MoEConfig(vocab_size=32_000, d_model=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, d_ff=14_336,
                              n_experts=8, top_k=2),
    # ~8x the FFN of llama3-400m; fits CPU test meshes and a single v5e.
    "moe-small": MoEConfig(vocab_size=32_768, d_model=1024, n_layers=8,
                           n_heads=8, n_kv_heads=4, d_ff=4096,
                           n_experts=8, top_k=2, max_seq_len=4096),
    "moe-tiny": MoEConfig(vocab_size=512, d_model=128, n_layers=2,
                          n_heads=4, n_kv_heads=2, d_ff=256,
                          n_experts=4, top_k=2, max_seq_len=256),
}


# ---------------------------------------------------------------------------
# Parameter init + logical sharding axes
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: MoEConfig) -> Params:
    """Initialize parameters; per-layer tensors stacked on axis 0."""
    d, ff, v, L, E = (cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers,
                      cfg.n_experts)
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    k = iter(jax.random.split(rng, 16))

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, cfg.param_dtype)
                * (fan_in ** -0.5))

    params: Params = {
        "embed": jax.random.normal(next(k), (v, d), cfg.param_dtype) * 0.02,
        "blocks": {
            "ln1": jnp.ones((L, d), cfg.param_dtype),
            "ln2": jnp.ones((L, d), cfg.param_dtype),
            "wq": norm_init(next(k), (L, d, nh, hd), d),
            "wk": norm_init(next(k), (L, d, nkv, hd), d),
            "wv": norm_init(next(k), (L, d, nkv, hd), d),
            "wo": norm_init(next(k), (L, nh, hd, d), nh * hd),
            "w_router": norm_init(next(k), (L, d, E), d),
            "we_gate": norm_init(next(k), (L, E, d, ff), d),
            "we_up": norm_init(next(k), (L, E, d, ff), d),
            "we_down": norm_init(next(k), (L, E, ff, d), ff),
        },
        "final_norm": jnp.ones((d,), cfg.param_dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(next(k), (d, v), d)
    return params


def param_logical_axes(cfg: MoEConfig) -> Params:
    axes: Params = {
        "embed": ("vocab", "embed"),
        "blocks": {
            "ln1": ("layer", "embed"),
            "ln2": ("layer", "embed"),
            "wq": ("layer", "embed", "heads", "head_dim"),
            "wk": ("layer", "embed", "kv_heads", "head_dim"),
            "wv": ("layer", "embed", "kv_heads", "head_dim"),
            "wo": ("layer", "heads", "head_dim", "embed"),
            "w_router": ("layer", "embed", "expert"),
            "we_gate": ("layer", "expert", "embed", "mlp"),
            "we_up": ("layer", "expert", "embed", "mlp"),
            "we_down": ("layer", "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# The expert FFN
# ---------------------------------------------------------------------------

def expert_capacity(cfg: MoEConfig, seq_len: int) -> int:
    """Per-expert per-row token capacity (static)."""
    cap = math.ceil(cfg.capacity_factor * cfg.top_k * seq_len / cfg.n_experts)
    return max(int(cap), 1)


def moe_ffn(cfg: MoEConfig, h: jax.Array, layer: Params,
            constrain=lambda x, axes: x) -> Tuple[jax.Array, jax.Array]:
    """Top-k capacity-dispatched expert FFN.

    h: [B, S, D] (post-norm). Returns (out [B, S, D], aux loss scalar).
    """
    B, S, D = h.shape
    E, K = cfg.n_experts, cfg.top_k
    C = expert_capacity(cfg, S)

    # --- Router (float32) -------------------------------------------------
    logits = jnp.einsum("bsd,de->bse", h.astype(jnp.float32),
                        layer["w_router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                     # [B,S,E]
    gate_vals, gate_idx = lax.top_k(probs, K)                   # [B,S,K]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # --- Aux load-balancing loss (Switch-style, over first choices) -------
    # f_e: fraction of tokens whose top-1 choice is e; p_e: mean router
    # prob for e. Balanced routing minimizes E * sum(f_e * p_e) at 1.0.
    top1 = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32)
    f = jnp.mean(top1, axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(f * p)

    # --- Capacity assignment ---------------------------------------------
    # Priority order: token position first, then choice rank — flatten
    # (S, K) to S*K so a cumulative sum assigns each (token, choice) its
    # position inside the chosen expert's buffer; >= C drops.
    expert_mask = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [B,S,K,E]
    flat = expert_mask.reshape(B, S * K, E)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)  # 0-based
    keep = expert_mask * (pos < C)                               # [B,S,K,E]
    gates = gate_vals[..., None] * keep                          # [B,S,K,E]

    pos_oh = jax.nn.one_hot(pos, C, dtype=cfg.dtype)             # [B,S,K,E,C]
    keepc = keep[..., None].astype(cfg.dtype) * pos_oh
    dispatch = keepc.sum(axis=2)                                 # [B,S,E,C]
    combine = (gates[..., None].astype(cfg.dtype) * pos_oh).sum(axis=2)

    # --- Expert compute (ep-sharded einsums) ------------------------------
    xe = jnp.einsum("bsd,bsec->becd", h, dispatch)               # [B,E,C,D]
    xe = constrain(xe, ("batch", "expert", "capacity", "embed"))
    g = jnp.einsum("becd,edf->becf", xe, layer["we_gate"].astype(cfg.dtype))
    u = jnp.einsum("becd,edf->becf", xe, layer["we_up"].astype(cfg.dtype))
    y = jnp.einsum("becf,efd->becd", jax.nn.silu(g) * u,
                   layer["we_down"].astype(cfg.dtype))
    y = constrain(y, ("batch", "expert", "capacity", "embed"))
    out = jnp.einsum("becd,bsec->bsd", y, combine)               # [B,S,D]
    return out, aux.astype(jnp.float32)


def decoder_layer(cfg: MoEConfig, x: jax.Array, layer: Params,
                  cos: jax.Array, sin: jax.Array,
                  constrain=lambda x, axes: x, mesh=None,
                  rules=None, segment_ids=None) -> Tuple[jax.Array, jax.Array]:
    """One pre-norm MoE decoder block. Returns (x, aux)."""
    h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    q = constrain(q, ("batch", "seq", "heads", "head_dim"))
    k = constrain(k, ("batch", "seq", "kv_heads", "head_dim"))
    o = llama._attention(q, k, v, cfg, mesh, rules, segment_ids)
    o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
    x = x + constrain(o, ("batch", "seq", "embed"))

    h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
    m, aux = moe_ffn(cfg, h, layer, constrain)
    return x + constrain(m, ("batch", "seq", "embed")), aux


# ---------------------------------------------------------------------------
# Forward / loss
# ---------------------------------------------------------------------------

def forward_hidden(params: Params, tokens: jax.Array, cfg: MoEConfig,
                   constrain=None, mesh=None, rules=None,
                   positions=None,
                   segment_ids=None) -> Tuple[jax.Array, jax.Array]:
    """[B, S] ids -> (final-norm hidden [B, S, D], mean aux loss).

    ``positions``/``segment_ids`` enable packed sequences, same as
    models.llama.forward_hidden.
    """
    if constrain is None:
        constrain = lambda x, axes: x

    B, S = tokens.shape
    tokens = constrain(tokens, ("batch", "seq"))
    # Lookup-friendly table layout (see models/llama.forward_hidden):
    # vocab sharded canonically, embed replicated -> gather output IS
    # the activation layout, no SPMD full-remat transition.
    table = constrain(params["embed"].astype(cfg.dtype),
                      ("vocab", "embed"))
    x = table[tokens]
    x = constrain(x, ("batch", "seq", "embed"))
    if positions is None:
        positions = jnp.arange(S)

    # Zigzag sequence layout (shared contract — the forward owns the
    # decision + permute so the attention dispatch and the layout
    # always agree). The MoE FFN is order-agnostic per token (capacity
    # priority follows the permuted order, still a valid priority), so
    # only attention needs the layout contract.
    from skypilot_tpu.parallel import ring_attention as ra
    (x, positions, segment_ids, layer_rules, use_zigzag,
     n_sp) = ra.apply_zigzag_layout(x, positions, segment_ids, mesh, rules)
    cos, sin = llama.rope_frequencies(cfg, positions)

    def body(carry, layer):
        x, aux_sum = carry
        y, aux = decoder_layer(cfg, x, layer, cos, sin, constrain, mesh,
                               layer_rules, segment_ids)
        return (y, aux_sum + aux), None

    if cfg.remat:
        body = jax.checkpoint(body, policy=llama.remat_policy(cfg))

    (x, aux_sum), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                               params["blocks"])
    if use_zigzag:
        x = ra.zigzag_unpermute(x, n_sp)
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x, aux_sum / cfg.n_layers


def forward(params: Params, tokens: jax.Array, cfg: MoEConfig,
            constrain=None, mesh=None,
            rules=None) -> Tuple[jax.Array, jax.Array]:
    """[B, S] ids -> (logits [B, S, vocab] fp32, mean aux loss scalar)."""
    if constrain is None:
        constrain = lambda x, axes: x
    x, aux = forward_hidden(params, tokens, cfg, constrain, mesh, rules)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits.astype(jnp.float32), aux


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: MoEConfig,
            constrain=None, mesh=None,
            rules=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy + weighted aux load-balancing loss.

    Honors ``cfg.xent_chunk`` via the shared llama.xent_metrics epilogue.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    tokens = batch["tokens"]
    h, aux = forward_hidden(params, tokens, cfg, constrain, mesh, rules,
                            positions=batch.get("positions"),
                            segment_ids=batch.get("segment_ids"))
    mask = llama.packed_loss_mask(batch)
    xent, acc, denom = llama.xent_metrics(params, h, tokens, mask, cfg,
                                          constrain)
    loss = xent + cfg.aux_loss_weight * aux
    return loss, {"loss": loss, "xent": xent, "aux_loss": aux,
                  "accuracy": acc, "tokens": denom}
