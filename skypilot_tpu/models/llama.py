"""Llama-family decoder-only transformer, pure functional JAX.

TPU-first design notes
----------------------
* Params are plain pytrees (nested dicts of ``jnp.ndarray``); per-layer
  weights are *stacked* along a leading ``layer`` axis so the whole decoder
  body runs as one ``lax.scan`` — a single traced layer, compiled once,
  instead of ``n_layers`` unrolled HLO copies.
* Every parameter has *logical axes* (see ``param_logical_axes``); the
  mapping logical-axis -> mesh-axis lives in ``skypilot_tpu.parallel.sharding``
  so the same model code runs single-chip, FSDP, TP, or any combination by
  swapping rules (MaxText-style).
* Compute in bfloat16 (MXU native), params kept in float32 by default;
  activations are sharding-constrained at layer boundaries so XLA inserts
  collectives (all-gather / reduce-scatter over ICI) instead of replicating.
* Attention dispatches through ``skypilot_tpu.ops.attention`` which picks a
  Pallas flash kernel on TPU and a plain XLA einsum path elsewhere.

Reference parity: the reference ships Llama only as *external* workload
recipes (reference: llm/llama-3_1-finetuning/lora.yaml, examples/tpu/v6e/
train-llama3-8b.yaml — PyTorch/XLA + torchtune). Here the model family is
in-tree, which is what makes the in-tree train/serve recipes (§2.11 of
SURVEY.md) possible.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Model hyperparameters (Llama-3 family proportions)."""

    vocab_size: int = 128_256
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16       # activation / compute dtype
    param_dtype: Any = jnp.float32  # storage dtype for parameters
    remat: bool = True              # rematerialize each layer in the bwd pass
    # "none" recomputes everything (min HBM); "dots" saves matmul
    # outputs with no batch dims (MXU results kept, elementwise
    # recomputed — the usual best FLOPs/HBM trade on TPU).
    remat_policy: str = "none"
    # Sequence positions per cross-entropy chunk (0 = single pass).
    # Chunking never materializes the full [B, S, vocab] fp32 logits:
    # each chunk's logits are recomputed in the backward (remat), so
    # peak HBM drops by ~B*S*vocab*4 bytes at the cost of one extra
    # lm_head matmul in the backward.
    xent_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        """Exact parameter count (used for MFU accounting in bench)."""
        d, ff, v = self.d_model, self.d_ff, self.vocab_size
        hd = self.head_dim
        attn = d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd + self.n_heads * hd * d
        mlp = 3 * d * ff
        norms = 2 * d
        per_layer = attn + mlp + norms
        emb = v * d if self.tie_embeddings else 2 * v * d
        return self.n_layers * per_layer + emb + d


# Pre-baked configs. 8B mirrors Llama-3.1-8B, 1B mirrors Llama-3.2-1B.
CONFIGS: Dict[str, LlamaConfig] = {
    "llama3-8b": LlamaConfig(vocab_size=128_256, d_model=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, d_ff=14_336),
    # Llama-3.1-70B proportions (multi-slice scale: llm/
    # llama3-70b-multislice.yaml shards it dp x fsdp x tp over v5p).
    "llama3-70b": LlamaConfig(vocab_size=128_256, d_model=8192,
                              n_layers=80, n_heads=64, n_kv_heads=8,
                              d_ff=28_672, max_seq_len=8192),
    # 1B-class config at Llama-3.2-1B proportions, with head_dim 128
    # (16 heads instead of 32): identical parameter count and FLOPs, but
    # the head dim matches the MXU lane width / Mosaic tiling so the
    # Pallas flash kernels engage.
    "llama3-1b": LlamaConfig(vocab_size=128_256, d_model=2048, n_layers=16,
                             n_heads=16, n_kv_heads=8, d_ff=8192),
    "llama3-tiny": LlamaConfig(vocab_size=512, d_model=128, n_layers=2,
                               n_heads=4, n_kv_heads=2, d_ff=256,
                               max_seq_len=256),
    # Fits a single 16 GB v5e chip with AdamW fp32 state: ~420M params.
    "llama3-400m": LlamaConfig(vocab_size=32_768, d_model=1536, n_layers=12,
                               n_heads=12, n_kv_heads=4, d_ff=6144,
                               max_seq_len=4096),
}


# ---------------------------------------------------------------------------
# Parameter init + logical sharding axes
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize parameters. Per-layer tensors are stacked on axis 0."""
    d, ff, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    k = iter(jax.random.split(rng, 16))

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, cfg.param_dtype)
                * (fan_in ** -0.5))

    params: Params = {
        "embed": jax.random.normal(next(k), (v, d), cfg.param_dtype) * 0.02,
        "blocks": {
            "ln1": jnp.ones((L, d), cfg.param_dtype),
            "ln2": jnp.ones((L, d), cfg.param_dtype),
            "wq": norm_init(next(k), (L, d, nh, hd), d),
            "wk": norm_init(next(k), (L, d, nkv, hd), d),
            "wv": norm_init(next(k), (L, d, nkv, hd), d),
            "wo": norm_init(next(k), (L, nh, hd, d), nh * hd),
            "w_gate": norm_init(next(k), (L, d, ff), d),
            "w_up": norm_init(next(k), (L, d, ff), d),
            "w_down": norm_init(next(k), (L, ff, d), ff),
        },
        "final_norm": jnp.ones((d,), cfg.param_dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(next(k), (d, v), d)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Logical axis names per parameter, same tree structure as params.

    Names are resolved to mesh axes by ``parallel.sharding.logical_to_sharding``.
    """
    axes: Params = {
        "embed": ("vocab", "embed"),
        "blocks": {
            "ln1": ("layer", "embed"),
            "ln2": ("layer", "embed"),
            "wq": ("layer", "embed", "heads", "head_dim"),
            "wk": ("layer", "embed", "kv_heads", "head_dim"),
            "wv": ("layer", "embed", "kv_heads", "head_dim"),
            "wo": ("layer", "heads", "head_dim", "embed"),
            "w_gate": ("layer", "embed", "mlp"),
            "w_up": ("layer", "embed", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def remat_policy(cfg: LlamaConfig):
    """Resolve cfg.remat_policy to a jax checkpoint policy."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy != "none":
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; "
            f"expected 'none' or 'dots'")
    return jax.checkpoint_policies.nothing_saveable


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + eps)).astype(dtype) * scale.astype(dtype)


def rope_frequencies(cfg: LlamaConfig, positions: jax.Array) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embeddings. positions: [B, S] or [S]."""
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., S, hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, hd]; cos/sin: [B, S, hd/2] or [S, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 2:  # [S, hd/2] -> broadcast over batch and heads
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:  # [B, S, hd/2]
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, mesh=None, rules=None,
               segment_ids=None):
    """Grouped-query causal attention; dispatches to ops.attention.

    With a mesh whose sequence mesh-axis (per the activation rule table,
    default ``seq -> sp``) is > 1, the sequence dimension is
    context-parallel: ring attention over that ring, circulating the
    unrepeated KV heads (see parallel.ring_attention). Otherwise local
    flash/XLA attention. Mesh-axis names come from the rules table, never
    hardcoded here.
    """
    from skypilot_tpu.ops import attention as attn_ops
    if mesh is not None:
        from skypilot_tpu.parallel import ring_attention as ra
        from skypilot_tpu.parallel import sharding as sh
        rules = rules if rules is not None else sh.ACT_RULES
        seq_axis = rules.get("seq")
        if (isinstance(seq_axis, str)
                and mesh.shape.get(seq_axis, 1) > 1
                and q.shape[1] % mesh.shape[seq_axis] == 0):
            # (seq not divisible by the ring size falls through to local
            # attention — same degrade-to-replicated convention as
            # spec_for.) Packed sequences ride the ring: segment ids
            # circulate with their K/V blocks.
            heads_axis = rules.get("heads")
            hspec = heads_axis if isinstance(heads_axis, str) else None
            if rules.get("seq_layout") == "zigzag":
                # forward_hidden already put activations/positions/segs
                # in the zigzag layout (it owns the decision + permute).
                return ra.zigzag_ring_attention(
                    q, k, v, mesh, axis=seq_axis,
                    batch_axes=rules.get("batch"), heads_axis=hspec,
                    segment_ids=segment_ids)
            return ra.ring_attention(
                q, k, v, mesh, causal=True, axis=seq_axis,
                batch_axes=rules.get("batch"), heads_axis=hspec,
                segment_ids=segment_ids)
    return attn_ops.gqa_attention(q, k, v, causal=True,
                                  segment_ids=segment_ids)


def decoder_layer(cfg: LlamaConfig, x: jax.Array, layer: Params,
                  cos: jax.Array, sin: jax.Array,
                  constrain=lambda x, axes: x, mesh=None,
                  rules=None, segment_ids=None) -> jax.Array:
    """One pre-norm decoder block. x: [B, S, D]."""
    B, S, D = x.shape
    h = rms_norm(x, layer["ln1"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    q = constrain(q, ("batch", "seq", "heads", "head_dim"))
    k = constrain(k, ("batch", "seq", "kv_heads", "head_dim"))
    o = _attention(q, k, v, cfg, mesh, rules, segment_ids)
    o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
    x = x + constrain(o, ("batch", "seq", "embed"))

    h = rms_norm(x, layer["ln2"], cfg.norm_eps)
    g = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
    m = jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u,
                   layer["w_down"].astype(cfg.dtype))
    return x + constrain(m, ("batch", "seq", "embed"))


# ---------------------------------------------------------------------------
# Forward pass
# ---------------------------------------------------------------------------

def forward_hidden(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                   constrain=None, mesh=None, rules=None,
                   positions=None, segment_ids=None) -> jax.Array:
    """Token ids [B, S] -> final-norm hidden states [B, S, D] (cfg.dtype).

    ``positions`` [B, S] and ``segment_ids`` [B, S] enable packed
    sequences (per-document rope restart + segment attention masking;
    see data.input_pipeline).
    """
    if constrain is None:
        constrain = lambda x, axes: x

    B, S = tokens.shape
    tokens = constrain(tokens, ("batch", "seq"))
    # Lookup-friendly table layout: vocab stays sharded (canonical
    # order), embed replicated — the gather then propagates the token
    # sharding straight to [batch, seq, embed-replicated], which IS the
    # activation layout; no cross-layout transition (and no involuntary
    # full rematerialization from the SPMD partitioner).
    table = constrain(params["embed"].astype(cfg.dtype),
                      ("vocab", "embed"))
    x = table[tokens]
    x = constrain(x, ("batch", "seq", "embed"))
    if positions is None:
        positions = jnp.arange(S)

    # Zigzag sequence layout (load-balanced causal ring, ~2x attention
    # FLOPs saving at large sp): permute embeddings + positions + seg
    # ids ONCE here, run every decoder block in the permuted order
    # (elementwise/matmul ops are order-agnostic; rope follows
    # positions), un-permute once at the end. Decided here so the
    # attention dispatch and the layout always agree.
    from skypilot_tpu.parallel import ring_attention as ra
    (x, positions, segment_ids, layer_rules, use_zigzag,
     n_sp) = ra.apply_zigzag_layout(x, positions, segment_ids, mesh, rules)
    cos, sin = rope_frequencies(cfg, positions)

    def body(carry, layer):
        y = decoder_layer(cfg, carry, layer, cos, sin, constrain, mesh,
                          layer_rules, segment_ids)
        return y, None

    if cfg.remat:
        body = jax.checkpoint(body, policy=remat_policy(cfg))

    x, _ = lax.scan(body, x, params["blocks"])
    if use_zigzag:
        x = ra.zigzag_unpermute(x, n_sp)
    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig,
            constrain=None, mesh=None, rules=None) -> jax.Array:
    """Token ids [B, S] -> logits [B, S, vocab] (float32).

    ``constrain`` is an optional fn(x, logical_axes) -> x applying
    ``with_sharding_constraint``; identity when running unsharded.
    ``mesh`` (+ optional activation ``rules``) enables the
    context-parallel attention path when the seq mesh-axis is > 1.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    x = forward_hidden(params, tokens, cfg, constrain, mesh, rules)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: LlamaConfig,
            constrain=None, mesh=None,
            rules=None) -> tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy. batch: {"tokens": [B, S] int32,
    optionally "mask": [B, S] (1 = predict this position's *next* token)}.

    With ``cfg.xent_chunk`` > 0 the head matmul + softmax run chunked
    over the sequence under remat, so the [B, S, vocab] logits tensor
    never exists in HBM.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    tokens = batch["tokens"]
    h = forward_hidden(params, tokens, cfg, constrain, mesh, rules,
                       positions=batch.get("positions"),
                       segment_ids=batch.get("segment_ids"))
    loss, acc, denom = xent_metrics(params, h, tokens,
                                    packed_loss_mask(batch), cfg,
                                    constrain)
    return loss, {"loss": loss, "accuracy": acc, "tokens": denom}


def packed_loss_mask(batch: Dict[str, jax.Array]):
    """Loss mask honoring packed segments: only within-document
    next-token transitions count (the last token of each segment has no
    target). Returns batch["mask"] unchanged when not packed."""
    mask = batch.get("mask")
    seg = batch.get("segment_ids")
    if seg is None:
        return mask
    same_next = (seg[:, :-1] == seg[:, 1:]) & (seg[:, :-1] > 0)
    pad = jnp.zeros((seg.shape[0], 1), bool)
    seg_mask = jnp.concatenate([same_next, pad], axis=1)
    return (seg_mask if mask is None
            else mask * seg_mask.astype(mask.dtype))


def xent_metrics(params: Params, h: jax.Array, tokens: jax.Array,
                 mask: Optional[jax.Array], cfg: LlamaConfig,
                 constrain=lambda x, axes: x, head: Optional[jax.Array] = None):
    """Shared LM-head + next-token cross-entropy epilogue.

    h: final-norm hidden states [B, S, D]. Returns (loss, acc, denom).
    Honors ``cfg.xent_chunk`` (see LlamaConfig) — used by the llama,
    moe, pipeline, and qlora loss functions alike. ``head`` overrides
    the [D, V] head matrix (qlora passes a dequantized head; the slim
    param tree carries no fp lm_head).
    """
    if head is None:
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
    head = head.astype(cfg.dtype)
    if not cfg.xent_chunk:
        logits = jnp.einsum("bsd,dv->bsv", h, head)
        logits = constrain(logits, ("batch", "seq", "vocab"))
        logits = logits.astype(jnp.float32)[:, :-1]
        targets = tokens[:, 1:]
        logps = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logps, targets[..., None], axis=-1)[..., 0]
        m = (jnp.ones_like(ll) if mask is None
             else mask[:, :-1].astype(ll.dtype))
        denom = jnp.maximum(m.sum(), 1.0)
        loss = -(ll * m).sum() / denom
        acc = ((jnp.argmax(logits, -1) == targets) * m).sum() / denom
        return loss, acc, denom

    B, S = tokens.shape
    # Positions 0..S-2 predict targets 1..S-1. Pad to a chunk multiple
    # with masked-out positions.
    h = h[:, :-1]
    targets = tokens[:, 1:]
    m = (jnp.ones((B, S - 1), jnp.float32) if mask is None
         else mask[:, :-1].astype(jnp.float32))
    c = cfg.xent_chunk
    pad = (-(S - 1)) % c
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        m = jnp.pad(m, ((0, 0), (0, pad)))
    n_chunks = h.shape[1] // c
    h = h.reshape(B, n_chunks, c, -1).swapaxes(0, 1)       # [N,B,c,D]
    targets = targets.reshape(B, n_chunks, c).swapaxes(0, 1)
    m = m.reshape(B, n_chunks, c).swapaxes(0, 1)

    def chunk_body(carry, xs):
        ll_sum, correct = carry
        hc, tc, mc = xs
        logits = jnp.einsum("bcd,dv->bcv", hc, head)
        logits = constrain(logits, ("batch", "seq", "vocab"))
        logits = logits.astype(jnp.float32)
        logps = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logps, tc[..., None], axis=-1)[..., 0]
        ll_sum += (ll * mc).sum()
        correct += ((jnp.argmax(logits, -1) == tc) * mc).sum()
        return (ll_sum, correct), None

    body = jax.checkpoint(chunk_body,
                          policy=jax.checkpoint_policies.nothing_saveable)
    (ll_sum, correct), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h, targets, m))
    denom = jnp.maximum(m.sum(), 1.0)
    return -ll_sum / denom, correct / denom, denom
