"""Vision Transformer (ViT) classifier, pure functional JAX.

The non-LLM model family: patchify -> encoder stack -> CLS head. Same
TPU-first conventions as models.llama — stacked per-layer weights under
one ``lax.scan``, logical sharding axes resolved by
``parallel.sharding``, bf16 compute / fp32 params, per-layer remat.
Patchify is a single conv-as-matmul (unfold + einsum) so the whole
model is MXU matmuls.

Plugs into train.trainer via the same init_params /
param_logical_axes / loss_fn surface (batch: {"images": [B,H,W,C],
"labels": [B]}).

Reference parity: the reference ships vision training only as external
workload recipes (reference: examples/resnet_distributed_torch.yaml,
examples/torch_ddp_benchmark/ — torch DDP). In-tree equivalent per
SURVEY.md §2.11.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models import llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    channels: int = 3
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    remat_policy: str = "none"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, ff = self.d_model, self.d_ff
        attn = 4 * d * d
        mlp = 2 * d * ff + ff + d      # weights + b_up + b_down
        per_layer = attn + mlp + 4 * d  # + ln scales/biases
        patch = self.patch_size ** 2 * self.channels * d + d
        return (self.n_layers * per_layer + patch
                + (self.n_patches + 1) * d        # pos emb
                + 3 * d                           # cls token + final ln
                + d * self.num_classes + self.num_classes)


CONFIGS: Dict[str, ViTConfig] = {
    "vit-b16": ViTConfig(),
    "vit-s16": ViTConfig(d_model=384, n_layers=12, n_heads=6, d_ff=1536),
    "vit-tiny": ViTConfig(image_size=32, patch_size=8, num_classes=10,
                          d_model=64, n_layers=2, n_heads=4, d_ff=128),
}


def init_params(rng: jax.Array, cfg: ViTConfig) -> Params:
    d, ff, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    p = cfg.patch_size ** 2 * cfg.channels
    k = iter(jax.random.split(rng, 12))

    def init(key, shape, fan_in):
        return (jax.random.normal(key, shape, cfg.param_dtype)
                * (fan_in ** -0.5))

    return {
        "patch_embed": init(next(k), (p, d), p),
        "patch_bias": jnp.zeros((d,), cfg.param_dtype),
        "pos_embed": jax.random.normal(
            next(k), (cfg.n_patches + 1, d), cfg.param_dtype) * 0.02,
        "cls_token": jnp.zeros((d,), cfg.param_dtype),
        "blocks": {
            "ln1": jnp.ones((L, d), cfg.param_dtype),
            "ln1_b": jnp.zeros((L, d), cfg.param_dtype),
            "wqkv": init(next(k), (L, d, 3, cfg.n_heads, cfg.head_dim),
                         d),
            "wo": init(next(k), (L, cfg.n_heads, cfg.head_dim, d), d),
            "ln2": jnp.ones((L, d), cfg.param_dtype),
            "ln2_b": jnp.zeros((L, d), cfg.param_dtype),
            "w_up": init(next(k), (L, d, ff), d),
            "b_up": jnp.zeros((L, ff), cfg.param_dtype),
            "w_down": init(next(k), (L, ff, d), ff),
            "b_down": jnp.zeros((L, d), cfg.param_dtype),
        },
        "final_ln": jnp.ones((d,), cfg.param_dtype),
        "final_ln_b": jnp.zeros((d,), cfg.param_dtype),
        "head": init(next(k), (d, cfg.num_classes), d),
        "head_b": jnp.zeros((cfg.num_classes,), cfg.param_dtype),
    }


def param_logical_axes(cfg: ViTConfig) -> Params:
    return {
        "patch_embed": ("patch", "embed"),
        "patch_bias": ("embed",),
        "pos_embed": ("seq_static", "embed"),
        "cls_token": ("embed",),
        "blocks": {
            "ln1": ("layer", "embed"),
            "ln1_b": ("layer", "embed"),
            "wqkv": ("layer", "embed", None, "heads", "head_dim"),
            "wo": ("layer", "heads", "head_dim", "embed"),
            "ln2": ("layer", "embed"),
            "ln2_b": ("layer", "embed"),
            "w_up": ("layer", "embed", "mlp"),
            "b_up": ("layer", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
            "b_down": ("layer", "embed"),
        },
        "final_ln": ("embed",),
        "final_ln_b": ("embed",),
        "head": ("embed", "vocab"),
        "head_b": ("vocab",),
    }


def _layer_norm(x, scale, bias, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    out = (x - mu) * lax.rsqrt(var + eps)
    return out.astype(dtype) * scale.astype(dtype) + bias.astype(dtype)


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, n_patches, p*p*C] (unfold, no conv op)."""
    B, H, W, C = images.shape
    p = cfg.patch_size
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def encoder_layer(cfg: ViTConfig, x: jax.Array, layer: Params,
                  constrain=lambda x, axes: x) -> jax.Array:
    h = _layer_norm(x, layer["ln1"], layer["ln1_b"], cfg.norm_eps)
    qkv = jnp.einsum("bsd,dthk->tbshk", h,
                     layer["wqkv"].astype(cfg.dtype))
    q, k, v = qkv[0], qkv[1], qkv[2]
    q = constrain(q, ("batch", "seq", "heads", "head_dim"))
    from skypilot_tpu.ops import attention as attn_ops
    o = attn_ops.gqa_attention(q, k, v, causal=False)
    o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
    x = x + constrain(o, ("batch", "seq", "embed"))

    h = _layer_norm(x, layer["ln2"], layer["ln2_b"], cfg.norm_eps)
    u = (jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
         + layer["b_up"].astype(cfg.dtype))
    m = (jnp.einsum("bsf,fd->bsd", jax.nn.gelu(u),
                    layer["w_down"].astype(cfg.dtype))
         + layer["b_down"].astype(cfg.dtype))
    return x + constrain(m, ("batch", "seq", "embed"))


def forward(params: Params, images: jax.Array, cfg: ViTConfig,
            constrain=None, mesh=None, rules=None) -> jax.Array:
    """[B, H, W, C] float images -> logits [B, num_classes] fp32."""
    if constrain is None:
        constrain = lambda x, axes: x
    x = patchify(images.astype(cfg.dtype), cfg)
    x = (jnp.einsum("bsp,pd->bsd", x,
                    params["patch_embed"].astype(cfg.dtype))
         + params["patch_bias"].astype(cfg.dtype))
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype),
                           (x.shape[0], 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"))

    def body(carry, layer):
        return encoder_layer(cfg, carry, layer, constrain), None

    if cfg.remat:
        body = jax.checkpoint(body, policy=llama.remat_policy(cfg))
    x, _ = lax.scan(body, x, params["blocks"])
    x = _layer_norm(x, params["final_ln"], params["final_ln_b"],
                    cfg.norm_eps)
    logits = (x[:, 0] @ params["head"].astype(cfg.dtype)
              + params["head_b"].astype(cfg.dtype))
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: ViTConfig,
            constrain=None, mesh=None,
            rules=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Softmax cross-entropy. batch: {"images", "labels"}."""
    logits = forward(params, batch["images"], cfg, constrain, mesh, rules)
    labels = batch["labels"]
    logps = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logps, labels[:, None], axis=-1)[:, 0]
    loss = -jnp.mean(ll)
    acc = jnp.mean(jnp.argmax(logits, -1) == labels)
    return loss, {"loss": loss, "accuracy": acc,
                  "tokens": jnp.asarray(labels.shape[0], jnp.float32)}


def synthetic_batch(cfg: ViTConfig, batch_size: int,
                    seed: int = 0) -> Dict[str, jax.Array]:
    k1, k2 = jax.random.split(jax.random.key(seed))
    return {
        "images": jax.random.normal(
            k1, (batch_size, cfg.image_size, cfg.image_size,
                 cfg.channels), jnp.float32),
        "labels": jax.random.randint(k2, (batch_size,), 0,
                                     cfg.num_classes, dtype=jnp.int32),
    }
