"""Step-timing callbacks for user training loops (`bench show` feed).

Usage::

    import skypilot_tpu.callbacks as sky_callback
    sky_callback.init(total_steps=1000)
    for batch in data:
        with sky_callback.step():
            train_step(...)

Writes a rolling summary (steps done, avg step seconds, ETA) to
``$SKYPILOT_TPU_HOME/benchmark_summary.json`` (override with
``SKYTPU_CALLBACK_LOG_DIR``), which `bench show` and the jobs dashboard
read.

Reference parity: sky/callbacks/ (`sky_callback` pip package —
init/step timing API + Keras/Lightning/Transformers adapters feeding
`sky bench show`; SURVEY.md §2.10).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Optional

_state: Optional[Dict[str, Any]] = None

SUMMARY_FILE = "benchmark_summary.json"
_WRITE_EVERY_S = 10.0


def _log_dir() -> str:
    d = os.environ.get("SKYTPU_CALLBACK_LOG_DIR")
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    from skypilot_tpu.utils import paths
    return paths.home()


def init(total_steps: Optional[int] = None,
         warmup_steps: int = 1) -> None:
    """Start timing. ``warmup_steps`` are excluded from the average
    (compile time would poison TPU step stats)."""
    global _state
    _state = {
        "total_steps": total_steps,
        "warmup_steps": warmup_steps,
        "steps": 0,
        "timed_steps": 0,
        "timed_seconds": 0.0,
        "started_s": time.time(),
        "last_write_s": 0.0,
    }


@contextlib.contextmanager
def step():
    if _state is None:
        yield
        return
    begin = time.time()
    yield
    dur = time.time() - begin
    _state["steps"] += 1
    if _state["steps"] > _state["warmup_steps"]:
        _state["timed_steps"] += 1
        _state["timed_seconds"] += dur
    now = time.time()
    if now - _state["last_write_s"] >= _WRITE_EVERY_S:
        _state["last_write_s"] = now
        write_summary()


def summary() -> Dict[str, Any]:
    if _state is None:
        return {}
    avg = (_state["timed_seconds"] / _state["timed_steps"]
           if _state["timed_steps"] else None)
    total = _state["total_steps"]
    eta = (avg * (total - _state["steps"])
           if avg and total and total > _state["steps"] else None)
    return {
        "steps": _state["steps"],
        "total_steps": total,
        "avg_step_s": round(avg, 6) if avg else None,
        "eta_s": round(eta, 1) if eta else None,
        "elapsed_s": round(time.time() - _state["started_s"], 1),
    }


def write_summary() -> None:
    if _state is None:
        return
    path = os.path.join(_log_dir(), SUMMARY_FILE)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(summary(), f)
