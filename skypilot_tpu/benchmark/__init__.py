"""`bench`: launch a task on N candidate resources, compare cost/time."""
