"""Benchmark state: sqlite records of benchmark runs and their results.

Reference parity: sky/benchmark/benchmark_state.py (sqlite-backed
benchmark + benchmark_results tables powering `sky bench ls/show`).
"""

from __future__ import annotations

import contextlib
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import db, paths


def _db():
    """Context manager: connection that commits AND closes on exit."""
    path = os.path.join(paths.home(), "benchmark.db")
    conn = db.connect(path, timeout=30)
    conn.execute("""CREATE TABLE IF NOT EXISTS benchmarks (
        name TEXT PRIMARY KEY,
        task_yaml TEXT,
        launched_at INTEGER,
        status TEXT DEFAULT 'RUNNING')""")
    conn.execute("""CREATE TABLE IF NOT EXISTS benchmark_results (
        benchmark TEXT,
        cluster TEXT,
        resources TEXT,
        price_per_hour REAL,
        duration_s REAL,
        metrics TEXT,
        status TEXT DEFAULT 'RUNNING',
        PRIMARY KEY (benchmark, cluster))""")
    conn.commit()

    @contextlib.contextmanager
    def _ctx():
        try:
            with conn:     # transaction: commit/rollback
                yield conn
        finally:
            conn.close()

    return _ctx()


def add_benchmark(name: str, task_yaml: str) -> None:
    with _db() as c:
        c.execute(
            "INSERT OR REPLACE INTO benchmarks (name, task_yaml,"
            " launched_at, status) VALUES (?,?,?,'RUNNING')",
            (name, task_yaml, int(time.time())))


def add_result(benchmark: str, cluster: str, resources: str,
               price_per_hour: float) -> None:
    with _db() as c:
        c.execute(
            "INSERT OR REPLACE INTO benchmark_results (benchmark, cluster,"
            " resources, price_per_hour, duration_s, metrics, status)"
            " VALUES (?,?,?,?,0,'{}','RUNNING')",
            (benchmark, cluster, resources, price_per_hour))


def finish_result(benchmark: str, cluster: str, duration_s: float,
                  metrics: Optional[Dict[str, Any]] = None,
                  status: str = "FINISHED") -> None:
    with _db() as c:
        c.execute(
            "UPDATE benchmark_results SET duration_s=?, metrics=?, status=?"
            " WHERE benchmark=? AND cluster=?",
            (duration_s, json.dumps(metrics or {}), status, benchmark,
             cluster))


def set_benchmark_status(name: str, status: str) -> None:
    with _db() as c:
        c.execute("UPDATE benchmarks SET status=? WHERE name=?",
                  (status, name))


def list_benchmarks() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute("SELECT name, task_yaml, launched_at, status"
                         " FROM benchmarks").fetchall()
    return [{"name": n, "task_yaml": t, "launched_at": la, "status": s}
            for n, t, la, s in rows]


def get_results(benchmark: str) -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT cluster, resources, price_per_hour, duration_s,"
            " metrics, status FROM benchmark_results WHERE benchmark=?",
            (benchmark,)).fetchall()
    return [{"cluster": cl, "resources": r, "price_per_hour": p,
             "duration_s": d, "metrics": json.loads(m or "{}"),
             "status": s}
            for cl, r, p, d, m, s in rows]


def delete_benchmark(name: str) -> None:
    with _db() as c:
        c.execute("DELETE FROM benchmarks WHERE name=?", (name,))
        c.execute("DELETE FROM benchmark_results WHERE benchmark=?",
                  (name,))
