"""Benchmark orchestration: launch one task on N resource candidates in
parallel, wait, record cost/time, summarize.

Reference parity: sky/benchmark/benchmark_utils.py (launch N resource
variants in parallel clusters named sky-bench-..., collect + summarize
for `sky bench show`; SURVEY.md §2.10).
"""

from __future__ import annotations

import concurrent.futures as cf
import copy
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import execution, optimizer
from skypilot_tpu.benchmark import benchmark_state
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

CLUSTER_PREFIX = "tpu-bench-"


def _candidate_tasks(task: Task,
                     candidates: List[Dict[str, Any]]) -> List[Task]:
    out = []
    for cand in candidates:
        t = copy.deepcopy(task)
        base = t.resources[0].to_yaml_config() if t.resources else {}
        base.update(cand)
        t.set_resources(Resources.from_yaml_config(base))
        out.append(t)
    return out


def launch_benchmark(benchmark: str, task: Task,
                     candidates: List[Dict[str, Any]],
                     wait: bool = True,
                     teardown: bool = True) -> List[Dict[str, Any]]:
    """Run ``task`` once per candidate resource dict; record results.

    Each candidate launches a cluster ``tpu-bench-<benchmark>-<i>``;
    cost/time come from the optimizer price and the measured job wall
    time. Returns the result rows (status RUNNING when wait=False).
    """
    benchmark_state.add_benchmark(benchmark,
                                  str(task.to_yaml_config()))
    tasks = _candidate_tasks(task, candidates)

    def _one(i: int, t: Task) -> Dict[str, Any]:
        cluster = f"{CLUSTER_PREFIX}{benchmark}-{i}"
        start = time.time()
        status = "FINISHED"
        price = 0.0
        error = None
        try:
            launchable = optimizer.optimize_task(t)
            price = (launchable.price or 0.0) * max(t.num_nodes, 1)
            benchmark_state.add_result(benchmark, cluster, str(launchable),
                                       price)
            job_id, handle = execution.launch(t, cluster_name=cluster,
                                              detach_run=True)
            if not wait:
                # Leave RUNNING (and the cluster up): duration/teardown
                # are meaningless until the job actually finishes.
                return {"cluster": cluster, "duration_s": 0.0,
                        "price_per_hour": price, "status": "RUNNING"}
            if job_id is not None:
                from skypilot_tpu.backend import TpuVmBackend
                from skypilot_tpu.runtime.job_queue import JobStatus
                final = TpuVmBackend().wait_job(handle, job_id,
                                                timeout=float("inf"))
                if final is not JobStatus.SUCCEEDED:
                    status = "FAILED"
                    error = f"job ended {final.value}"
        except Exception as e:  # noqa: BLE001 — other candidates continue
            status = "FAILED"
            error = f"{type(e).__name__}: {e}"
            benchmark_state.add_result(benchmark, cluster, "-", price)
        duration = time.time() - start
        metrics = {"error": error} if error else {}
        benchmark_state.finish_result(benchmark, cluster, duration,
                                      metrics=metrics, status=status)
        if teardown:
            try:
                from skypilot_tpu import core
                core.down(cluster)
            except Exception:  # noqa: BLE001
                pass
        return {"cluster": cluster, "duration_s": duration,
                "price_per_hour": price, "status": status,
                "error": error}

    with cf.ThreadPoolExecutor(max_workers=max(len(tasks), 1)) as pool:
        results = list(pool.map(lambda it: _one(*it), enumerate(tasks)))
    any_running = any(r["status"] == "RUNNING" for r in results)
    benchmark_state.set_benchmark_status(
        benchmark, "RUNNING" if any_running else "FINISHED")
    return results


def summarize(benchmark: str) -> List[Dict[str, Any]]:
    """Result rows + derived $ cost, cheapest-first."""
    rows = benchmark_state.get_results(benchmark)
    for r in rows:
        r["cost"] = round(r["price_per_hour"] * r["duration_s"] / 3600.0, 6)
    return sorted(rows, key=lambda r: (r["status"] != "FINISHED",
                                       r["cost"]))
