"""Usage telemetry: schema'd per-run messages with an opt-out.

Default sink is a local JSONL file (``~/.skypilot_tpu/usage/``). An
HTTP endpoint can be configured (``SKYTPU_USAGE_ENDPOINT``); with no
endpoint nothing leaves the machine — the schema/collection machinery
is what the framework standardizes, not any phone-home default.

Opt-out: ``SKYTPU_DISABLE_USAGE_COLLECTION=1``.

Reference parity: sky/usage/usage_lib.py (MessageToReport:49 schema'd
heartbeat + per-run usage to a Loki endpoint :341,
SKYPILOT_DISABLE_USAGE_COLLECTION; SURVEY.md §5 Telemetry).
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import time
import uuid
from typing import Any, Dict, Optional

from skypilot_tpu.observability import metrics
from skypilot_tpu.utils import paths

DISABLE_ENV = "SKYTPU_DISABLE_USAGE_COLLECTION"
ENDPOINT_ENV = "SKYTPU_USAGE_ENDPOINT"
# HTTP sends are strictly bounded: a dead/blackholed endpoint must
# never stall an entrypoint past this (connect + read, urllib's
# combined timeout), after which the record falls back to the local
# file sink.
TIMEOUT_ENV = "SKYTPU_USAGE_TIMEOUT"
DEFAULT_SEND_TIMEOUT_S = 1.0

USAGE_REPORTS = metrics.counter(
    "skytpu_usage_reports_total",
    "Usage records written, by sink (http endpoint or local file)",
    labelnames=("sink",))
USAGE_SEND_FAILURES = metrics.counter(
    "skytpu_usage_send_failures_total",
    "Usage HTTP endpoint sends that failed (swallowed; record fell "
    "back to the local file sink)")

_run_id: Optional[str] = None


def disabled() -> bool:
    return os.environ.get(DISABLE_ENV, "0") == "1"


def run_id() -> str:
    global _run_id
    if _run_id is None:
        _run_id = uuid.uuid4().hex[:12]
    return _run_id


class MessageToReport:
    """One schema'd usage record, filled over the life of an entrypoint."""

    SCHEMA_VERSION = 1

    def __init__(self, kind: str):
        self.kind = kind
        self.start_s = time.time()
        self.fields: Dict[str, Any] = {}
        self.exception: Optional[str] = None

    def set(self, key: str, value: Any) -> None:
        self.fields[key] = value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema_version": self.SCHEMA_VERSION,
            "kind": self.kind,
            "run_id": run_id(),
            "start_s": round(self.start_s, 3),
            "duration_s": round(time.time() - self.start_s, 3),
            "exception": self.exception,
            **self.fields,
        }


def _send_timeout() -> float:
    try:
        return float(os.environ.get(TIMEOUT_ENV,
                                    DEFAULT_SEND_TIMEOUT_S))
    except ValueError:
        return DEFAULT_SEND_TIMEOUT_S


def _sink(record: Dict[str, Any]) -> None:
    endpoint = os.environ.get(ENDPOINT_ENV)
    if endpoint:
        try:
            import urllib.request
            req = urllib.request.Request(
                endpoint, data=json.dumps(record).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=_send_timeout())
            USAGE_REPORTS.labels(sink="http").inc()
            return
        except Exception:  # noqa: BLE001 — telemetry must never break ops
            # Swallowed but COUNTED: a silently dead endpoint would
            # otherwise be indistinguishable from opted-out telemetry.
            USAGE_SEND_FAILURES.inc()
    usage_dir = os.path.join(paths.home(), "usage")
    os.makedirs(usage_dir, exist_ok=True)
    with open(os.path.join(usage_dir, "usage.jsonl"), "a",
              encoding="utf-8") as f:
        f.write(json.dumps(record) + "\n")
    USAGE_REPORTS.labels(sink="file").inc()


def report(message: MessageToReport) -> None:
    if disabled():
        return
    try:
        _sink(message.to_dict())
    except Exception:  # noqa: BLE001
        pass


@contextlib.contextmanager
def entrypoint_context(kind: str, **fields: Any):
    """Collect timing + outcome for one API entrypoint."""
    msg = MessageToReport(kind)
    for k, v in fields.items():
        msg.set(k, v)
    try:
        yield msg
    except BaseException as e:
        msg.exception = type(e).__name__
        raise
    finally:
        report(msg)


def entrypoint(fn):
    """Decorator form of ``entrypoint_context``."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with entrypoint_context(f"{fn.__module__}.{fn.__qualname__}"):
            return fn(*args, **kwargs)

    return wrapper
