"""Usage telemetry (schema'd, opt-out, local-sink by default)."""
