"""SSH keypair management + per-cloud public-key registration.

Reference parity: sky/authentication.py (keypair generation at
``~/.ssh/sky-key``, per-cloud pubkey upload). Here: an ed25519 keypair
at ``~/.ssh/skypilot_tpu`` generated once with ssh-keygen (every image
ships it), and GCP registration via project OS Login metadata — the
TPU-VM path uses the project-wide ``ssh-keys`` metadata entry exactly
as ``gcloud compute tpus tpu-vm ssh`` does.
"""

from __future__ import annotations

import functools
import os
import subprocess
from typing import Tuple

_KEY_PATH = "~/.ssh/skypilot_tpu"


def key_paths() -> Tuple[str, str]:
    priv = os.path.expanduser(
        os.environ.get("SKYPILOT_TPU_SSH_KEY", _KEY_PATH))
    return priv, priv + ".pub"


@functools.lru_cache(maxsize=None)
def get_or_generate_keys() -> Tuple[str, str]:
    """Return (private, public) key paths, generating once if absent."""
    priv, pub = key_paths()
    if not os.path.exists(priv):
        os.makedirs(os.path.dirname(priv), mode=0o700, exist_ok=True)
        subprocess.run(
            ["ssh-keygen", "-t", "ed25519", "-N", "", "-q", "-f", priv,
             "-C", "skypilot-tpu"],
            check=True, capture_output=True)
        os.chmod(priv, 0o600)
    if not os.path.exists(pub):
        out = subprocess.run(["ssh-keygen", "-y", "-f", priv],
                             check=True, capture_output=True, text=True)
        with open(pub, "w") as f:
            f.write(out.stdout)
    return priv, pub


def public_key_openssh() -> str:
    _, pub = get_or_generate_keys()
    with open(pub) as f:
        return f.read().strip()


def get_user_identity() -> dict:
    """Stable {"id", "name"} for the invoking user.

    Reference parity: sky/global_user_state.py:110 (users) +
    backend_utils.py get_user_identities — identity derives from who
    holds the key material, not just the login name, so two people
    sharing a UNIX account but different sky keys stay distinct.
    ``SKYPILOT_TPU_USER`` overrides (used by the API server to carry
    the CLIENT's identity into its request workers).
    """
    import getpass
    import hashlib

    uid = os.environ.get("SKYPILOT_TPU_USER_ID")
    if uid:
        # Set by the API server's executor: the request worker acts AS
        # the submitting client, exactly (no re-hashing).
        return {"id": uid,
                "name": os.environ.get("SKYPILOT_TPU_USER_NAME", uid)}
    override = os.environ.get("SKYPILOT_TPU_USER")
    if override:
        return {"id": hashlib.sha256(override.encode()).hexdigest()[:16],
                "name": override}
    try:
        name = getpass.getuser()
    except Exception:  # noqa: BLE001 — no passwd entry in containers
        name = "unknown"
    try:
        pub = public_key_openssh()
    except Exception:  # noqa: BLE001 — no ssh-keygen available
        pub = ""
    uid = hashlib.sha256(f"{name}:{pub}".encode()).hexdigest()[:16]
    return {"id": uid, "name": name}


def _gcp_http(method: str, url: str, body=None) -> dict:
    import json
    import urllib.request

    from skypilot_tpu.provision import gcp_auth

    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={"Authorization": f"Bearer {gcp_auth.get_access_token()}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read() or b"{}")


def setup_gcp_authentication(project: str, ssh_user: str = "skypilot") -> str:
    """Ensure our pubkey is in the project-wide ssh-keys metadata.

    Returns the ssh user name. Idempotent: a key already present is
    left alone. Same mechanism as ``gcloud compute tpus tpu-vm ssh``
    (TPU-VMs honor project-wide compute metadata ssh-keys).
    """
    key = public_key_openssh()
    entry = f"{ssh_user}:{key}"

    base = f"https://compute.googleapis.com/compute/v1/projects/{project}"
    meta = _gcp_http("GET", base).get("commonInstanceMetadata", {})
    items = meta.get("items", [])
    ssh_item = next((i for i in items if i["key"] == "ssh-keys"), None)
    existing = ssh_item["value"] if ssh_item else ""
    if entry in existing:
        return ssh_user
    new_value = (existing + "\n" + entry).strip()
    if ssh_item:
        ssh_item["value"] = new_value
    else:
        items.append({"key": "ssh-keys", "value": new_value})
    body = {"fingerprint": meta.get("fingerprint"), "items": items}
    _gcp_http("POST", f"{base}/setCommonInstanceMetadata", body)
    return ssh_user
