"""Azure ARM credentials, zero-SDK.

Reference parity: sky/adaptors/azure.py + sky/clouds/azure.py
check_credentials — the reference rides the azure-mgmt SDKs; here the
credential IS an ARM bearer token obtained from the ``az`` CLI the
repo's Azure Blob store already depends on (``az account
get-access-token``), or from ``AZURE_ACCESS_TOKEN`` +
``AZURE_SUBSCRIPTION_ID`` directly (CI / workload identity setups that
mint tokens out-of-band).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Optional, Tuple

_cached: Optional[dict] = None
_cached_until = 0.0


class AzureCredentials:
    def __init__(self, token: str, subscription: str):
        self.token = token
        self.subscription = subscription


def load_credentials() -> Optional[AzureCredentials]:
    """Bearer token + subscription id, or None when unauthenticated.
    az-CLI tokens are cached until shortly before their expiry."""
    global _cached, _cached_until
    tok = os.environ.get("AZURE_ACCESS_TOKEN")
    sub = os.environ.get("AZURE_SUBSCRIPTION_ID")
    if tok and sub:
        return AzureCredentials(tok, sub)
    if _cached is not None and time.time() < _cached_until:
        return AzureCredentials(_cached["accessToken"],
                                _cached["subscription"])
    try:
        out = subprocess.run(
            ["az", "account", "get-access-token", "--output", "json"],
            capture_output=True, text=True, timeout=30)
    except (FileNotFoundError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    data = json.loads(out.stdout)
    _cached = data
    # expiresOn is local-format; re-fetch conservatively after 10 min
    # rather than parsing its locale-dependent shape.
    _cached_until = time.time() + 600
    return AzureCredentials(data["accessToken"], data["subscription"])


def check_credentials() -> Tuple[bool, str]:
    creds = load_credentials()
    if creds is None:
        return False, ("no Azure credentials (run `az login`, or set "
                       "AZURE_ACCESS_TOKEN + AZURE_SUBSCRIPTION_ID)")
    return True, f"subscription {creds.subscription}"
