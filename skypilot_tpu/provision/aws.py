"""AWS EC2 provisioning over the Query API, zero-SDK.

Reference parity: sky/provision/aws/instance.py (run/stop/terminate/
query instances, security-group port exposure at sky/provision/aws/
instance.py open_ports) — redesigned in the same style as this repo's
GCP provider: plain signed HTTPS (SigV4, see aws_auth.py) instead of
boto3, an injectable transport so the whole module is unit-testable
offline against canned XML (tests/test_aws_provision.py), and the
uniform functional provision API.

AWS carries the GPU/CPU side of the cross-cloud story (no TPUs): the
optimizer arbitrates p4d/p5/g5 against GCP A100/H100/L4 rows, and the
failover loop can block one cloud wholesale and land on the other.

Cluster model: instances are tagged ``skypilot-cluster=<name>`` (the
idempotency key — run_instances reuses/restarts whatever already
carries the tag), one self-referencing security group per cluster
carries SSH + user port exposure, and the cluster keypair is the same
``~/.ssh/sky-key`` every other provider uses.
"""

from __future__ import annotations

import base64
import re
import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET
from typing import Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import aws_auth
from skypilot_tpu.utils import retry
from skypilot_tpu.provision import Feature as _F
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)
from skypilot_tpu.resources import extract_docker_image
from skypilot_tpu.utils import command_runner

API_VERSION = "2016-11-15"
CLUSTER_TAG = "skypilot-cluster"
SSH_USER = "ubuntu"
KEYPAIR_PREFIX = "sky-key"

# Canonical Ubuntu 22.04 LTS amd64 server images, resolved at call time
# via DescribeImages (owner = Canonical's account) so the catalog never
# hardcodes per-region AMI ids (reference: the aws catalog ships an
# image table; a DescribeImages lookup is the zero-catalog equivalent).
UBUNTU_OWNER = "099720109477"
UBUNTU_NAME_FILTER = \
    "ubuntu/images/hvm-ssd/ubuntu-jammy-22.04-amd64-server-*"

FEATURES = frozenset(_F)

# transport(action, params, region) -> raw XML response text.
Transport = Callable[[str, Dict[str, str], str], str]
_transport: Optional[Transport] = None


def set_transport(fn: Optional[Transport]) -> None:
    """Inject a fake EC2 API (tests) or reset to real HTTPS (None)."""
    global _transport
    _transport = fn


def _api(action: str, params: Dict[str, str], region: str) -> ET.Element:
    """One signed EC2 Query API call; returns the parsed XML root with
    namespaces stripped (EC2 stamps every element with the doc ns)."""
    full = {"Action": action, "Version": API_VERSION, **params}
    if _transport is not None:
        text = _transport(action, full, region)
    else:
        creds = aws_auth.load_credentials()
        if creds is None:
            raise exceptions.NoCloudAccessError(
                "no AWS credentials (set AWS_ACCESS_KEY_ID/"
                "AWS_SECRET_ACCESS_KEY or ~/.aws/credentials)")
        host = f"ec2.{region}.amazonaws.com"
        url, headers, body = aws_auth.sign_request(
            creds, "POST", host, "/", full, region=region, service="ec2")
        req = urllib.request.Request(url, data=body, headers=headers,
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                text = resp.read().decode()
        except urllib.error.HTTPError as e:
            raise _map_api_error(e.code, e.read().decode(errors="replace"))
    root = ET.fromstring(text)
    _strip_ns(root)
    if root.tag == "Response":           # error document (fake transport
        err = root.find(".//Error")      # may return it with HTTP 200)
        code = err.findtext("Code", "") if err is not None else ""
        msg = err.findtext("Message", "") if err is not None else text
        raise _map_error_code(code, msg)
    return root


def _strip_ns(elem: ET.Element) -> None:
    for e in elem.iter():
        if "}" in e.tag:
            e.tag = e.tag.split("}", 1)[1]


def _map_api_error(http_code: int, body: str) -> Exception:
    try:
        root = ET.fromstring(body)
        _strip_ns(root)
        err = root.find(".//Error")
        if err is not None:
            return _map_error_code(err.findtext("Code", ""),
                                   err.findtext("Message", body))
    except ET.ParseError:
        pass
    return exceptions.ResourcesUnavailableError(
        f"EC2 API error ({http_code}): {body[:500]}")


def _map_error_code(code: str, message: str) -> Exception:
    """EC2 error code -> the failover taxonomy (scopes drive the
    blocklist: capacity = zone-blockable, quota = region-blockable)."""
    err: Exception
    if code in ("InsufficientInstanceCapacity", "InsufficientHostCapacity",
                "SpotMaxPriceTooLow", "InsufficientCapacity",
                "Unsupported"):
        err = exceptions.CapacityError(f"EC2 capacity: {code}: {message}")
    elif code in ("InstanceLimitExceeded", "VcpuLimitExceeded",
                  "MaxSpotInstanceCountExceeded", "RequestLimitExceeded"):
        err = exceptions.QuotaExceededError(
            f"EC2 quota: {code}: {message}")
    elif code in ("AuthFailure", "UnauthorizedOperation",
                  "OptInRequired"):
        err = exceptions.NoCloudAccessError(
            f"EC2 auth: {code}: {message}")
    elif code.endswith(".Duplicate"):
        err = DuplicateError(f"EC2: {code}: {message}")
    elif code.startswith("InvalidGroup") or code.startswith(
            "InvalidKeyPair") or code.endswith(".NotFound"):
        err = exceptions.ClusterNotUpError(f"EC2: {code}: {message}")
    else:
        err = exceptions.ResourcesUnavailableError(
            f"EC2: {code}: {message}")
    err.ec2_code = code
    return err


class DuplicateError(Exception):
    """Resource already exists — the idempotent paths treat this as
    success (CreateSecurityGroup / ImportKeyPair on re-launch)."""


_ZONE_RE = re.compile(r"^([a-z]+-[a-z]+-\d+)")


def _region_of_zone(zone: str) -> str:
    """'us-east-1a' -> 'us-east-1'. Regex, not rstrip: Local/Wavelength
    zone names ('us-west-2-lax-1a') carry extra dashed segments that a
    letter-strip would fold into a bogus region."""
    m = _ZONE_RE.match(zone)
    if not m:
        raise ValueError(f"unparseable AWS zone {zone!r}")
    return m.group(1)


def _numbered(prefix: str, values) -> Dict[str, str]:
    return {f"{prefix}.{i + 1}": v for i, v in enumerate(values)}


# -- instance listing -------------------------------------------------------

def _list_instances(cluster_name: str, region: str,
                    states=("pending", "running", "stopping", "stopped")
                    ) -> List[Dict]:
    params = {
        "Filter.1.Name": f"tag:{CLUSTER_TAG}",
        "Filter.1.Value.1": cluster_name,
        **_numbered("Filter.2.Value", states),
    }
    params["Filter.2.Name"] = "instance-state-name"
    root = _api("DescribeInstances", params, region)
    out = []
    for inst in root.iter("instancesSet"):
        for item in inst.findall("item"):
            if item.find("instanceId") is None:
                continue
            out.append({
                "id": item.findtext("instanceId"),
                "state": item.findtext("instanceState/name"),
                "internal_ip": item.findtext("privateIpAddress") or "",
                "external_ip": item.findtext("ipAddress"),
                "launch_index": int(item.findtext("amiLaunchIndex", "0")),
                "sg_id": item.findtext(
                    "groupSet/item/groupId"),
            })
    return out


# -- security group / ports -------------------------------------------------

def _sg_name(cluster_name: str) -> str:
    return f"sky-sg-{cluster_name}"


def _ensure_security_group(cluster_name: str, region: str) -> str:
    """Per-cluster SG: SSH from anywhere + all traffic within the group
    (gang hosts reach each other on every port, like the GCP intra-
    cluster allowance). Idempotent via .Duplicate."""
    try:
        root = _api("CreateSecurityGroup", {
            "GroupName": _sg_name(cluster_name),
            "GroupDescription": f"skypilot_tpu cluster {cluster_name}",
        }, region)
        sg_id = root.findtext("groupId")
    except DuplicateError:
        sg_id = _find_security_group(cluster_name, region)
    if sg_id is None:
        raise exceptions.ResourcesUnavailableError(
            f"security group for {cluster_name} neither created nor found")
    for rule in (
            {"IpPermissions.1.IpProtocol": "tcp",
             "IpPermissions.1.FromPort": "22",
             "IpPermissions.1.ToPort": "22",
             "IpPermissions.1.IpRanges.1.CidrIp": "0.0.0.0/0"},
            {"IpPermissions.1.IpProtocol": "-1",
             "IpPermissions.1.UserIdGroupPairs.1.GroupId": sg_id}):
        try:
            _api("AuthorizeSecurityGroupIngress",
                 {"GroupId": sg_id, **rule}, region)
        except DuplicateError:
            pass
    return sg_id


def _find_security_group(cluster_name: str, region: str) -> Optional[str]:
    try:
        root = _api("DescribeSecurityGroups", {
            "Filter.1.Name": "group-name",
            "Filter.1.Value.1": _sg_name(cluster_name),
        }, region)
    except exceptions.ClusterNotUpError:
        return None
    return root.findtext(".//securityGroupInfo/item/groupId") or \
        root.findtext(".//item/groupId")


def open_ports(cluster_name: str, ports: List[int],
               zone: Optional[str] = None) -> None:
    """Expose task/serve ports: one tcp ingress rule per port on the
    cluster SG (reference: sky/provision/aws/instance.py open_ports).
    Idempotent — re-authorizing an existing rule is a .Duplicate.

    ``zone`` is REQUIRED: security groups are regional, and guessing
    the region from AWS_DEFAULT_REGION would authorize ports on (or
    create!) a wrong-region SG while the cluster's real ports stay
    closed."""
    if not zone:
        raise ValueError(
            "aws.open_ports needs the cluster's zone to locate its "
            "regional security group")
    region = _region_of_zone(zone)
    sg_id = _find_security_group(cluster_name, region)
    if sg_id is None:
        # Creating a fresh SG here would attach to NOTHING — the call
        # would "succeed" while the cluster's real ports stay closed
        # (wrong zone passed, or cluster already gone). Fail instead.
        raise exceptions.ClusterNotUpError(
            f"no security group for cluster {cluster_name!r} in "
            f"{region} — wrong zone, or the cluster is terminated")
    for port in ports:
        try:
            _api("AuthorizeSecurityGroupIngress", {
                "GroupId": sg_id,
                "IpPermissions.1.IpProtocol": "tcp",
                "IpPermissions.1.FromPort": str(port),
                "IpPermissions.1.ToPort": str(port),
                "IpPermissions.1.IpRanges.1.CidrIp": "0.0.0.0/0",
            }, region)
        except DuplicateError:
            pass


def cleanup_ports(cluster_name: str, zone: Optional[str] = None) -> None:
    """The SG is deleted with the cluster (terminate_instances); nothing
    to do for port-only cleanup — rules die with the group."""


# -- keypair ----------------------------------------------------------------

def _ensure_keypair(region: str) -> str:
    """Import the local public key; the keypair NAME embeds a hash of
    the key material, so a regenerated or different-machine key gets a
    fresh name instead of silently colliding with a stale 'sky-key'
    import (whose instances the local private key could never reach —
    the reference hashes material into the name for the same reason)."""
    import hashlib

    from skypilot_tpu import authentication
    _, pub = authentication.get_or_generate_keys()
    with open(pub) as f:
        content = f.read().strip()
    digest = hashlib.sha256(content.encode()).hexdigest()[:10]
    name = f"{KEYPAIR_PREFIX}-{digest}"
    material = base64.b64encode(content.encode()).decode()
    try:
        _api("ImportKeyPair", {
            "KeyName": name,
            "PublicKeyMaterial": material,
        }, region)
    except DuplicateError:
        pass
    return name


# -- AMI --------------------------------------------------------------------

def _resolve_image(config: ProvisionConfig, region: str) -> str:
    if config.image_id and not extract_docker_image(config.image_id):
        return config.image_id
    root = _api("DescribeImages", {
        "Owner.1": UBUNTU_OWNER,
        "Filter.1.Name": "name",
        "Filter.1.Value.1": UBUNTU_NAME_FILTER,
        "Filter.2.Name": "state",
        "Filter.2.Value.1": "available",
    }, region)
    images = []
    for item in root.iter("item"):
        ami = item.findtext("imageId")
        if ami:
            images.append((item.findtext("creationDate", ""), ami))
    if not images:
        raise exceptions.ResourcesUnavailableError(
            f"no Ubuntu 22.04 AMI found in {region}")
    return max(images)[1]   # latest creationDate


# -- provision API ----------------------------------------------------------

def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    """Create or resume the cluster's instances. Idempotent by the
    cluster tag: stopped instances restart, missing ones are created,
    running ones are left alone."""
    zone = config.zone
    region = _region_of_zone(zone)
    want = config.num_nodes * config.hosts_per_node
    existing = _list_instances(config.cluster_name, region)
    record = ProvisionRecord(provider="aws",
                             cluster_name=config.cluster_name, zone=zone)

    # An instance in 'stopping' (autostop just fired, user relaunches
    # immediately) rejects StartInstances with IncorrectInstanceState —
    # which would read as a zone failure and split the cluster across
    # a failover. Wait out the transition first.
    deadline = time.monotonic() + 300
    while any(i["state"] == "stopping" for i in existing):
        if time.monotonic() >= deadline:
            raise exceptions.ResourcesUnavailableError(
                f"instances of {config.cluster_name} stuck in 'stopping'")
        time.sleep(3 if _transport is None else 0)
        existing = _list_instances(config.cluster_name, region)
    stopped = [i for i in existing if i["state"] == "stopped"]
    alive = [i for i in existing
             if i["state"] in ("pending", "running")]
    if stopped:
        _api("StartInstances",
             _numbered("InstanceId", [i["id"] for i in stopped]), region)
        record.resumed = True
        alive += stopped
    missing = want - len(alive)
    if missing > 0:
        key_name = _ensure_keypair(region)
        sg_id = _ensure_security_group(config.cluster_name, region)
        params = {
            "ImageId": _resolve_image(config, region),
            "InstanceType": config.instance_type,
            "MinCount": str(missing),   # gang semantics: all-or-nothing
            "MaxCount": str(missing),
            "KeyName": key_name,
            "Placement.AvailabilityZone": zone,
            "SecurityGroupId.1": sg_id,
            "BlockDeviceMapping.1.DeviceName": "/dev/sda1",
            "BlockDeviceMapping.1.Ebs.VolumeSize": str(config.disk_size),
            "BlockDeviceMapping.1.Ebs.VolumeType": "gp3",
            "TagSpecification.1.ResourceType": "instance",
            "TagSpecification.1.Tag.1.Key": CLUSTER_TAG,
            "TagSpecification.1.Tag.1.Value": config.cluster_name,
        }
        if config.use_spot:
            params["InstanceMarketOptions.MarketType"] = "spot"
            params["InstanceMarketOptions.SpotOptions."
                   "InstanceInterruptionBehavior"] = "terminate"
        for i, (k, v) in enumerate(sorted(config.labels.items())):
            params[f"TagSpecification.1.Tag.{i + 2}.Key"] = k
            params[f"TagSpecification.1.Tag.{i + 2}.Value"] = v
        root = _api("RunInstances", params, region)
        for item in root.iter("item"):
            iid = item.findtext("instanceId")
            if iid:
                record.created_instance_ids.append(iid)
    if config.ports:
        open_ports(config.cluster_name, list(config.ports), zone)
    return record


def wait_instances(cluster_name: str, zone: str,
                   timeout: float = 600) -> None:
    region = _region_of_zone(zone)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        insts = _list_instances(cluster_name, region)
        if insts and all(i["state"] == "running" for i in insts):
            return
        time.sleep(2 if _transport is None else 0)
    raise exceptions.ResourcesUnavailableError(
        f"instances of {cluster_name} not running after {timeout}s")


def stop_instances(cluster_name: str, zone: str) -> None:
    region = _region_of_zone(zone)
    ids = [i["id"] for i in _list_instances(cluster_name, region)
           if i["state"] in ("pending", "running")]
    if ids:
        _api("StopInstances", _numbered("InstanceId", ids), region)


def terminate_instances(cluster_name: str, zone: str) -> None:
    region = _region_of_zone(zone)
    insts = _list_instances(cluster_name, region)
    if insts:
        _api("TerminateInstances",
             _numbered("InstanceId", [i["id"] for i in insts]), region)
    # The SG can only delete once its instances are gone; EC2 keeps a
    # terminating instance attached for a while, so retry briefly and
    # leave an orphan SG (free, reused on relaunch) rather than fail
    # the teardown. The attached group id rides the instance listing;
    # fall back to the name lookup when no instance remained.
    sg_id = next((i["sg_id"] for i in insts if i["sg_id"]), None) \
        or _find_security_group(cluster_name, region)
    if sg_id is None:
        return
    try:
        retry.call(
            lambda: _api("DeleteSecurityGroup", {"GroupId": sg_id}, region),
            name="aws.delete_sg",
            policy=retry.RetryPolicy(
                max_attempts=1 if _transport is not None else 30,
                backoff_base_s=5.0, backoff_multiplier=1.0,
                backoff_max_s=5.0, jitter=0.0))
    except Exception:  # noqa: BLE001 — DependencyViolation until gone
        return


def query_instances(cluster_name: str, zone: str) -> str:
    region = _region_of_zone(zone)
    insts = _list_instances(cluster_name, region)
    if not insts:
        return "NOT_FOUND"
    states = {i["state"] for i in insts}
    if states <= {"pending", "running"}:
        return "UP"
    if states <= {"stopped", "stopping"}:
        return "STOPPED"
    return "PARTIAL"


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    region = _region_of_zone(zone)
    insts = [i for i in _list_instances(cluster_name, region)
             if i["state"] in ("pending", "running")]
    if not insts:
        raise exceptions.ClusterNotUpError(
            f"no running instances for {cluster_name}")
    # Stable host order: launch index, then instance id (fresh launches
    # share one reservation; resumes fall back to id order).
    insts.sort(key=lambda i: (i["launch_index"], i["id"]))
    hosts = [HostInfo(host_id=n, node_id=n, worker_id=0,
                      internal_ip=i["internal_ip"],
                      external_ip=i["external_ip"],
                      ssh_user=SSH_USER, ssh_port=22)
             for n, i in enumerate(insts)]
    return ClusterInfo(cluster_name=cluster_name, provider="aws",
                       zone=zone, hosts=hosts,
                       ssh_key_path="~/.ssh/sky-key",
                       metadata={"instance_ids": [i["id"] for i in insts]})


def get_command_runners(info: ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    runners = []
    for h in info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(command_runner.SSHRunner(
            ip=ip, user=h.ssh_user or SSH_USER,
            key_path=info.ssh_key_path or "~/.ssh/sky-key",
            host_id=h.host_id, port=h.ssh_port))
    return runners


def check_credentials():
    return aws_auth.check_credentials()
