"""Uniform functional provision API, dispatched per provider.

Reference parity: sky/provision/__init__.py (_route_to_cloud_impl:38 —
``run_instances(provider, ...)`` resolves to
``sky.provision.<provider>.<fn>``). Same shape here: every provider
module exports the same function set; the backend never imports a
provider directly.
"""

from __future__ import annotations

import importlib
from typing import Any

from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)

_PROVIDERS = {}


def _impl(provider: str):
    if provider not in _PROVIDERS:
        _PROVIDERS[provider] = importlib.import_module(
            f"skypilot_tpu.provision.{provider}")
    return _PROVIDERS[provider]


def run_instances(provider: str, config: ProvisionConfig) -> ProvisionRecord:
    """Create (or resume) the cluster's instances. Idempotent."""
    return _impl(provider).run_instances(config)


def stop_instances(provider: str, cluster_name: str, zone: str) -> None:
    return _impl(provider).stop_instances(cluster_name, zone)


def terminate_instances(provider: str, cluster_name: str, zone: str) -> None:
    return _impl(provider).terminate_instances(cluster_name, zone)


def query_instances(provider: str, cluster_name: str, zone: str) -> str:
    """'UP' | 'STOPPED' | 'PARTIAL' | 'NOT_FOUND' (cloud ground truth)."""
    return _impl(provider).query_instances(cluster_name, zone)


def wait_instances(provider: str, cluster_name: str, zone: str,
                   timeout: float = 600) -> None:
    return _impl(provider).wait_instances(cluster_name, zone, timeout)


def get_cluster_info(provider: str, cluster_name: str,
                     zone: str) -> ClusterInfo:
    return _impl(provider).get_cluster_info(cluster_name, zone)


def get_command_runners(info: ClusterInfo) -> list:
    return _impl(info.provider).get_command_runners(info)
