"""Uniform functional provision API, dispatched per provider.

Reference parity: sky/provision/__init__.py (_route_to_cloud_impl:38 —
``run_instances(provider, ...)`` resolves to
``sky.provision.<provider>.<fn>``). Same shape here: every provider
module exports the same function set; the backend never imports a
provider directly.
"""

from __future__ import annotations

import enum
import importlib
from typing import Any, FrozenSet

from skypilot_tpu import chaos
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)

_PROVIDERS = {}


class Feature(enum.Enum):
    """Capability negotiation (reference: CloudImplementationFeatures,
    sky/clouds/cloud.py:29 — STOP, MULTI_NODE, AUTO_TERMINATE, ... with
    per-cloud NotSupportedError refusals). Providers declare a FEATURES
    frozenset; callers refuse early with a clear message instead of
    rediscovering the contract ad hoc per provider."""

    STOP = "stop"                      # instances can stop (vs only down)
    MULTI_NODE = "multi_node"          # >1 logical node per cluster
    MULTI_NODE_EXEC = "multi_node_exec"  # head can gang-exec across hosts
    HOST_CONTROLLERS = "host_controllers"  # can host jobs/serve controllers


ALL_FEATURES: FrozenSet[Feature] = frozenset(Feature)


def supports(provider: str, feature: Feature) -> bool:
    mod = _impl(provider)
    if not hasattr(mod, "FEATURES"):
        # Fail loudly at wiring time, not deep inside a skylet: every
        # provider must state its capabilities.
        raise AttributeError(
            f"provision provider {provider!r} declares no FEATURES set")
    return feature in mod.FEATURES


def _impl(provider: str):
    if provider not in _PROVIDERS:
        _PROVIDERS[provider] = importlib.import_module(
            f"skypilot_tpu.provision.{provider}")
    return _PROVIDERS[provider]


def run_instances(provider: str, config: ProvisionConfig) -> ProvisionRecord:
    """Create (or resume) the cluster's instances. Idempotent."""
    # Chaos points sit in the dispatcher — ABOVE every provider — so
    # one fault plan covers gcp/aws/azure/k8s/local identically (a
    # CapacityError injected here is indistinguishable to the failover
    # loop from a real zone stockout).
    chaos.point("provision.run_instances", provider=provider,
                cluster=config.cluster_name, zone=config.zone)
    return _impl(provider).run_instances(config)


def stop_instances(provider: str, cluster_name: str, zone: str) -> None:
    chaos.point("provision.stop_instances", provider=provider,
                cluster=cluster_name, zone=zone)
    return _impl(provider).stop_instances(cluster_name, zone)


def terminate_instances(provider: str, cluster_name: str, zone: str) -> None:
    chaos.point("provision.terminate_instances", provider=provider,
                cluster=cluster_name, zone=zone)
    return _impl(provider).terminate_instances(cluster_name, zone)


def query_instances(provider: str, cluster_name: str, zone: str) -> str:
    """'UP' | 'STOPPED' | 'PARTIAL' | 'NOT_FOUND' (cloud ground truth)."""
    chaos.point("provision.query_instances", provider=provider,
                cluster=cluster_name, zone=zone)
    return _impl(provider).query_instances(cluster_name, zone)


def wait_instances(provider: str, cluster_name: str, zone: str,
                   timeout: float = 600) -> None:
    chaos.point("provision.wait_instances", provider=provider,
                cluster=cluster_name, zone=zone)
    return _impl(provider).wait_instances(cluster_name, zone, timeout)


def get_cluster_info(provider: str, cluster_name: str,
                     zone: str) -> ClusterInfo:
    return _impl(provider).get_cluster_info(cluster_name, zone)


def get_command_runners(info: ClusterInfo) -> list:
    return _impl(info.provider).get_command_runners(info)


def query_ports(provider: str, cluster_name: str) -> dict:
    """{service port: "host:port"} for providers with explicit port
    exposure (kubernetes NodePort Services); {} elsewhere — VM/local
    providers serve directly on the host address."""
    fn = getattr(_impl(provider), "query_ports", None)
    return fn(cluster_name) if fn else {}


def open_ports(provider: str, cluster_name: str, ports: list,
               zone: str = None) -> None:
    """Expose ``ports`` on the cluster (GCP: firewall rule targeting
    the cluster's network tag; kubernetes: NodePort Service; AWS:
    security-group ingress — NEEDS ``zone`` to locate the region).
    Providers also call this themselves at provision time when the
    config carries ports; the dispatcher form serves post-hoc exposure
    (reference: sky/provision/__init__.py open_ports)."""
    fn = getattr(_impl(provider), "open_ports", None)
    if fn:
        fn(cluster_name, ports, zone)


def cleanup_ports(provider: str, cluster_name: str,
                  zone: str = None) -> None:
    fn = getattr(_impl(provider), "cleanup_ports", None)
    if fn:
        fn(cluster_name, zone)
