"""Local fake cloud: hosts are directories, instances are metadata files.

Serves three purposes, mirroring the reference's offline-test strategy
(reference: tests/common_test_fixtures.py + LocalDockerBackend):

1. Offline end-to-end tests — launch/exec/logs/cancel/down run for real
   on one machine, with a multi-"host" cluster simulated as one
   workspace directory per host.
2. Fault injection for the failover loop — a ``fail_marker`` file in the
   cluster dir (or SKYTPU_LOCAL_FAIL_ATTEMPTS env) makes the next N
   ``run_instances`` calls raise CapacityError, exercising
   blocklist/re-optimize/retry paths without a cloud. (Legacy seam: new
   fault scenarios should use the seedable plans of
   ``skypilot_tpu/chaos`` instead — its ``provision.*`` points sit in
   the dispatcher above every provider, and SKYTPU_LOCAL_ZONES gives
   this fake cloud multiple zones for stockout-failover runs; see
   docs/robustness.md.)
3. Remote-cluster emulation — with SKYTPU_LOCAL_FAKE_SSH=1, hosts are
   reached through FakeSSHRunner (scrubbed env, $HOME-rooted layout),
   so the whole on-cluster runtime (rpc, driver, skylet, rsynced
   framework) runs the exact code path a real SSH cluster gets.

The clusters root is OUTSIDE any client's home when
SKYTPU_LOCAL_CLUSTERS_ROOT is set — the "cloud" must survive a client
dying, which is precisely what the client-death tests assert.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import List

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)
from skypilot_tpu.utils import command_runner, paths

_META = "local_meta.json"

# Everything works on the fake cloud (it exists to exercise all paths).
from skypilot_tpu.provision import Feature as _F  # noqa: E402
FEATURES = frozenset(_F)


def _clusters_root() -> str:
    return os.environ.get("SKYTPU_LOCAL_CLUSTERS_ROOT",
                          os.path.join(paths.home(), "local_clusters"))


def _cluster_root(cluster_name: str) -> str:
    return os.path.join(_clusters_root(), cluster_name)


def _meta_path(cluster_name: str) -> str:
    return os.path.join(_cluster_root(cluster_name), _META)


def _maybe_inject_failure(config: ProvisionConfig) -> None:
    # Env-based: fail the next N attempts globally (counter in a file).
    n = int(os.environ.get("SKYTPU_LOCAL_FAIL_ATTEMPTS", "0"))
    if n > 0:
        counter_file = os.path.join(paths.home(), "local_fail_counter")
        used = 0
        if os.path.exists(counter_file):
            used = int(open(counter_file).read().strip() or 0)
        if used < n:
            with open(counter_file, "w") as f:
                f.write(str(used + 1))
            raise exceptions.CapacityError(
                f"[fault-injection] no capacity for {config.accelerator} "
                f"in {config.zone} (attempt {used + 1}/{n})")


def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    _maybe_inject_failure(config)
    root = _cluster_root(config.cluster_name)
    n_hosts = config.num_nodes * config.hosts_per_node
    ids = []
    for h in range(n_hosts):
        ws = os.path.join(root, f"host{h}")
        os.makedirs(ws, exist_ok=True)
        ids.append(f"local-{config.cluster_name}-{h}")
    meta = {
        "zone": config.zone,
        "num_nodes": config.num_nodes,
        "hosts_per_node": config.hosts_per_node,
        "status": "UP",
        "instance_ids": ids,
        "fake_ssh": os.environ.get("SKYTPU_LOCAL_FAKE_SSH") == "1",
    }
    with open(_meta_path(config.cluster_name), "w") as f:
        json.dump(meta, f)
    return ProvisionRecord(provider="local",
                           cluster_name=config.cluster_name,
                           zone=config.zone, created_instance_ids=ids)


def _load_meta(cluster_name: str) -> dict:
    p = _meta_path(cluster_name)
    if not os.path.exists(p):
        raise exceptions.ClusterNotUpError(
            f"local cluster {cluster_name!r} not found")
    with open(p) as f:
        return json.load(f)


def stop_instances(cluster_name: str, zone: str) -> None:
    meta = _load_meta(cluster_name)
    meta["status"] = "STOPPED"
    with open(_meta_path(cluster_name), "w") as f:
        json.dump(meta, f)


def terminate_instances(cluster_name: str, zone: str) -> None:
    shutil.rmtree(_cluster_root(cluster_name), ignore_errors=True)


def query_instances(cluster_name: str, zone: str) -> str:
    try:
        return _load_meta(cluster_name)["status"]
    except exceptions.ClusterNotUpError:
        return "NOT_FOUND"


def wait_instances(cluster_name: str, zone: str, timeout: float = 600) -> None:
    _load_meta(cluster_name)  # local instances are ready instantly


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    meta = _load_meta(cluster_name)
    fake = bool(meta.get("fake_ssh"))
    hosts: List[HostInfo] = []
    hpn = meta["hosts_per_node"]
    for h in range(meta["num_nodes"] * hpn):
        hosts.append(HostInfo(
            host_id=h, node_id=h // hpn, worker_id=h % hpn,
            internal_ip="127.0.0.1",
            workspace=os.path.join(_cluster_root(cluster_name), f"host{h}"),
            runner_kind="fake" if fake else "local"))
    info = ClusterInfo(cluster_name=cluster_name, provider="local",
                       zone=meta["zone"], hosts=hosts)
    # The cluster-side runtime must reach this fake cloud's API (its
    # metadata files) regardless of which client launched it.
    info.metadata["provider_env"] = {
        "SKYTPU_LOCAL_CLUSTERS_ROOT": _clusters_root()}
    return info


def get_command_runners(info: ClusterInfo) -> List[command_runner.CommandRunner]:
    runners: List[command_runner.CommandRunner] = []
    for h in info.hosts:
        if h.runner_kind == "fake":
            runners.append(command_runner.FakeSSHRunner(
                root=h.workspace, host_id=h.host_id, ip=h.internal_ip))
        else:
            # Each "host" gets its own $HOME (the host dir) so
            # `~`-relative layout matches a real multi-VM cluster.
            runners.append(command_runner.LocalRunner(
                h.host_id, h.internal_ip, h.workspace,
                env_overrides={"HOME": h.workspace,
                               "SKYPILOT_TPU_HOME": None}))
    return runners
