"""Azure VM provisioning over the ARM REST API, zero-SDK.

Reference parity: sky/provision/azure/instance.py (run/stop/terminate/
query, NSG port exposure at open_ports) — redesigned the way this
repo's AWS provider redid EC2: plain HTTPS against
``management.azure.com`` with a bearer token from azure_auth (az CLI
or env), an injectable JSON transport so every path is unit-testable
offline against a stateful fake ARM (tests/test_azure_provision.py),
and the uniform functional provision API. The reference's 1,301-line
provider leans on five azure-mgmt SDKs and a Jinja ARM template; ARM
is itself a declarative PUT-per-resource API, so the template layer
dissolves into a handful of idempotent PUTs.

Cluster model: ONE resource group per cluster (``skytpu-<name>``) —
teardown is a single resource-group DELETE, the strongest cleanup
guarantee any cloud here offers. Inside it: one VNet + subnet, one NSG
(SSH + user ports), and per node a public IP, NIC, and VM tagged
``skypilot-cluster=<name>``. VMs carry the shared ``~/.ssh/sky-key``
and log in as ``azureuser``.

Azure carries GPU/CPU offerings (no TPUs): the optimizer arbitrates
its NC/ND GPU SKUs and D-series CPU boxes against GCP and AWS rows,
and cross-cloud failover can land here when both others are blocked.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.provision import Feature as _F
from skypilot_tpu.provision import azure_auth
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig,
                                           ProvisionRecord)
from skypilot_tpu.resources import extract_docker_image
from skypilot_tpu.utils import command_runner

ARM = "https://management.azure.com"
CLUSTER_TAG = "skypilot-cluster"
SSH_USER = "azureuser"

API = {
    "rg": "2021-04-01",
    "network": "2023-04-01",
    "compute": "2023-07-01",
}

# Canonical Ubuntu 22.04 LTS Gen2 marketplace image.
UBUNTU_IMAGE = {
    "publisher": "Canonical",
    "offer": "0001-com-ubuntu-server-jammy",
    "sku": "22_04-lts-gen2",
    "version": "latest",
}

FEATURES = frozenset(_F)

# transport(method, path, body) -> (http_status, parsed_json_or_{}).
# ``path`` is the ARM path + query (no scheme/host).
Transport = Callable[[str, str, Optional[dict]], Tuple[int, dict]]
_transport: Optional[Transport] = None


def set_transport(fn: Optional[Transport]) -> None:
    """Inject a fake ARM API (tests) or reset to real HTTPS (None)."""
    global _transport
    _transport = fn


def _subscription() -> str:
    if _transport is not None:
        return "sub-fake"
    creds = azure_auth.load_credentials()
    if creds is None:
        raise exceptions.NoCloudAccessError(
            "no Azure credentials (az login, or AZURE_ACCESS_TOKEN + "
            "AZURE_SUBSCRIPTION_ID)")
    return creds.subscription


def _api(method: str, path: str, body: Optional[dict] = None,
         ok_missing: bool = False) -> dict:
    """One ARM call; raises the mapped failover exception on errors.
    ``ok_missing``: a 404 returns {} instead of raising (list/GET of
    things that may legitimately not exist yet)."""
    if _transport is not None:
        status, data = _transport(method, path, body)
    else:
        creds = azure_auth.load_credentials()
        if creds is None:
            raise exceptions.NoCloudAccessError(
                "no Azure credentials (az login, or AZURE_ACCESS_TOKEN "
                "+ AZURE_SUBSCRIPTION_ID)")
        req = urllib.request.Request(
            ARM + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {creds.token}",
                     "Content-Type": "application/json"},
            method=method)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                status = resp.status
                text = resp.read().decode() or "{}"
        except urllib.error.HTTPError as e:
            status = e.code
            text = e.read().decode(errors="replace") or "{}"
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            data = {"raw": text}
    if status == 404 and ok_missing:
        return {}
    if status >= 400:
        err = (data.get("error") or {}) if isinstance(data, dict) else {}
        raise _map_error_code(err.get("code", f"HTTP{status}"),
                              err.get("message", str(data)[:500]))
    return data if isinstance(data, dict) else {}


def _map_error_code(code: str, message: str) -> Exception:
    """ARM error code -> the failover taxonomy (capacity errors are
    zone-blockable, quota region-blockable — same scopes the EC2/GCP
    mappings feed into RetryingProvisioner)."""
    err: Exception
    if code in ("SkuNotAvailable", "AllocationFailed",
                "OverconstrainedAllocationRequest",
                "OverconstrainedZonalAllocationRequest",
                "ZonalAllocationFailed", "SpotEvictionPolicyNotAllowed",
                "PriorityNotAllowed"):
        err = exceptions.CapacityError(f"ARM capacity: {code}: {message}")
    elif code in ("QuotaExceeded", "OperationNotAllowed") and (
            "quota" in message.lower() or code == "QuotaExceeded"):
        err = exceptions.QuotaExceededError(
            f"ARM quota: {code}: {message}")
    elif code in ("AuthenticationFailed", "AuthorizationFailed",
                  "InvalidAuthenticationToken",
                  "InvalidAuthenticationTokenTenant",
                  "ExpiredAuthenticationToken", "SubscriptionNotFound"):
        err = exceptions.NoCloudAccessError(f"ARM auth: {code}: {message}")
    elif code in ("ResourceNotFound", "ResourceGroupNotFound",
                  "NotFound", "ParentResourceNotFound"):
        err = exceptions.ClusterNotUpError(f"ARM: {code}: {message}")
    else:
        err = exceptions.ResourcesUnavailableError(
            f"ARM: {code}: {message}")
    err.arm_code = code
    return err


def _region_of_zone(zone: str) -> Tuple[str, Optional[str]]:
    """'eastus-2' -> ('eastus', '2'); 'eastus' -> ('eastus', None).
    Azure regions never contain dashes, so the split is unambiguous —
    the catalog emits zoned rows for zonal regions and bare-region rows
    elsewhere."""
    if "-" in zone:
        region, _, z = zone.rpartition("-")
        if z.isdigit():
            return region, z
    return zone, None


def _rg(cluster_name: str) -> str:
    return f"skytpu-{cluster_name}"


def _p(cluster_name: str, kind: str, rest: str = "",
       api: str = "network") -> str:
    """ARM path inside the cluster's resource group."""
    base = (f"/subscriptions/{_subscription()}/resourceGroups/"
            f"{_rg(cluster_name)}")
    if kind == "rg":
        return f"{base}?api-version={API['rg']}"
    provider = ("Microsoft.Compute" if api == "compute"
                else "Microsoft.Network")
    return (f"{base}/providers/{provider}/{kind}{rest}"
            f"?api-version={API[api]}")


# -- networking -------------------------------------------------------------

def _ensure_network(cluster_name: str, region: str,
                    ports: Optional[List[int]] = None) -> None:
    """RG + VNet/subnet + NSG, all idempotent PUTs (ARM PUT = upsert).

    The NSG full-body PUT happens only on CREATE: ARM replaces
    ``securityRules`` wholesale on PUT, so re-running it at relaunch
    would silently delete any rule added post-hoc by ``open_ports``
    (serve LB exposure would go dark after a stop/start). On an
    existing NSG, missing rules are added one by one through the
    securityRules subresource instead."""
    _api("PUT", _p(cluster_name, "rg"), {"location": region})
    nsg_path = _p(cluster_name, "networkSecurityGroups",
                  f"/{_rg(cluster_name)}-nsg")
    existing = _api("GET", nsg_path, ok_missing=True)
    if not existing:
        nsg_rules = [_ssh_rule()] + [
            _port_rule(p, 1100 + i) for i, p in enumerate(ports or [])]
        _api("PUT", nsg_path,
             {"location": region,
              "properties": {"securityRules": nsg_rules}})
    elif ports:
        open_ports(cluster_name, list(ports))
    nsg_id = _id(cluster_name, "networkSecurityGroups",
                 f"{_rg(cluster_name)}-nsg")
    _api("PUT", _p(cluster_name, "virtualNetworks",
                   f"/{_rg(cluster_name)}-vnet"),
         {"location": region,
          "properties": {
              "addressSpace": {"addressPrefixes": ["10.0.0.0/16"]},
              "subnets": [{"name": "default",
                           "properties": {
                               "addressPrefix": "10.0.0.0/24",
                               "networkSecurityGroup": {"id": nsg_id},
                           }}]}})


def _ssh_rule() -> dict:
    return {"name": "skytpu-ssh",
            "properties": {"priority": 1000, "direction": "Inbound",
                           "access": "Allow", "protocol": "Tcp",
                           "sourceAddressPrefix": "*",
                           "sourcePortRange": "*",
                           "destinationAddressPrefix": "*",
                           "destinationPortRange": "22"}}


def _port_rule(port: int, priority: int) -> dict:
    return {"name": f"skytpu-port-{port}",
            "properties": {"priority": priority, "direction": "Inbound",
                           "access": "Allow", "protocol": "Tcp",
                           "sourceAddressPrefix": "*",
                           "sourcePortRange": "*",
                           "destinationAddressPrefix": "*",
                           "destinationPortRange": str(port)}}


def _id(cluster_name: str, kind: str, name: str,
        api: str = "network") -> str:
    provider = ("Microsoft.Compute" if api == "compute"
                else "Microsoft.Network")
    return (f"/subscriptions/{_subscription()}/resourceGroups/"
            f"{_rg(cluster_name)}/providers/{provider}/{kind}/{name}")


def open_ports(cluster_name: str, ports: List[int],
               zone: Optional[str] = None) -> None:
    """Post-hoc exposure: add one NSG rule per port (reference:
    sky/provision/azure/instance.py open_ports adds rules to the
    cluster NSG the same way)."""
    del zone
    nsg = f"{_rg(cluster_name)}-nsg"
    existing = _api("GET", _p(cluster_name, "networkSecurityGroups",
                              f"/{nsg}"), ok_missing=True)
    rules = (existing.get("properties") or {}).get("securityRules", [])
    used = {r["properties"]["priority"] for r in rules}
    have = {r["name"] for r in rules}
    prio = 1100
    for port in ports:
        name = f"skytpu-port-{port}"
        if name in have:
            continue
        while prio in used:
            prio += 1
        used.add(prio)
        _api("PUT", _p(cluster_name, "networkSecurityGroups",
                       f"/{nsg}/securityRules/{name}"),
             {"properties": _port_rule(port, prio)["properties"]})


def cleanup_ports(cluster_name: str,
                  zone: Optional[str] = None) -> None:
    # Ports live in the cluster NSG; the resource-group DELETE at
    # terminate removes them wholesale. Nothing to do separately.
    del cluster_name, zone


# -- instances --------------------------------------------------------------

def _vm_name(cluster_name: str, index: int) -> str:
    return f"{cluster_name}-{index}"


def _list_vms(cluster_name: str) -> List[dict]:
    data = _api("GET", _p(cluster_name, "virtualMachines", api="compute"),
                ok_missing=True)
    out = []
    for vm in data.get("value", []):
        tags = vm.get("tags") or {}
        if tags.get(CLUSTER_TAG) != cluster_name:
            continue
        out.append(vm)
    out.sort(key=lambda v: v["name"])
    return out


def _power_state(cluster_name: str, vm_name: str) -> str:
    """'running' | 'deallocated' | 'starting' | ... from instanceView."""
    data = _api("GET", _p(cluster_name, "virtualMachines",
                          f"/{vm_name}/instanceView", api="compute"),
                ok_missing=True)
    for s in data.get("statuses", []):
        code = s.get("code", "")
        if code.startswith("PowerState/"):
            return code.split("/", 1)[1]
    return "unknown"


def _image_reference(config: ProvisionConfig) -> dict:
    img = config.image_id
    if img and not extract_docker_image(img):
        if img.startswith("/"):
            return {"id": img}         # custom managed image / gallery
        parts = img.split(":")
        if len(parts) == 4:            # publisher:offer:sku:version URN
            return {"publisher": parts[0], "offer": parts[1],
                    "sku": parts[2], "version": parts[3]}
        raise exceptions.InvalidTaskError(
            f"unrecognized Azure image_id {img!r} (expected a resource "
            "id or publisher:offer:sku:version)")
    return dict(UBUNTU_IMAGE)


def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    """Create or resume the cluster's VMs. Idempotent: the resource
    group + per-resource PUTs upsert; deallocated VMs restart; missing
    ones are created; running ones are left alone."""
    from skypilot_tpu import authentication

    region, zone_number = _region_of_zone(config.zone)
    want = config.num_nodes * config.hosts_per_node
    record = ProvisionRecord(provider="azure",
                             cluster_name=config.cluster_name,
                             zone=config.zone)
    _ensure_network(config.cluster_name, region,
                    list(config.ports) if config.ports else None)

    existing = {vm["name"]: vm for vm in _list_vms(config.cluster_name)}
    _, pub = authentication.get_or_generate_keys()
    with open(pub) as f:
        ssh_key = f.read().strip()

    for i in range(want):
        name = _vm_name(config.cluster_name, i)
        if name in existing:
            if _power_state(config.cluster_name, name) in (
                    "deallocated", "stopped"):
                _api("POST", _p(config.cluster_name, "virtualMachines",
                                f"/{name}/start", api="compute"), {})
                record.resumed = True
            continue
        _api("PUT", _p(config.cluster_name, "publicIPAddresses",
                       f"/{name}-ip"),
             {"location": region, "sku": {"name": "Standard"},
              "properties": {"publicIPAllocationMethod": "Static"}})
        subnet_id = (_id(config.cluster_name, "virtualNetworks",
                         f"{_rg(config.cluster_name)}-vnet")
                     + "/subnets/default")
        _api("PUT", _p(config.cluster_name, "networkInterfaces",
                       f"/{name}-nic"),
             {"location": region,
              "properties": {"ipConfigurations": [{
                  "name": "primary",
                  "properties": {
                      "subnet": {"id": subnet_id},
                      "publicIPAddress": {
                          "id": _id(config.cluster_name,
                                    "publicIPAddresses", f"{name}-ip")},
                  }}]}})
        vm_body = {
            "location": region,
            "tags": {CLUSTER_TAG: config.cluster_name,
                     **config.labels},
            "properties": {
                "hardwareProfile": {"vmSize": config.instance_type},
                "storageProfile": {
                    "imageReference": _image_reference(config),
                    "osDisk": {"createOption": "FromImage",
                               "diskSizeGB": config.disk_size,
                               "managedDisk": {
                                   "storageAccountType":
                                       "Premium_LRS"}},
                },
                "osProfile": {
                    "computerName": name,
                    "adminUsername": SSH_USER,
                    "linuxConfiguration": {
                        "disablePasswordAuthentication": True,
                        "ssh": {"publicKeys": [{
                            "path": (f"/home/{SSH_USER}/.ssh/"
                                     "authorized_keys"),
                            "keyData": ssh_key}]},
                    },
                },
                "networkProfile": {"networkInterfaces": [{
                    "id": _id(config.cluster_name, "networkInterfaces",
                              f"{name}-nic")}]},
            },
        }
        if zone_number is not None:
            vm_body["zones"] = [zone_number]
        if config.use_spot:
            vm_body["properties"]["priority"] = "Spot"
            vm_body["properties"]["evictionPolicy"] = "Deallocate"
            vm_body["properties"]["billingProfile"] = {"maxPrice": -1}
        _api("PUT", _p(config.cluster_name, "virtualMachines",
                       f"/{name}", api="compute"), vm_body)
        record.created_instance_ids.append(name)
    return record


def wait_instances(cluster_name: str, zone: str,
                   timeout: float = 600) -> None:
    del zone
    deadline = time.monotonic() + timeout
    polls = 0
    while time.monotonic() < deadline:
        vms = _list_vms(cluster_name)
        if vms and all(
                _power_state(cluster_name, vm["name"]) == "running"
                for vm in vms):
            return
        polls += 1
        if _transport is not None:
            # Fakes transition instantly; a handful of polls is ample.
            # Without this cap a never-running fake VM busy-spins the
            # zero-sleep loop at full CPU for the whole timeout.
            if polls >= 10:
                break
        else:
            time.sleep(3)
    raise exceptions.ResourcesUnavailableError(
        f"VMs of {cluster_name} not running after {timeout}s")


def stop_instances(cluster_name: str, zone: str) -> None:
    del zone
    for vm in _list_vms(cluster_name):
        # Deallocate (not just power off): a deallocated VM stops
        # billing compute, the semantic every other provider's 'stop'
        # carries.
        _api("POST", _p(cluster_name, "virtualMachines",
                        f"/{vm['name']}/deallocate", api="compute"), {})


def terminate_instances(cluster_name: str, zone: str) -> None:
    del zone
    # The whole cluster lives in one resource group: a single DELETE
    # tears down VMs, NICs, IPs, disks, VNet, and NSG with no orphan
    # sweep (the reference's azure teardown deletes the same way).
    _api("DELETE", _p(cluster_name, "rg"), ok_missing=True)


def query_instances(cluster_name: str, zone: str) -> str:
    del zone
    vms = _list_vms(cluster_name)
    if not vms:
        return "NOT_FOUND"
    states = {_power_state(cluster_name, vm["name"]) for vm in vms}
    if states <= {"running", "starting"}:
        return "UP"
    if states <= {"deallocated", "deallocating", "stopped", "stopping"}:
        return "STOPPED"
    return "PARTIAL"


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    vms = [vm for vm in _list_vms(cluster_name)
           if _power_state(cluster_name, vm["name"]) in ("running",
                                                         "starting")]
    if not vms:
        raise exceptions.ClusterNotUpError(
            f"no running VMs for {cluster_name}")
    hosts = []
    for n, vm in enumerate(vms):
        name = vm["name"]
        ip_data = _api("GET", _p(cluster_name, "publicIPAddresses",
                                 f"/{name}-ip"), ok_missing=True)
        nic_data = _api("GET", _p(cluster_name, "networkInterfaces",
                                  f"/{name}-nic"), ok_missing=True)
        external = (ip_data.get("properties") or {}).get("ipAddress")
        internal = ""
        for ipc in (nic_data.get("properties") or {}).get(
                "ipConfigurations", []):
            internal = (ipc.get("properties") or {}).get(
                "privateIPAddress") or internal
        hosts.append(HostInfo(host_id=n, node_id=n, worker_id=0,
                              internal_ip=internal,
                              external_ip=external,
                              ssh_user=SSH_USER, ssh_port=22))
    return ClusterInfo(cluster_name=cluster_name, provider="azure",
                       zone=zone, hosts=hosts,
                       ssh_key_path="~/.ssh/sky-key",
                       metadata={"resource_group": _rg(cluster_name)})


def get_command_runners(info: ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    runners = []
    for h in info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(command_runner.SSHRunner(
            ip=ip, user=h.ssh_user or SSH_USER,
            key_path=info.ssh_key_path or "~/.ssh/sky-key",
            host_id=h.host_id, port=h.ssh_port))
    return runners


def check_credentials():
    return azure_auth.check_credentials()
