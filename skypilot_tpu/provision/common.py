"""Shared provision-layer types. Reference parity: sky/provision/common.py
(ProvisionConfig/ProvisionRecord/ClusterInfo)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    """One physical host (a TPU slice worker VM or a GPU/CPU VM)."""
    host_id: int          # global host index across the cluster
    node_id: int          # logical node (slice) this host belongs to
    worker_id: int        # index within the slice (TPU_WORKER_ID)
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_user: Optional[str] = None
    ssh_port: int = 22
    workspace: Optional[str] = None   # local provider: host directory
    # How the head reaches this host for gang execution:
    # "ssh" (real VMs) | "local" (fake-cloud dir, same machine) |
    # "fake" (fake-cloud dir behaving as a remote host) | "k8s" (pod).
    runner_kind: str = "ssh"


@dataclasses.dataclass
class ClusterInfo:
    cluster_name: str
    provider: str                      # "local" | "gcp"
    zone: str
    hosts: List[HostInfo] = dataclasses.field(default_factory=list)
    ssh_key_path: Optional[str] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def head(self) -> HostInfo:
        return self.hosts[0]

    def hosts_of_node(self, node_id: int) -> List[HostInfo]:
        return [h for h in self.hosts if h.node_id == node_id]


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a provider needs to create instances."""
    cluster_name: str
    num_nodes: int                     # logical nodes (slices)
    hosts_per_node: int
    zone: str
    region: str
    accelerator: Optional[str] = None  # "tpu-v5e-16" | "A100"
    accelerator_count: int = 0
    instance_type: Optional[str] = None
    use_spot: bool = False
    runtime_version: Optional[str] = None
    disk_size: int = 256
    image_id: Optional[str] = None
    ports: Optional[List[int]] = None
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    user_data: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProvisionRecord:
    provider: str
    cluster_name: str
    zone: str
    created_instance_ids: List[str] = dataclasses.field(default_factory=list)
    resumed: bool = False
