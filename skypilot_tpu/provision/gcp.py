"""GCP TPU-VM provisioning over the TPU REST API (tpu.googleapis.com/v2).

Reference parity: sky/provision/gcp/instance_utils.py GCPTPUVMInstance
(:1191 — REST calls, op polling :1217, create :1487). TPU-first deltas
the reference never implemented (SURVEY.md §2.3 'north-star gap'):

* **Queued resources** (`queuedResources` API) are first-class: v5e/v5p/
  v6e capacity is requested through the queue (the only reliable way to
  get modern slices), with spot + valid-until windows; v2/v3 fall back
  to direct node creation.
* One *slice* is one logical node; hosts are enumerated from the node's
  ``networkEndpoints`` after READY.

Zero-SDK: plain HTTPS via urllib with a gcloud-sourced bearer token.
The transport is injectable (``set_transport``) so the whole module is
unit-testable offline with a fake TPU API (tests/test_gcp_provision.py).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import gcp_auth
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)
from skypilot_tpu.utils import command_runner

TPU_API = "https://tpu.googleapis.com/v2"

# Generations whose capacity must go through the queued-resource API.
QUEUED_RESOURCE_GENS = ("v5e", "v5p", "v6e")

Transport = Callable[[str, str, Optional[dict]], dict]
_transport: Optional[Transport] = None


def set_transport(fn: Optional[Transport]) -> None:
    """Inject a fake transport (tests) or reset to real HTTPS (None)."""
    global _transport
    _transport = fn


def _http(method: str, url: str, body: Optional[dict] = None) -> dict:
    if _transport is not None:
        return _transport(method, url, body)
    token = gcp_auth.get_access_token()
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        raise _map_http_error(e.code, e.read().decode(errors="replace"))


def _map_http_error(code: int, body: str) -> Exception:
    low = body.lower()
    if code == 429 or "resource_exhausted" in low or "stockout" in low \
            or "no more capacity" in low or "out of capacity" in low:
        return exceptions.CapacityError(f"TPU capacity error ({code}): {body}")
    if code == 403 and "quota" in low:
        return exceptions.QuotaExceededError(f"TPU quota error: {body}")
    if code == 404:
        return exceptions.ClusterNotUpError(f"TPU not found: {body}")
    return exceptions.ResourcesUnavailableError(
        f"TPU API error ({code}): {body}")


# -- naming -----------------------------------------------------------------

def to_gcp_accelerator_type(accelerator: str) -> str:
    """'tpu-v5e-16' -> 'v5litepod-16'; 'tpu-v5p-16' -> 'v5p-16'."""
    name = accelerator.removeprefix("tpu-")
    gen, _, size = name.partition("-")
    return f"v5litepod-{size}" if gen == "v5e" else name


def _generation(accelerator: str) -> str:
    return accelerator.removeprefix("tpu-").partition("-")[0]


def _parent(zone: str) -> str:
    project = gcp_auth.get_project()
    if not project:
        raise exceptions.NoCloudAccessError(
            "no GCP project configured (set GOOGLE_CLOUD_PROJECT or "
            "`gcloud config set project`)")
    return f"projects/{project}/locations/{zone}"


def _node_name(cluster_name: str) -> str:
    return cluster_name


def _node_url(cluster_name: str, zone: str) -> str:
    return f"{TPU_API}/{_parent(zone)}/nodes/{_node_name(cluster_name)}"


def _qr_url(cluster_name: str, zone: str) -> str:
    return (f"{TPU_API}/{_parent(zone)}/queuedResources/"
            f"{_node_name(cluster_name)}")


# -- provision API ----------------------------------------------------------

def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    if config.num_nodes != 1:
        raise exceptions.ResourcesUnavailableError(
            "gcp provider: multi-slice (num_nodes>1) lands with multislice "
            "support; use one slice per cluster for now", no_failover=True)
    accel = config.accelerator or ""
    # Resume path: node already exists?
    status = query_instances(config.cluster_name, config.zone)
    if status == "UP":
        return ProvisionRecord("gcp", config.cluster_name, config.zone,
                               resumed=True)
    if status == "STOPPED":
        _http("POST", _node_url(config.cluster_name, config.zone) + ":start")
        return ProvisionRecord("gcp", config.cluster_name, config.zone,
                               resumed=True)

    node_body = {
        "acceleratorType": to_gcp_accelerator_type(accel),
        "runtimeVersion": config.runtime_version,
        "networkConfig": {"enableExternalIps": True},
        "labels": dict(config.labels, **{"skypilot-tpu-cluster":
                                         config.cluster_name}),
        "metadata": {},
        "schedulingConfig": {"preemptible": config.use_spot}
        if config.use_spot else {},
    }
    if _generation(accel) in QUEUED_RESOURCE_GENS:
        body = {
            "tpu": {"nodeSpec": [{
                "parent": _parent(config.zone),
                "nodeId": _node_name(config.cluster_name),
                "node": node_body,
            }]},
        }
        if config.use_spot:
            body["spot"] = {}
            node_body.pop("schedulingConfig", None)
        _http("POST",
              f"{TPU_API}/{_parent(config.zone)}/queuedResources"
              f"?queuedResourceId={_node_name(config.cluster_name)}", body)
    else:
        _http("POST",
              f"{TPU_API}/{_parent(config.zone)}/nodes"
              f"?nodeId={_node_name(config.cluster_name)}", node_body)
    return ProvisionRecord("gcp", config.cluster_name, config.zone,
                           created_instance_ids=[config.cluster_name])


def wait_instances(cluster_name: str, zone: str, timeout: float = 1800,
                   poll: float = 10.0) -> None:
    """Wait for the node READY (queued resources: WAITING->PROVISIONING->
    ACTIVE, then the node itself READY). Non-recoverable queue states
    (FAILED/SUSPENDED) raise CapacityError -> failover."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            node = _http("GET", _node_url(cluster_name, zone))
        except exceptions.ClusterNotUpError:
            node = None
        if node is not None:
            state = node.get("state")
            if state == "READY":
                return
            if state in ("PREEMPTED", "TERMINATED"):
                raise exceptions.CapacityError(
                    f"TPU node entered {state} while waiting")
        else:
            # Node not yet materialized; check the queued resource.
            try:
                qr = _http("GET", _qr_url(cluster_name, zone))
                qstate = qr.get("state", {}).get("state")
                if qstate in ("FAILED", "SUSPENDED", "SUSPENDING"):
                    raise exceptions.CapacityError(
                        f"queued resource {qstate}: "
                        f"{qr.get('state')}")
            except exceptions.ClusterNotUpError:
                pass
        time.sleep(poll)
    raise exceptions.ProvisionTimeoutError(
        f"TPU {cluster_name} not READY within {timeout}s")


def stop_instances(cluster_name: str, zone: str) -> None:
    # TPU-VM pods cannot stop (reference: clouds/gcp.py:206-212 carries
    # the same restriction); single-host nodes can.
    info = get_cluster_info(cluster_name, zone)
    if len(info.hosts) > 1:
        raise exceptions.ResourcesUnavailableError(
            "multi-host TPU slices cannot be stopped; use down instead",
            no_failover=True)
    _http("POST", _node_url(cluster_name, zone) + ":stop")


def terminate_instances(cluster_name: str, zone: str) -> None:
    for url in (_node_url(cluster_name, zone),
                _qr_url(cluster_name, zone)):
        try:
            _http("DELETE", url + "?force=true")
        except exceptions.ClusterNotUpError:
            continue
        except exceptions.ResourcesUnavailableError:
            # queued resources require force delete only when provisioning
            raise


def query_instances(cluster_name: str, zone: str) -> str:
    try:
        node = _http("GET", _node_url(cluster_name, zone))
    except exceptions.ClusterNotUpError:
        return "NOT_FOUND"
    state = node.get("state")
    return {"READY": "UP", "STOPPED": "STOPPED",
            "PREEMPTED": "NOT_FOUND", "TERMINATED": "NOT_FOUND"}.get(
                state, "PARTIAL")


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    node = _http("GET", _node_url(cluster_name, zone))
    hosts: List[HostInfo] = []
    for i, ep in enumerate(node.get("networkEndpoints", [])):
        ext = (ep.get("accessConfig") or {}).get("externalIp")
        hosts.append(HostInfo(
            host_id=i, node_id=0, worker_id=i,
            internal_ip=ep.get("ipAddress", ""),
            external_ip=ext, ssh_user="skypilot", ssh_port=22))
    return ClusterInfo(cluster_name=cluster_name, provider="gcp", zone=zone,
                       hosts=hosts,
                       ssh_key_path="~/.ssh/sky-key",
                       metadata={"accelerator_type":
                                 node.get("acceleratorType"),
                                 "state": node.get("state")})


def get_command_runners(info: ClusterInfo) -> List[command_runner.CommandRunner]:
    runners = []
    for h in info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(command_runner.SSHRunner(
            ip=ip, user=h.ssh_user or "skypilot",
            key_path=info.ssh_key_path or "~/.ssh/sky-key",
            host_id=h.host_id, port=h.ssh_port))
    return runners
