"""GCP TPU-VM provisioning over the TPU REST API (tpu.googleapis.com/v2).

Reference parity: sky/provision/gcp/instance_utils.py GCPTPUVMInstance
(:1191 — REST calls, op polling :1217, create :1487). TPU-first deltas
the reference never implemented (SURVEY.md §2.3 'north-star gap'):

* **Queued resources** (`queuedResources` API) are first-class: v5e/v5p/
  v6e capacity is requested through the queue (the only reliable way to
  get modern slices), with spot + valid-until windows; v2/v3 fall back
  to direct node creation.
* One *slice* is one logical node; hosts are enumerated from the node's
  ``networkEndpoints`` after READY.

Zero-SDK: plain HTTPS via urllib with a gcloud-sourced bearer token.
The transport is injectable (``set_transport``) so the whole module is
unit-testable offline with a fake TPU API (tests/test_gcp_provision.py).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import gcp_auth
from skypilot_tpu.resources import extract_docker_image
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)
from skypilot_tpu.utils import command_runner

TPU_API = "https://tpu.googleapis.com/v2"
COMPUTE_API = "https://compute.googleapis.com/compute/v1"

# Generations whose capacity must go through the queued-resource API.
QUEUED_RESOURCE_GENS = ("v5e", "v5p", "v6e")

# GPU name -> Compute Engine acceleratorType, for machine families that
# do not embed their GPUs (N1 attachments). A2/A3/G2 machine types carry
# their GPUs implicitly.
GPU_ACCELERATOR_TYPES = {
    "T4": "nvidia-tesla-t4",
    "V100": "nvidia-tesla-v100",
    "P100": "nvidia-tesla-p100",
}
_BUILTIN_GPU_FAMILIES = ("a2-", "a3-", "g2-")

DEFAULT_VM_IMAGE = "projects/debian-cloud/global/images/family/debian-12"

# Full feature set; STOP is additionally resource-dependent (multi-host/
# multislice TPUs cannot stop — refused in stop_instances/set_autostop).
from skypilot_tpu.provision import Feature as _F  # noqa: E402
FEATURES = frozenset(_F)

Transport = Callable[[str, str, Optional[dict]], dict]
_transport: Optional[Transport] = None


def set_transport(fn: Optional[Transport]) -> None:
    """Inject a fake transport (tests) or reset to real HTTPS (None)."""
    global _transport
    _transport = fn


def _http(method: str, url: str, body: Optional[dict] = None) -> dict:
    if _transport is not None:
        return _transport(method, url, body)
    token = gcp_auth.get_access_token()
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        raise _map_http_error(e.code, e.read().decode(errors="replace"))


def _map_http_error(code: int, body: str) -> Exception:
    low = body.lower()
    if code == 429 or "resource_exhausted" in low or "stockout" in low \
            or "no more capacity" in low or "out of capacity" in low:
        err: Exception = exceptions.CapacityError(
            f"TPU capacity error ({code}): {body}")
    elif code == 403 and "quota" in low:
        err = exceptions.QuotaExceededError(f"TPU quota error: {body}")
    elif code == 404:
        err = exceptions.ClusterNotUpError(f"TPU not found: {body}")
    else:
        err = exceptions.ResourcesUnavailableError(
            f"TPU API error ({code}): {body}")
    err.http_code = code   # callers branch on 404/409 without parsing
    return err


# -- naming -----------------------------------------------------------------

def to_gcp_accelerator_type(accelerator: str) -> str:
    """'tpu-v5e-16' -> 'v5litepod-16'; 'tpu-v5p-16' -> 'v5p-16'."""
    name = accelerator.removeprefix("tpu-")
    gen, _, size = name.partition("-")
    return f"v5litepod-{size}" if gen == "v5e" else name


def _generation(accelerator: str) -> str:
    return accelerator.removeprefix("tpu-").partition("-")[0]


def _parent(zone: str) -> str:
    project = gcp_auth.get_project()
    if not project:
        raise exceptions.NoCloudAccessError(
            "no GCP project configured (set GOOGLE_CLOUD_PROJECT or "
            "`gcloud config set project`)")
    return f"projects/{project}/locations/{zone}"


def _node_name(cluster_name: str) -> str:
    return cluster_name


def _node_url(cluster_name: str, zone: str, node_name: str = None) -> str:
    return (f"{TPU_API}/{_parent(zone)}/nodes/"
            f"{node_name or _node_name(cluster_name)}")


def _qr_url(cluster_name: str, zone: str) -> str:
    return (f"{TPU_API}/{_parent(zone)}/queuedResources/"
            f"{_node_name(cluster_name)}")


def _node_names_ex(cluster_name: str, zone: str) -> tuple:
    """(node names, qr_exists). Names are ``cluster`` for a single
    slice; for multislice, ``{prefix}-{i}`` — the names the TPU API
    generates from the queued resource's multiNodeParams — derived from
    the QR so callers need no extra state. ``qr_exists`` tells callers
    this is definitely a TPU cluster (no Compute fallback probing
    needed)."""
    try:
        qr = _http("GET", _qr_url(cluster_name, zone))
    except exceptions.ClusterNotUpError:
        return [cluster_name], False
    specs = qr.get("body", qr).get("tpu", {}).get("nodeSpec", [])
    for spec in specs:
        ms = spec.get("multiNodeParams")
        if ms:
            prefix = ms.get("nodeIdPrefix", cluster_name)
            return ([f"{prefix}-{i}"
                     for i in range(int(ms["nodeCount"]))], True)
    if specs and specs[0].get("nodeId"):
        return [specs[0]["nodeId"]], True
    return [cluster_name], True


def _node_names(cluster_name: str, zone: str) -> List[str]:
    return _node_names_ex(cluster_name, zone)[0]


def _node_states(cluster_name: str, zone: str,
                 names: List[str]) -> List[Optional[str]]:
    """Per-node TPU state, None where the node does not exist."""
    states: List[Optional[str]] = []
    for name in names:
        try:
            node = _http("GET", _node_url(cluster_name, zone, name))
            states.append(node.get("state"))
        except exceptions.ClusterNotUpError:
            states.append(None)
    return states


# -- provision API ----------------------------------------------------------

def configured_reservations() -> List[str]:
    """Reservation names from config (``gcp.specific_reservations``).
    Reference parity: sky/skypilot_config gcp.specific_reservations +
    sky/clouds/gcp.py:1098 get_reservations_available_resources."""
    from skypilot_tpu import config as config_lib
    return list(config_lib.get_nested(("gcp", "specific_reservations"),
                                      []) or [])


def use_reserved_tpu_capacity() -> bool:
    """``gcp.use_reserved_tpu_capacity``: request the queued-resource
    guaranteed/reserved tier (the project holds a TPU reservation)."""
    from skypilot_tpu import config as config_lib
    return bool(config_lib.get_nested(
        ("gcp", "use_reserved_tpu_capacity"), False))


def list_reservations_available(zone: str,
                                instance_type: Optional[str] = None
                                ) -> Dict[str, int]:
    """Unused capacity per configured SPECIFIC reservation in ``zone``:
    {name: free_count}. Empty when none configured. With
    ``instance_type``, only reservations whose machine type matches
    count (a VM reservation must never discount a TPU candidate — TPU
    reservations are consumed via the queued-resource guaranteed tier
    instead, see run_instances)."""
    names = set(configured_reservations())
    if not names:
        return {}
    resp = _http("GET", f"{_compute_zone_url(zone)}/reservations")
    out: Dict[str, int] = {}
    for r in resp.get("items", []):
        if r.get("name") not in names:
            continue
        sr = r.get("specificReservation", {})
        mt = (sr.get("instanceProperties") or {}).get("machineType")
        if instance_type is not None and mt != instance_type:
            continue
        total = int(sr.get("count", 0))
        used = int(sr.get("inUseCount", 0))
        out[r["name"]] = max(total - used, 0)
    return out


# -- firewall / port exposure -----------------------------------------------
#
# Reference parity: sky/provision/gcp/instance.py:573 (open_ports),
# :628 (cleanup_ports) and the firewall-rule machinery in
# sky/provision/gcp/config.py:392-460. Design delta: instances are
# network-tagged with the cluster name AT CREATE (both the TPU node and
# Compute VM bodies carry the tag), so exposure is one idempotent
# firewall-rule upsert — no retrofit tag-patching pass per instance.

def _firewall_rule_name(cluster_name: str) -> str:
    return f"skytpu-{cluster_name}-ports"


def open_ports(cluster_name: str, ports: List[int],
               zone: str = None) -> None:
    """Create/update the cluster's ingress allow rule (tcp ``ports``
    from anywhere, scoped by target tag to this cluster's instances).
    Without it a default-VPC deployment silently blackholes every
    serve LB/replica and task ``ports:`` endpoint."""
    del zone  # firewall rules are global compute resources
    if not ports:
        return
    project = gcp_auth.get_project()
    name = _firewall_rule_name(cluster_name)
    body = {
        "name": name,
        "description": f"skypilot-tpu ports for cluster {cluster_name}",
        "network": "global/networks/default",
        "direction": "INGRESS",
        "allowed": [{"IPProtocol": "tcp",
                     "ports": [str(p) for p in sorted(set(ports))]}],
        "sourceRanges": ["0.0.0.0/0"],
        "targetTags": [cluster_name],
    }
    url = f"{COMPUTE_API}/projects/{project}/global/firewalls"
    try:
        _http("POST", url, body)
    except exceptions.ResourcesUnavailableError as e:
        if getattr(e, "http_code", None) != 409:
            raise
        # Rule exists (resume / replica count change): converge it.
        _http("PATCH", f"{url}/{name}", body)


def cleanup_ports(cluster_name: str, zone: str = None) -> None:
    """Delete the cluster's firewall rule; absent rule is fine (the
    cluster may never have exposed ports)."""
    del zone
    project = gcp_auth.get_project()
    try:
        _http("DELETE", f"{COMPUTE_API}/projects/{project}/global/"
                        f"firewalls/{_firewall_rule_name(cluster_name)}")
    except exceptions.ClusterNotUpError:
        pass


def _is_tpu_config(config: ProvisionConfig) -> bool:
    """TPU vs Compute Engine dispatch (reference: GCPNodeType selection
    at sky/provision/gcp/instance_utils.py:1658-1666)."""
    return bool(config.accelerator) and config.accelerator.startswith("tpu-")


def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    if not _is_tpu_config(config):
        return _run_compute_instances(config)
    accel = config.accelerator or ""
    if config.num_nodes > 1 and _generation(accel) not in QUEUED_RESOURCE_GENS:
        raise exceptions.ResourcesUnavailableError(
            f"gcp provider: multislice requires a queued-resource "
            f"generation ({'/'.join(QUEUED_RESOURCE_GENS)}); "
            f"{accel or 'this accelerator'} slices cannot be combined",
            no_failover=True)
    # Resume path: node(s) already exist?
    status = query_instances(config.cluster_name, config.zone)
    if status == "UP":
        open_ports(config.cluster_name, config.ports)
        return ProvisionRecord("gcp", config.cluster_name, config.zone,
                               resumed=True)
    if status == "STOPPED":
        _http("POST", _node_url(config.cluster_name, config.zone) + ":start")
        open_ports(config.cluster_name, config.ports)
        return ProvisionRecord("gcp", config.cluster_name, config.zone,
                               resumed=True)

    node_body = {
        "acceleratorType": to_gcp_accelerator_type(accel),
        "runtimeVersion": config.runtime_version,
        "networkConfig": {"enableExternalIps": True},
        # The cluster network tag is attached at create so the port
        # firewall rule (targetTags) applies to every node with no
        # retrofit pass.
        "tags": [config.cluster_name],
        "labels": dict(config.labels, **{"skypilot-tpu-cluster":
                                         config.cluster_name}),
        "metadata": {},
        "schedulingConfig": {"preemptible": config.use_spot}
        if config.use_spot else {},
    }
    if _generation(accel) in QUEUED_RESOURCE_GENS:
        spec: Dict[str, Any] = {
            "parent": _parent(config.zone),
            "node": node_body,
        }
        if config.num_nodes > 1:
            # Multislice: ONE queued resource atomically requests N
            # slices (the API names the nodes {prefix}-{i}); DCN wiring
            # between them is the driver's MEGASCALE_* env. The
            # reference never implemented this (SURVEY.md §2.3
            # north-star gap; its GCPTPUVMInstance at
            # instance_utils.py:1191 is single-node only).
            spec["multiNodeParams"] = {
                "nodeCount": config.num_nodes,
                "nodeIdPrefix": _node_name(config.cluster_name),
            }
        else:
            spec["nodeId"] = _node_name(config.cluster_name)
        body = {"tpu": {"nodeSpec": [spec]}}
        if config.use_spot:
            body["spot"] = {}
            node_body.pop("schedulingConfig", None)
        elif use_reserved_tpu_capacity():
            # Consume the project's TPU reservation (QR guaranteed
            # tier; reference: DWS/reserved capacity paths,
            # sky/provision/gcp/mig_utils.py). Gated on its OWN config
            # key: VM reservation names in specific_reservations must
            # not force the tier — a project with only VM reservations
            # would see every TPU QR go FAILED.
            body["guaranteed"] = {"reserved": True}
        _http("POST",
              f"{TPU_API}/{_parent(config.zone)}/queuedResources"
              f"?queuedResourceId={_node_name(config.cluster_name)}", body)
        ids = ([f"{config.cluster_name}-{i}"
                for i in range(config.num_nodes)]
               if config.num_nodes > 1 else [config.cluster_name])
    else:
        _http("POST",
              f"{TPU_API}/{_parent(config.zone)}/nodes"
              f"?nodeId={_node_name(config.cluster_name)}", node_body)
        ids = [config.cluster_name]
    open_ports(config.cluster_name, config.ports)
    return ProvisionRecord("gcp", config.cluster_name, config.zone,
                           created_instance_ids=ids)


def wait_instances(cluster_name: str, zone: str, timeout: float = 1800,
                   poll: float = 10.0) -> None:
    """Wait for every node of the cluster READY (queued resources:
    WAITING->PROVISIONING->ACTIVE, then the node(s) READY; multislice
    QRs gang-provision all slices atomically). Non-recoverable queue
    states (FAILED/SUSPENDED) raise CapacityError -> failover."""
    names, qr_exists = _node_names_ex(cluster_name, zone)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not qr_exists:
            # QR may have materialized since the first look (direct v2/
            # v3 nodes never have one; re-resolving is one GET).
            names, qr_exists = _node_names_ex(cluster_name, zone)
        states = _node_states(cluster_name, zone, names)
        if not qr_exists and all(s is None for s in states):
            # Not a TPU cluster (or not materialized): Compute VMs?
            vms = _list_cluster_vms_safe(cluster_name, zone)
            if vms:
                if all(v.get("status") == "RUNNING" for v in vms):
                    return
                time.sleep(poll)
                continue
        if any(s in ("PREEMPTED", "TERMINATED") for s in states):
            raise exceptions.CapacityError(
                f"TPU node entered {states} while waiting")
        if states and all(s == "READY" for s in states):
            return
        if any(s is None for s in states):
            # Node(s) not yet materialized; check the queued resource.
            try:
                qr = _http("GET", _qr_url(cluster_name, zone))
                qstate = qr.get("state", {}).get("state")
                if qstate in ("FAILED", "SUSPENDED", "SUSPENDING"):
                    raise exceptions.CapacityError(
                        f"queued resource {qstate}: "
                        f"{qr.get('state')}")
            except exceptions.ClusterNotUpError:
                pass
        time.sleep(poll)
    raise exceptions.ProvisionTimeoutError(
        f"TPU {cluster_name} not READY within {timeout}s")


def stop_instances(cluster_name: str, zone: str) -> None:
    # TPU-VM pods cannot stop (reference: clouds/gcp.py:206-212 carries
    # the same restriction); single-host nodes can. The node state is
    # read directly (not via get_cluster_info) so a STOPPED or transient
    # node cannot mask the friendly refusal below.
    names = _node_names(cluster_name, zone)
    if len(names) > 1:
        raise exceptions.ResourcesUnavailableError(
            "multislice clusters cannot be stopped; use down instead",
            no_failover=True)
    try:
        node = _http("GET", _node_url(cluster_name, zone, names[0]))
    except exceptions.ClusterNotUpError:
        vms = _list_cluster_vms_safe(cluster_name, zone)
        if not vms:
            raise
        for vm in vms:  # Compute VMs stop per-instance
            if vm.get("status") != "TERMINATED":
                _http("POST", f"{_compute_zone_url(zone)}/instances/"
                              f"{vm['name']}/stop")
        return
    if node.get("state") == "STOPPED":
        return  # idempotent
    if len(node.get("networkEndpoints", [])) > 1:
        raise exceptions.ResourcesUnavailableError(
            "multi-host TPU slices cannot be stopped; use down instead",
            no_failover=True)
    _http("POST", _node_url(cluster_name, zone, names[0]) + ":stop")


def terminate_instances(cluster_name: str, zone: str) -> None:
    urls = [_node_url(cluster_name, zone, n)
            for n in _node_names(cluster_name, zone)]
    urls.append(_qr_url(cluster_name, zone))
    for url in urls:
        try:
            _http("DELETE", url + "?force=true")
        except exceptions.ClusterNotUpError:
            continue
        except exceptions.ResourcesUnavailableError:
            # queued resources require force delete only when provisioning
            raise
    for vm in _list_cluster_vms_safe(cluster_name, zone):
        try:
            _http("DELETE",
                  f"{_compute_zone_url(zone)}/instances/{vm['name']}")
        except exceptions.ClusterNotUpError:
            continue
    # The port firewall rule dies with the cluster (reference:
    # sky/provision/gcp/instance.py:628 cleanup_ports).
    cleanup_ports(cluster_name)


def query_instances(cluster_name: str, zone: str) -> str:
    """Aggregate across slices: UP iff every node READY; NOT_FOUND iff
    every node gone (incl. a preempted multislice member — slice-wide
    preemption takes the whole gang, so recovery relaunches all)."""
    names, _ = _node_names_ex(cluster_name, zone)
    states = _node_states(cluster_name, zone, names)
    mapped = [{"READY": "UP", "STOPPED": "STOPPED",
               "PREEMPTED": None, "TERMINATED": None}.get(s, "PARTIAL")
              if s is not None else None for s in states]
    if all(m is None for m in mapped):
        return _compute_status(_list_cluster_vms_safe(cluster_name, zone))
    if all(m == "UP" for m in mapped):
        return "UP"
    if all(m == "STOPPED" for m in mapped):
        return "STOPPED"
    return "PARTIAL"


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    hosts: List[HostInfo] = []
    accel_type = None
    node_state = None
    names = _node_names(cluster_name, zone)
    try:
        _http("GET", _node_url(cluster_name, zone, names[0]))
    except exceptions.ClusterNotUpError:
        vms = _list_cluster_vms_safe(cluster_name, zone)
        if vms:
            return _compute_cluster_info(cluster_name, zone, vms)
        raise
    for slice_id, name in enumerate(names):
        node = _http("GET", _node_url(cluster_name, zone, name))
        accel_type = node.get("acceleratorType")
        node_state = node.get("state")
        for i, ep in enumerate(node.get("networkEndpoints", [])):
            ext = (ep.get("accessConfig") or {}).get("externalIp")
            hosts.append(HostInfo(
                host_id=len(hosts), node_id=slice_id, worker_id=i,
                internal_ip=ep.get("ipAddress", ""),
                external_ip=ext, ssh_user="skypilot", ssh_port=22))
    return ClusterInfo(cluster_name=cluster_name, provider="gcp", zone=zone,
                       hosts=hosts,
                       ssh_key_path="~/.ssh/sky-key",
                       metadata={"accelerator_type": accel_type,
                                 "state": node_state,
                                 "num_slices": len(names)})


def get_command_runners(info: ClusterInfo) -> List[command_runner.CommandRunner]:
    runners = []
    for h in info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(command_runner.SSHRunner(
            ip=ip, user=h.ssh_user or "skypilot",
            key_path=info.ssh_key_path or "~/.ssh/sky-key",
            host_id=h.host_id, port=h.ssh_port))
    return runners


# -- Compute Engine (GPU / CPU VMs) ----------------------------------------
# Reference parity: GCPComputeInstance (sky/provision/gcp/
# instance_utils.py:311). Needed for the GPU catalog rows and for CPU
# controller VMs (jobs/serve controller-as-task); same zero-SDK REST
# style as the TPU path.

def _compute_zone_url(zone: str) -> str:
    project = gcp_auth.get_project()
    if not project:
        raise exceptions.NoCloudAccessError(
            "no GCP project configured (set GOOGLE_CLOUD_PROJECT or "
            "`gcloud config set project`)")
    return f"{COMPUTE_API}/projects/{project}/zones/{zone}"


def _vm_names(config: ProvisionConfig) -> List[str]:
    n = config.num_nodes * config.hosts_per_node
    if n == 1:
        return [config.cluster_name]
    return [f"{config.cluster_name}-{i}" for i in range(n)]


def _list_cluster_vms(cluster_name: str, zone: str) -> List[dict]:
    resp = _http(
        "GET",
        f"{_compute_zone_url(zone)}/instances"
        f"?filter=labels.skypilot-tpu-cluster%3D{cluster_name}")
    return resp.get("items", [])


def _list_cluster_vms_safe(cluster_name: str, zone: str) -> List[dict]:
    """Compute list as a fallback PROBE from TPU paths: a project with
    the Compute API disabled (TPU-only scope) must not fail TPU
    status/teardown just because the probe errored."""
    try:
        return _list_cluster_vms(cluster_name, zone)
    except exceptions.SkyTpuError:
        return []


def _ssh_pubkey_metadata() -> List[dict]:
    import os
    pub = os.path.expanduser("~/.ssh/sky-key.pub")
    if not os.path.exists(pub):
        return []
    with open(pub) as f:
        return [{"key": "ssh-keys", "value": f"skypilot:{f.read().strip()}"}]


def _run_compute_instances(config: ProvisionConfig) -> ProvisionRecord:
    expected = _vm_names(config)
    existing = {v["name"]: v for v in
                _list_cluster_vms(config.cluster_name, config.zone)}
    # Resume stopped VMs ("TERMINATED" is Compute-speak for stopped).
    for name, vm in existing.items():
        if vm.get("status") == "TERMINATED":
            _http("POST",
                  f"{_compute_zone_url(config.zone)}/instances/"
                  f"{name}/start")
    # Reconcile against the expected set: a partially-failed earlier
    # create must top up the missing VMs, not silently under-provision.
    missing = [n for n in expected if n not in existing]
    if not missing:
        open_ports(config.cluster_name, config.ports)
        return ProvisionRecord("gcp", config.cluster_name, config.zone,
                               resumed=True)

    if not config.instance_type:
        raise exceptions.ResourcesUnavailableError(
            f"gcp VM provisioning needs an instance_type (accelerator="
            f"{config.accelerator!r} is not a TPU)", no_failover=True)
    accel_attach = []
    if config.accelerator and not config.instance_type.startswith(
            _BUILTIN_GPU_FAMILIES):
        gpu_type = GPU_ACCELERATOR_TYPES.get(config.accelerator)
        if gpu_type is None:
            raise exceptions.ResourcesUnavailableError(
                f"no Compute Engine accelerator mapping for "
                f"{config.accelerator!r} on {config.instance_type}",
                no_failover=True)
        accel_attach = [{
            "acceleratorType": (f"zones/{config.zone}/acceleratorTypes/"
                                f"{gpu_type}"),
            "acceleratorCount": config.accelerator_count or 1,
        }]
    created = []
    for name in missing:
        body = {
            "name": name,
            "machineType": (f"zones/{config.zone}/machineTypes/"
                            f"{config.instance_type}"),
            "disks": [{"boot": True, "autoDelete": True,
                       "initializeParams": {
                           # docker:<img> is a CONTAINER image — the VM
                           # still boots the stock image; the container
                           # is set up post-provision.
                           "sourceImage": (
                               DEFAULT_VM_IMAGE
                               if extract_docker_image(config.image_id)
                               else config.image_id or DEFAULT_VM_IMAGE),
                           "diskSizeGb": str(config.disk_size)}}],
            "networkInterfaces": [{
                "network": "global/networks/default",
                "accessConfigs": [{"type": "ONE_TO_ONE_NAT",
                                   "name": "External NAT"}]}],
            "tags": {"items": [config.cluster_name]},
            "labels": dict(config.labels,
                           **{"skypilot-tpu-cluster": config.cluster_name}),
            "metadata": {"items": _ssh_pubkey_metadata()},
        }
        if accel_attach:
            body["guestAccelerators"] = accel_attach
        if accel_attach or config.use_spot:
            # GPUs require TERMINATE-on-maintenance; spot VMs likewise.
            body["scheduling"] = {
                "onHostMaintenance": "TERMINATE",
                "preemptible": bool(config.use_spot),
            }
        if not config.use_spot:
            # Name only reservations that exist in THIS zone with free
            # capacity and a matching machine type: a blanket
            # SPECIFIC_RESERVATION affinity is rejected by the API in
            # every other zone, turning an advisory cost hint into a
            # hard provisioning outage.
            try:
                usable = [n for n, free in list_reservations_available(
                    config.zone, config.instance_type).items() if free > 0]
            except Exception:  # noqa: BLE001 — advisory; create without
                usable = []
            if usable:
                body["reservationAffinity"] = {
                    "consumeReservationType": "SPECIFIC_RESERVATION",
                    "key": "compute.googleapis.com/reservation-name",
                    "values": usable,
                }
        _http("POST", f"{_compute_zone_url(config.zone)}/instances", body)
        created.append(name)
    open_ports(config.cluster_name, config.ports)
    return ProvisionRecord("gcp", config.cluster_name, config.zone,
                           created_instance_ids=created,
                           resumed=bool(existing))


def _compute_status(vms: List[dict]) -> str:
    if not vms:
        return "NOT_FOUND"
    statuses = {v.get("status") for v in vms}
    if statuses == {"RUNNING"}:
        return "UP"
    if statuses == {"TERMINATED"}:
        return "STOPPED"
    return "PARTIAL"


def _compute_cluster_info(cluster_name: str, zone: str,
                          vms: List[dict]) -> ClusterInfo:
    hosts: List[HostInfo] = []
    for i, vm in enumerate(sorted(vms, key=lambda v: v["name"])):
        nic = (vm.get("networkInterfaces") or [{}])[0]
        ext = ((nic.get("accessConfigs") or [{}])[0]).get("natIP")
        hosts.append(HostInfo(
            host_id=i, node_id=i, worker_id=0,
            internal_ip=nic.get("networkIP", ""),
            external_ip=ext, ssh_user="skypilot", ssh_port=22))
    return ClusterInfo(cluster_name=cluster_name, provider="gcp",
                       zone=zone, hosts=hosts,
                       ssh_key_path="~/.ssh/sky-key",
                       metadata={"vm_cluster": True})
