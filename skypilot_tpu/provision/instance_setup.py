"""Post-provision runtime setup on cluster hosts.

Reference parity: sky/provision/instance_setup.py (wait_for_ssh via
provisioner.py:349, internal_file_mounts :536, setup_runtime_on_cluster
:202) — minus the Ray/venv bootstrap, which has no TPU-native
equivalent: TPU-VM runtime images ship JAX/libtpu matched to the chip,
and gang scheduling needs no cluster manager (runtime/driver.py). What
remains is: wait for SSH, push the framework + workspace dirs, verify
the JAX runtime imports.
"""

from __future__ import annotations

import concurrent.futures
import os
import shlex
import time
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.utils import command_runner

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SETUP_COMMANDS = (
    "mkdir -p ~/sky_workdir ~/.skypilot_tpu",
    # Verify the TPU runtime python can import jax (the runtime image's
    # venv); tolerate CPU-only hosts (controllers).
    "python3 -c 'import jax' 2>/dev/null || true",
)


def wait_for_ssh(info: ClusterInfo, timeout: float = 600,
                 poll: float = 5.0) -> None:
    """Block until every host accepts commands."""
    runners = _runners(info)
    deadline = time.time() + timeout
    pending = list(range(len(runners)))
    while pending and time.time() < deadline:
        still = []
        for i in pending:
            rc, _, _ = runners[i].run("true", timeout=15)
            if rc != 0:
                still.append(i)
        pending = still
        if pending:
            time.sleep(poll)
    if pending:
        raise exceptions.ProvisionTimeoutError(
            f"hosts {pending} unreachable after {timeout}s")


def setup_runtime_on_cluster(info: ClusterInfo,
                             sync_framework: bool = True,
                             max_workers: int = 32) -> None:
    """Run setup on all hosts in parallel (reference: per-node parallel
    SSH with result cache, instance_setup.py:135)."""
    runners = _runners(info)

    def setup_one(runner: command_runner.CommandRunner) -> None:
        for cmd in SETUP_COMMANDS:
            rc, _, err = runner.run(cmd, timeout=120)
            if rc != 0:
                raise exceptions.CommandError(rc, cmd, err)
        if sync_framework and not runner.is_local:
            # Self-replication: push this package so in-tree recipes can
            # `import skypilot_tpu` on the hosts (the role of the
            # reference's wheel build, backends/wheel_utils.py:140).
            # Remote runners put $HOME/.skypilot_tpu/pkg on PYTHONPATH
            # (command_runner.framework_invocation + the driver's job
            # wrapper), which makes this dir importable.
            runner.rsync(_PKG_ROOT, "~/.skypilot_tpu/pkg/skypilot_tpu",
                         up=True)
        if (runner.host_id == info.head.host_id and not runner.is_local
                and info.ssh_key_path
                and os.path.exists(os.path.expanduser(info.ssh_key_path))):
            # The head runs the gang driver and must SSH to its peer
            # hosts: give it the cluster key at the path
            # runtime/topology.py's runners expect.
            runner.run("mkdir -p ~/.skypilot_tpu/ssh")
            runner.rsync(os.path.expanduser(info.ssh_key_path),
                         "~/.skypilot_tpu/ssh/sky-key", up=True)
            runner.run("chmod 600 ~/.skypilot_tpu/ssh/sky-key")

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(max_workers, max(len(runners), 1))) as ex:
        list(ex.map(setup_one, runners))


DOCKER_CONTAINER = "skytpu-container"

# `docker` without the daemon group needs sudo; probe once per command.
_DOCKER_PREFIX = ('d=docker; docker info >/dev/null 2>&1 || '
                  'd="sudo docker"; ')


def docker_exec_command(inner: str, env: dict = None) -> str:
    """Wrap a shell script to run inside the cluster's task container
    with ``env`` injected (docker exec does not inherit the host
    process env the way a plain detached job does)."""
    flags = "".join(f"-e {shlex.quote(f'{k}={v}')} "
                    for k, v in (env or {}).items())
    return (f"{_DOCKER_PREFIX}$d exec {flags}{DOCKER_CONTAINER} "
            f"bash -c {shlex.quote(inner)}")


def setup_docker_on_cluster(info: ClusterInfo, image: str,
                            max_workers: int = 32) -> None:
    """Pull ``image`` and (re)start the task container on every host.

    Reference parity: sky/provision/docker_utils.py (initialize: pull,
    docker run with host networking, then exec user commands inside).
    Design delta: the host's $HOME — including the synced framework pkg
    and ~/sky_workdir — is bind-mounted at /root, so the container sees
    exactly the files the plain-VM path uses; --net=host --privileged
    keeps TPU device access and the gang rank/coordinator ports
    identical inside and outside."""
    runners = _runners(info)
    q = shlex.quote(image)
    cmds = [
        # Stock VM images (the boot image under a docker: task) ship
        # without docker — install it first (reference:
        # docker_utils.py initialize checks/installs the daemon).
        "command -v docker >/dev/null 2>&1 || "
        "(sudo apt-get update -qq >/dev/null 2>&1 && "
        "sudo apt-get install -y -qq docker.io >/dev/null) || "
        "command -v docker",
        f"{_DOCKER_PREFIX}$d pull {q}",
        f"{_DOCKER_PREFIX}$d rm -f {DOCKER_CONTAINER} >/dev/null 2>&1; "
        # --entrypoint: ML images commonly set ENTRYPOINT (python3,
        # conda run, ...) which would turn a bare `sleep infinity` CMD
        # into `<entrypoint> sleep infinity` and exit at once.
        f'$d run -d --name {DOCKER_CONTAINER} --net=host --privileged '
        f'-v "$HOME:/root" --entrypoint sleep {q} infinity',
    ]

    def docker_one(runner: command_runner.CommandRunner) -> None:
        for cmd in cmds:
            rc, out, err = runner.run(cmd, timeout=600)
            if rc != 0:
                raise exceptions.CommandError(rc, cmd, out + err)

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(max_workers, max(len(runners), 1))) as ex:
        list(ex.map(docker_one, runners))


def start_host_agents(info: ClusterInfo, token: str,
                      port: int = command_runner.AGENT_PORT) -> str:
    """Start runtime/hostd.py on every host that needs a non-SSH exec
    transport (kubernetes pods): push the shared token, launch the
    agent detached, under `python -S` with the rsynced package.

    Returns the token actually IN FORCE: hostd reads its token file once
    at startup and the launch guard keeps a running agent alive, so a
    re-provision must reuse the existing cluster token rather than
    overwrite it with a fresh one the live agents would reject."""
    import shlex
    runners = _runners(info)
    agent_hosts = [(h, r) for h, r in zip(info.hosts, runners)
                   if h.runner_kind == "k8s"]
    if not agent_hosts:
        return token
    existing = agent_hosts[0][1].read_file("~/.skypilot_tpu/agent_token")
    if existing and existing.strip():
        token = existing.strip()

    def start_one(pair) -> None:
        host, runner = pair
        rc, _, err = runner.run(
            f"mkdir -p ~/.skypilot_tpu && "
            f"printf %s {shlex.quote(token)} > ~/.skypilot_tpu/agent_token"
            f" && chmod 600 ~/.skypilot_tpu/agent_token")
        if rc != 0:
            raise exceptions.CommandError(rc, "push agent token", err)
        # Logging is handled inside the command ($HOME expands in the
        # pod's shell — a quoted log_path argument would not). A live
        # agent speaking an older wire protocol (recorded in
        # hostd.protocol at its startup) is killed first, or it would
        # silently drop newer request fields forever.
        from skypilot_tpu.runtime import hostd
        want = hostd.PROTOCOL_VERSION
        # pgrep/pkill must not match this script's own detached wrapper
        # shell, whose argv contains the whole script text — so the
        # module path never appears as a contiguous literal anywhere in
        # it: it is assembled in $m from a split literal, and the agent
        # is launched via "$m" too.
        runner.run_detached(
            'm=skypilot_tpu.runtime.host; m="${m}d"; '
            'v=$(cat "$HOME/.skypilot_tpu/hostd.protocol" 2>/dev/null'
            f' || echo 0); if [ "$v" != "{want}" ]; then '
            # Bounded wait for the old agent to actually exit: a fixed
            # 0.2s sleep races a slow-dying process, and the pgrep
            # start-guard below would then suppress the relaunch,
            # leaving the pod with no agent at all.
            'pkill -f "$m"; i=0; '
            'while pgrep -f "$m" >/dev/null && [ "$i" -lt 25 ]; do '
            'sleep 0.2; i=$((i+1)); done; '
            'pgrep -f "$m" >/dev/null && { pkill -9 -f "$m"; sleep 0.3; }; '
            'fi; '
            'pgrep -f "$m" >/dev/null || '
            '(cd "$HOME" && mkdir -p .skypilot_tpu && '
            f'PYTHONPATH="$HOME/{command_runner.REMOTE_PKG_DIR}'
            ':$PYTHONPATH" python3 -S -m "$m" '
            f"--port {port} >> .skypilot_tpu/hostd.log 2>&1)",
            log_path="/dev/null")

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, max(len(agent_hosts), 1))) as ex:
        list(ex.map(start_one, agent_hosts))
    return token


def _runners(info: ClusterInfo) -> List[command_runner.CommandRunner]:
    from skypilot_tpu import provision
    return provision.get_command_runners(info)
