"""AWS credentials + SigV4 request signing, zero-SDK.

Counterpart of ``gcp_auth.py`` for AWS: the reference shells out to
boto3 via adaptors (reference: sky/adaptors/aws.py session caching,
sky/clouds/aws.py:check_credentials); here credentials come straight
from the environment or ``~/.aws/credentials`` and requests to the EC2/
STS Query APIs are signed with a stdlib-only Signature V4
implementation (hashlib/hmac), so no SDK is imported anywhere.

SigV4 is specified publicly (AWS General Reference, "Signature Version
4 signing process"); the implementation is tested against the
documented derived-key example vector in tests/test_aws_provision.py.
"""

from __future__ import annotations

import configparser
import datetime
import hashlib
import hmac
import os
import urllib.parse
from typing import Dict, Optional, Tuple


class AwsCredentials:
    def __init__(self, access_key: str, secret_key: str,
                 session_token: Optional[str] = None):
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token


def load_credentials(profile: Optional[str] = None
                     ) -> Optional[AwsCredentials]:
    """Env first (CI/containers), then ~/.aws/credentials INI."""
    ak = os.environ.get("AWS_ACCESS_KEY_ID")
    sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if ak and sk:
        return AwsCredentials(ak, sk, os.environ.get("AWS_SESSION_TOKEN"))
    path = os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE",
        os.path.expanduser("~/.aws/credentials"))
    if not os.path.exists(path):
        return None
    cp = configparser.ConfigParser()
    cp.read(path)
    section = profile or os.environ.get("AWS_PROFILE", "default")
    if section not in cp:
        return None
    sec = cp[section]
    ak = sec.get("aws_access_key_id")
    sk = sec.get("aws_secret_access_key")
    if not (ak and sk):
        return None
    return AwsCredentials(ak, sk, sec.get("aws_session_token"))


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def derive_signing_key(secret_key: str, date: str, region: str,
                       service: str) -> bytes:
    """kSigning = HMAC-chain over date/region/service/'aws4_request'
    (the documented SigV4 key-derivation ladder)."""
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def sign_request(creds: AwsCredentials, method: str, host: str,
                 path: str, params: Dict[str, str], region: str,
                 service: str,
                 now: Optional[datetime.datetime] = None
                 ) -> Tuple[str, Dict[str, str], bytes]:
    """Sign a form-encoded POST (the EC2/STS Query API convention).

    Returns (url, headers, body). ``now`` is injectable for the
    known-vector test.
    """
    if now is None:
        now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")

    body = urllib.parse.urlencode(sorted(params.items())).encode()
    payload_hash = hashlib.sha256(body).hexdigest()

    headers = {
        "Content-Type": "application/x-www-form-urlencoded; charset=utf-8",
        "Host": host,
        "X-Amz-Date": amz_date,
    }
    if creds.session_token:
        headers["X-Amz-Security-Token"] = creds.session_token

    signed_names = sorted(h.lower() for h in headers)
    canonical_headers = "".join(
        f"{n}:{headers[next(h for h in headers if h.lower() == n)].strip()}\n"
        for n in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method, path, "",  # query string empty: params ride in the body
        canonical_headers, signed_headers, payload_hash])

    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    key = derive_signing_key(creds.secret_key, date, region, service)
    signature = hmac.new(key, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return f"https://{host}{path}", headers, body


def check_credentials() -> Tuple[bool, str]:
    """STS GetCallerIdentity — the canonical 'are my keys valid' probe
    (reference: sky/clouds/aws.py check_credentials uses the same)."""
    creds = load_credentials()
    if creds is None:
        return False, ("no AWS credentials (set AWS_ACCESS_KEY_ID/"
                       "AWS_SECRET_ACCESS_KEY or ~/.aws/credentials)")
    import urllib.error
    import urllib.request

    url, headers, body = sign_request(
        creds, "POST", "sts.amazonaws.com", "/",
        {"Action": "GetCallerIdentity", "Version": "2011-06-15"},
        region="us-east-1", service="sts")
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            text = resp.read().decode()
    except urllib.error.HTTPError as e:
        return False, f"STS rejected the credentials ({e.code})"
    except OSError as e:
        return False, f"cannot reach STS: {e}"
    import re
    m = re.search(r"<Arn>([^<]+)</Arn>", text)
    return True, f"authenticated as {m.group(1) if m else 'unknown ARN'}"


def default_region() -> str:
    return (os.environ.get("AWS_DEFAULT_REGION")
            or os.environ.get("AWS_REGION") or "us-east-1")
