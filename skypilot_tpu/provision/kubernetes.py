"""Kubernetes (GKE-first) provisioning: TPU podslice pods via kubectl.

Implements the uniform provider function set (provision/__init__.py) on
top of ``kubectl`` — no python k8s SDK dependency. One pod per host;
TPU hosts get GKE podslice nodeSelectors
(``cloud.google.com/gke-tpu-accelerator`` + ``gke-tpu-topology``) and a
``google.com/tpu`` chip request, which is how GKE gang-places the pods
of one slice (all-or-nothing by node-pool shape).

``kubectl`` binary is overridable via ``SKYTPU_KUBECTL`` (tests inject
a recording fake, mirroring the reference's offline strategy).

Reference parity: sky/provision/kubernetes/ (pod-based provisioning,
TPU-on-GKE labels; SURVEY.md §2.3) and the GKE TPU podslice smoke test
(reference: tests/smoke_tests/test_cluster_job.py:593-601).
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.resources import extract_docker_image
from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord)
from skypilot_tpu.utils.command_runner import CommandRunner

LABEL = "skypilot-tpu/cluster"
# Pods cannot stop (delete/recreate is the k8s lifecycle). Multi-pod
# gang execution works through the per-pod hostd agent
# (runtime/hostd.py), started by instance_setup.start_host_agents.
from skypilot_tpu.provision import Feature as _F  # noqa: E402
FEATURES = frozenset(_F) - {_F.STOP}

NODE_LABEL = "skypilot-tpu/node"
WORKER_LABEL = "skypilot-tpu/worker"
DEFAULT_IMAGE = "python:3.11-slim"

# TPU accelerator ("tpu-v5e-16") -> GKE podslice accelerator label.
_GKE_TPU_ACCEL = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}

# GPU accelerator name -> GKE node label value
# (cloud.google.com/gke-accelerator). Reference:
# sky/provision/kubernetes/utils.py GKELabelFormatter.
_GKE_GPU_ACCEL = {
    "T4": "nvidia-tesla-t4",
    "L4": "nvidia-l4",
    "V100": "nvidia-tesla-v100",
    "P100": "nvidia-tesla-p100",
    "A100": "nvidia-tesla-a100",
    "A100-80GB": "nvidia-a100-80gb",
    "H100": "nvidia-h100-80gb",
}

# (version, total chips) -> topology label. 8 chips/host for v5e/v6e
# single-host; pods of multi-host slices each see 4 chips (2x2 per host).
_TOPOLOGY = {
    ("v5e", 1): "1x1", ("v5e", 4): "2x2", ("v5e", 8): "2x4",
    ("v5e", 16): "4x4", ("v5e", 32): "4x8", ("v5e", 64): "8x8",
    ("v5e", 128): "8x16", ("v5e", 256): "16x16",
    ("v6e", 1): "1x1", ("v6e", 4): "2x2", ("v6e", 8): "2x4",
    ("v6e", 16): "4x4", ("v6e", 32): "4x8", ("v6e", 64): "8x8",
    ("v6e", 128): "8x16", ("v6e", 256): "16x16",
    ("v5p", 8): "2x2x1", ("v5p", 16): "2x2x2", ("v5p", 32): "2x2x4",
    ("v4", 8): "2x2x1", ("v4", 16): "2x2x2", ("v4", 32): "2x2x4",
}


def _kubectl() -> str:
    return os.environ.get("SKYTPU_KUBECTL", "kubectl")


def _run(args: List[str], stdin: Optional[str] = None) -> Tuple[int, str]:
    proc = subprocess.run([_kubectl(), *args], input=stdin,
                          capture_output=True, text=True)
    return proc.returncode, (proc.stdout or "") + (proc.stderr or "")


def parse_tpu_accelerator(accelerator: str) -> Tuple[str, int]:
    """'tpu-v5e-16' -> ('v5e', 16)."""
    parts = accelerator.split("-")
    if len(parts) != 3 or parts[0] != "tpu":
        raise exceptions.ProvisionError(
            f"unrecognized TPU accelerator {accelerator!r}")
    return parts[1], int(parts[2])


def pod_manifest(config: ProvisionConfig, node_id: int,
                 worker_id: int) -> Dict:
    """One host's pod spec (dict -> YAML/JSON for kubectl apply)."""
    name = f"{config.cluster_name}-{node_id}-{worker_id}"
    spec: Dict = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "labels": {
                LABEL: config.cluster_name,
                NODE_LABEL: str(node_id),
                WORKER_LABEL: str(worker_id),
                **config.labels,
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "task",
                # On k8s the pod IS the container: a docker:<img>
                # image_id becomes the pod image directly (the VM
                # providers instead boot a stock image and run the
                # container inside; backend skips that path for k8s
                # hosts).
                "image": (extract_docker_image(config.image_id)
                          or config.image_id or DEFAULT_IMAGE),
                "command": ["/bin/sh", "-c",
                            "sleep infinity"],
                "resources": {"requests": {}, "limits": {}},
            }],
        },
    }
    if config.accelerator and config.accelerator.startswith("tpu-"):
        version, chips = parse_tpu_accelerator(config.accelerator)
        accel_label = _GKE_TPU_ACCEL.get(version)
        if accel_label is None:
            raise exceptions.ProvisionError(
                f"no GKE podslice mapping for TPU version {version!r}")
        topology = _TOPOLOGY.get((version, chips))
        if topology is None:
            raise exceptions.ProvisionError(
                f"no GKE topology mapping for {config.accelerator!r}")
        chips_per_host = max(chips // max(config.hosts_per_node, 1), 1)
        spec["spec"]["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": accel_label,
            "cloud.google.com/gke-tpu-topology": topology,
        }
        res = spec["spec"]["containers"][0]["resources"]
        res["requests"]["google.com/tpu"] = str(chips_per_host)
        res["limits"]["google.com/tpu"] = str(chips_per_host)
        if config.use_spot:
            spec["spec"]["tolerations"] = [{
                "key": "cloud.google.com/gke-spot",
                "operator": "Equal", "value": "true",
                "effect": "NoSchedule"}]
            spec["spec"]["nodeSelector"][
                "cloud.google.com/gke-spot"] = "true"
    elif config.accelerator:
        # GPU-on-k8s (LIVE-UNTESTED like the rest of this provider —
        # modeled on GKE's device-plugin contract: nvidia.com/gpu
        # requests + gke-accelerator node label; reference:
        # sky/provision/kubernetes/utils.py GKELabelFormatter).
        gke_label = _GKE_GPU_ACCEL.get(config.accelerator.upper())
        if gke_label is None:
            raise exceptions.ProvisionError(
                f"no GKE accelerator mapping for "
                f"{config.accelerator!r} (known: "
                f"{sorted(_GKE_GPU_ACCEL)})")
        n = config.accelerator_count or 1
        spec["spec"].setdefault("nodeSelector", {})[
            "cloud.google.com/gke-accelerator"] = gke_label
        res = spec["spec"]["containers"][0]["resources"]
        res["requests"]["nvidia.com/gpu"] = str(n)
        res["limits"]["nvidia.com/gpu"] = str(n)
        spec["spec"]["tolerations"] = [{
            "key": "nvidia.com/gpu", "operator": "Exists",
            "effect": "NoSchedule"}]
        if config.use_spot:
            spec["spec"]["tolerations"].append({
                "key": "cloud.google.com/gke-spot",
                "operator": "Equal", "value": "true",
                "effect": "NoSchedule"})
            spec["spec"]["nodeSelector"][
                "cloud.google.com/gke-spot"] = "true"
    return spec


# ---------------------------------------------------------------------------
# Provider function set
# ---------------------------------------------------------------------------

def check_credentials() -> Tuple[bool, str]:
    """kubectl reachable and pointed at a context?"""
    try:
        rc, out = _run(["config", "current-context"])
    except FileNotFoundError:
        return False, "kubectl not installed"
    if rc != 0:
        return False, f"no kubectl context: {out.strip()[:200]}"
    return True, f"context {out.strip()}"


def run_instances(config: ProvisionConfig) -> ProvisionRecord:
    created = []
    for node_id in range(config.num_nodes):
        for worker_id in range(config.hosts_per_node):
            manifest = pod_manifest(config, node_id, worker_id)
            rc, out = _run(["apply", "-f", "-"],
                           stdin=json.dumps(manifest))
            if rc != 0:
                raise exceptions.ProvisionError(
                    f"kubectl apply failed for "
                    f"{manifest['metadata']['name']}: {out.strip()}")
            created.append(manifest["metadata"]["name"])
    if config.ports:
        open_ports(config.cluster_name, config.ports)
    return ProvisionRecord(provider="kubernetes",
                           cluster_name=config.cluster_name,
                           zone=config.zone,
                           created_instance_ids=created)


# -- networking --------------------------------------------------------------
#
# Reference parity: sky/provision/kubernetes/network.py (open_ports /
# query_ports / cleanup_ports; LoadBalancer vs ingress modes). Here a
# NodePort Service on the HEAD pod is the portable default (works on
# GKE and kind alike, no ingress controller prerequisite);
# port_forward_command covers clusters whose nodes have no reachable
# address.

def _service_name(cluster_name: str) -> str:
    return f"{cluster_name}-skytpu-svc"


def _ingress_name(cluster_name: str) -> str:
    return f"{cluster_name}-skytpu-ingress"


def ports_mode() -> str:
    """'nodeport' (default — works on GKE and kind with no
    prerequisite) | 'loadbalancer' | 'ingress' (requires an nginx
    ingress controller). Reference parity:
    sky/provision/kubernetes/network.py port modes."""
    from skypilot_tpu import config as config_lib
    mode = (config_lib.get_nested(("kubernetes", "ports"),
                                  "nodeport") or "nodeport").lower()
    if mode not in ("nodeport", "loadbalancer", "ingress"):
        raise exceptions.ProvisionError(
            f"kubernetes.ports must be nodeport|loadbalancer|ingress, "
            f"got {mode!r}")
    return mode


def service_manifest(cluster_name: str, ports: List[int],
                     svc_type: str = "NodePort") -> Dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": _service_name(cluster_name),
                     "labels": {LABEL: cluster_name}},
        "spec": {
            "type": svc_type,
            "selector": {LABEL: cluster_name,
                         NODE_LABEL: "0", WORKER_LABEL: "0"},
            "ports": [{"name": f"p{p}", "port": int(p),
                       "targetPort": int(p), "protocol": "TCP"}
                      for p in ports],
        },
    }


def ingress_manifest(cluster_name: str, ports: List[int]) -> Dict:
    """One nginx Ingress fronting the cluster Service: port p is
    reachable at path /skytpu/{cluster}/{p}/ (rewritten to / for the
    backend). Reference: sky/provision/kubernetes/network.py ingress
    mode with the same per-port path layout."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "Ingress",
        "metadata": {
            "name": _ingress_name(cluster_name),
            "labels": {LABEL: cluster_name},
            "annotations": {
                "nginx.ingress.kubernetes.io/rewrite-target": "/$2",
                "nginx.ingress.kubernetes.io/use-regex": "true",
            },
        },
        "spec": {
            "ingressClassName": "nginx",
            "rules": [{
                "http": {
                    "paths": [{
                        "path": (f"/skytpu/{cluster_name}/{p}"
                                 f"(/|$)(.*)"),
                        "pathType": "ImplementationSpecific",
                        "backend": {"service": {
                            "name": _service_name(cluster_name),
                            "port": {"number": int(p)}}},
                    } for p in ports],
                },
            }],
        },
    }


def open_ports(cluster_name: str, ports: List[int],
               zone: str = None) -> None:
    mode = ports_mode()
    svc_type = {"nodeport": "NodePort",
                "loadbalancer": "LoadBalancer",
                "ingress": "ClusterIP"}[mode]
    manifest = service_manifest(cluster_name, ports, svc_type)
    rc, out = _run(["apply", "-f", "-"], stdin=json.dumps(manifest))
    if rc != 0:
        raise exceptions.ProvisionError(
            f"kubectl apply (service) failed: {out.strip()}")
    if mode == "ingress":
        rc, out = _run(["apply", "-f", "-"],
                       stdin=json.dumps(
                           ingress_manifest(cluster_name, ports)))
        if rc != 0:
            raise exceptions.ProvisionError(
                f"kubectl apply (ingress) failed: {out.strip()}")


def cleanup_ports(cluster_name: str, zone: str = None) -> None:
    _run(["delete", "service", _service_name(cluster_name),
          "--ignore-not-found", "--wait=false"])
    _run(["delete", "ingress", _ingress_name(cluster_name),
          "--ignore-not-found", "--wait=false"])


def _json_from(out: str) -> Optional[Dict]:
    start = out.find("{")
    return json.loads(out[start:]) if start >= 0 else None


def _get_service(cluster_name: str) -> Optional[Dict]:
    rc, out = _run(["get", "service", _service_name(cluster_name),
                    "-o", "json"])
    return _json_from(out) if rc == 0 else None


def _node_address() -> Optional[str]:
    """A reachable node address: any node's ExternalIP first (NodePorts
    open on EVERY node, and the first-listed node — often a
    control-plane or private-pool node — may have none), else any
    InternalIP."""
    rc, out = _run(["get", "nodes", "-o", "json"])
    doc = _json_from(out) if rc == 0 else None
    if not doc or not doc.get("items"):
        return None
    internal = None
    for node in doc["items"]:
        for a in node.get("status", {}).get("addresses", []):
            if a.get("type") == "ExternalIP" and a.get("address"):
                return a["address"]
            if a.get("type") == "InternalIP" and a.get("address"):
                internal = internal or a["address"]
    return internal


def query_ports(cluster_name: str) -> Dict[int, str]:
    """{service port: endpoint} for the cluster's exposure objects.
    Endpoint shapes per mode (all usable as ``http://{endpoint}``):
    nodeport ``node_addr:node_port``, loadbalancer ``lb_addr:port``,
    ingress ``ingress_addr/skytpu/{cluster}/{port}``."""
    svc = _get_service(cluster_name)
    if svc is None:
        return {}
    ports = [int(p["port"])
             for p in svc.get("spec", {}).get("ports", [])]
    svc_type = svc.get("spec", {}).get("type", "NodePort")
    if svc_type == "LoadBalancer":
        addr = _lb_address(svc.get("status", {}))
        return ({p: f"{addr}:{p}" for p in ports} if addr else {})
    if svc_type == "ClusterIP":      # ingress mode
        rc, out = _run(["get", "ingress", _ingress_name(cluster_name),
                        "-o", "json"])
        ing = _json_from(out) if rc == 0 else None
        addr = _lb_address(ing.get("status", {})) if ing else None
        if not addr:
            return {}
        return {p: f"{addr}/skytpu/{cluster_name}/{p}" for p in ports}
    host = _node_address()
    if host is None:
        return {}
    out: Dict[int, str] = {}
    for p in svc.get("spec", {}).get("ports", []):
        node_port = p.get("nodePort")
        if node_port:
            out[int(p["port"])] = f"{host}:{node_port}"
    return out


def _lb_address(status: Dict) -> Optional[str]:
    for ing in status.get("loadBalancer", {}).get("ingress", []):
        if ing.get("ip"):
            return ing["ip"]
        if ing.get("hostname"):
            return ing["hostname"]
    return None


def port_forward_command(cluster_name: str, port: int,
                         local_port: Optional[int] = None) -> str:
    """For clusters whose nodes have no reachable address (laptops,
    private GKE): the kubectl tunnel that exposes the Service port."""
    return (f"{_kubectl()} port-forward service/"
            f"{_service_name(cluster_name)} "
            f"{local_port or port}:{port}")


def stop_instances(cluster_name: str, zone: str) -> None:
    raise exceptions.NotSupportedError(
        "kubernetes pods cannot be stopped; use down (terminate) instead")


def terminate_instances(cluster_name: str, zone: str) -> None:
    cleanup_ports(cluster_name)
    rc, out = _run(["delete", "pods", "-l", f"{LABEL}={cluster_name}",
                    "--ignore-not-found", "--wait=false"])
    if rc != 0:
        raise exceptions.ProvisionError(
            f"kubectl delete failed: {out.strip()}")


def _get_pods(cluster_name: str) -> List[Dict]:
    rc, out = _run(["get", "pods", "-l", f"{LABEL}={cluster_name}",
                    "-o", "json"])
    if rc != 0:
        raise exceptions.ProvisionError(
            f"kubectl get pods failed: {out.strip()}")
    # kubectl may append warnings after the JSON on stderr; _json_from
    # finds the JSON object in the combined stream.
    doc = _json_from(out)
    return doc["items"] if doc else []


def query_instances(cluster_name: str, zone: str) -> str:
    pods = _get_pods(cluster_name)
    if not pods:
        return "NOT_FOUND"
    phases = [p.get("status", {}).get("phase", "Unknown") for p in pods]
    if all(ph == "Running" for ph in phases):
        return "UP"
    return "PARTIAL"


def wait_instances(cluster_name: str, zone: str,
                   timeout: float = 600) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if query_instances(cluster_name, zone) == "UP":
            return
        time.sleep(2)
    raise exceptions.ProvisionError(
        f"pods of {cluster_name} not Running within {timeout}s")


def get_cluster_info(cluster_name: str, zone: str) -> ClusterInfo:
    pods = _get_pods(cluster_name)
    hosts = []
    for pod in sorted(pods, key=lambda p: (
            int(p["metadata"]["labels"].get(NODE_LABEL, 0)),
            int(p["metadata"]["labels"].get(WORKER_LABEL, 0)))):
        labels = pod["metadata"]["labels"]
        hosts.append(HostInfo(
            host_id=len(hosts),
            node_id=int(labels.get(NODE_LABEL, 0)),
            worker_id=int(labels.get(WORKER_LABEL, 0)),
            internal_ip=pod.get("status", {}).get("podIP", ""),
            external_ip=None,
            workspace=None,
            runner_kind="k8s",
        ))
    info = ClusterInfo(cluster_name=cluster_name, provider="kubernetes",
                       zone=zone, hosts=hosts)
    info.metadata["pod_names"] = [p["metadata"]["name"] for p in pods]
    # Port endpoints are NOT resolved here: get_cluster_info runs on
    # every exec/setup path and most callers don't need them — the
    # dispatcher-level provision.query_ports serves the consumers that
    # do (serve's replica URLs).
    return info


def get_command_runners(info: ClusterInfo) -> List[CommandRunner]:
    names = info.metadata.get("pod_names") or [
        f"{info.cluster_name}-{h.node_id}-{h.worker_id}"
        for h in info.hosts]
    return [KubernetesRunner(pod, host_id=h.host_id, ip=h.internal_ip)
            for pod, h in zip(sorted(names), info.hosts)]


class KubernetesRunner(CommandRunner):
    """kubectl exec / cp against one pod."""

    def __init__(self, pod_name: str, host_id: int = 0, ip: str = ""):
        super().__init__(host_id, ip)
        self.pod_name = pod_name

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        env_prefix = "".join(
            f"export {k}={shlex.quote(str(v))}; "
            for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)}; " if cwd else ""
        full = f"{env_prefix}{cd}{cmd}"
        exec_flags = ["-i"] if stdin is not None else []
        proc = subprocess.run(
            [_kubectl(), "exec", *exec_flags, self.pod_name, "--",
             "/bin/sh", "-c", full],
            capture_output=True, text=True, timeout=timeout, input=stdin)
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                f.write((proc.stdout or "").encode())
                f.write((proc.stderr or "").encode())
            return proc.returncode, "", ""
        return proc.returncode, proc.stdout, proc.stderr

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        env_prefix = "".join(
            f"export {k}={shlex.quote(str(v))}; "
            for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)}; " if cwd else ""
        wrapped = (f"nohup sh -c {shlex.quote(env_prefix + cd + cmd)} "
                   f">> {shlex.quote(log_path)} 2>&1 & echo $!")
        rc, out, err = self.run(wrapped)
        if rc != 0:
            raise exceptions.ProvisionError(
                f"detached exec failed on {self.pod_name}: {err}")
        return int(out.strip().splitlines()[-1])

    def rsync(self, src, dst, up=True, excludes=None):
        # tar-over-exec instead of `kubectl cp`: cp cannot expand `~` or
        # $HOME on the pod side, and the framework push targets
        # ~/.skypilot_tpu/pkg. `dst`/`src` may be ~-relative; the pod's
        # shell resolves them.
        def pod_path(p):
            return ('"$HOME"' + shlex.quote(p[1:])) if p.startswith("~") \
                else shlex.quote(p)
        if up:
            src = os.path.expanduser(src)
            if os.path.isdir(src):
                tar = subprocess.run(
                    ["tar", "-C", src, "-cf", "-", "."],
                    capture_output=True)
                unpack = (f"mkdir -p {pod_path(dst)} && "
                          f"tar -C {pod_path(dst)} -xf -")
            else:
                tar = subprocess.run(
                    ["tar", "-C", os.path.dirname(src) or ".", "-cf", "-",
                     os.path.basename(src)],
                    capture_output=True)
                d = os.path.dirname(dst) or "."
                unpack = (f"mkdir -p {pod_path(d)} && "
                          f"tar -C {pod_path(d)} -xf - && "
                          f"mv {pod_path(d)}/{shlex.quote(os.path.basename(src))} "
                          f"{pod_path(dst)}")
            if tar.returncode != 0:
                raise RuntimeError(f"tar {src} failed: {tar.stderr!r}")
            proc = subprocess.run(
                [_kubectl(), "exec", "-i", self.pod_name, "--", "/bin/sh",
                 "-c", unpack], input=tar.stdout, capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pod unpack to {dst} failed: {proc.stderr!r}")
        else:
            proc = subprocess.run(
                [_kubectl(), "exec", self.pod_name, "--", "/bin/sh", "-c",
                 f"tar -C $(dirname {pod_path(src)}) -cf - "
                 f"$(basename {pod_path(src)})"],
                capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(f"pod pack {src} failed: {proc.stderr!r}")
            os.makedirs(dst if os.path.isdir(dst) else
                        os.path.dirname(dst) or ".", exist_ok=True)
            unpack = subprocess.run(
                ["tar", "-C", os.path.dirname(dst) or ".", "-xf", "-"],
                input=proc.stdout, capture_output=True)
            if unpack.returncode != 0:
                raise RuntimeError(
                    f"local unpack {src} -> {dst} failed: "
                    f"{unpack.stderr!r}")

    def read_file(self, path: str) -> Optional[str]:
        # `~` must expand pod-side; shlex.quote would make it literal.
        quoted = ('"$HOME"' + shlex.quote(path[1:])
                  if path.startswith("~") else shlex.quote(path))
        rc, out, _ = self.run(f"cat {quoted}")
        return out if rc == 0 else None

    def kill(self, pid: int) -> None:
        self.run(f"kill -TERM -- -{pid} 2>/dev/null || "
                 f"kill -TERM {pid} 2>/dev/null || true")
    # framework_invocation: base CommandRunner default (remote contract).
