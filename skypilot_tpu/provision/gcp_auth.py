"""GCP credential discovery (no network).

Reference parity: sky/check.py + sky/clouds/gcp.py check_credentials —
validates local credentials and caches enabled clouds. Token acquisition
for REST calls lives here so provision/gcp.py stays transport-only.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from typing import Optional, Tuple

ADC_PATH = "~/.config/gcloud/application_default_credentials.json"


def check_credentials() -> Tuple[bool, str]:
    """(enabled, reason). Enabled iff ADC or gcloud auth is present."""
    if os.environ.get("GOOGLE_APPLICATION_CREDENTIALS"):
        p = os.environ["GOOGLE_APPLICATION_CREDENTIALS"]
        if os.path.exists(p):
            return True, "GOOGLE_APPLICATION_CREDENTIALS"
        return False, f"GOOGLE_APPLICATION_CREDENTIALS points to missing {p}"
    if os.path.exists(os.path.expanduser(ADC_PATH)):
        return True, "application-default credentials"
    if shutil.which("gcloud"):
        try:
            out = subprocess.run(
                ["gcloud", "auth", "list", "--format=json"],
                capture_output=True, text=True, timeout=10)
            if out.returncode == 0 and json.loads(out.stdout or "[]"):
                return True, "gcloud auth"
        except Exception:  # noqa: BLE001
            pass
    return False, "no application-default credentials or gcloud auth"


def get_project() -> Optional[str]:
    for env in ("GOOGLE_CLOUD_PROJECT", "GCP_PROJECT", "CLOUDSDK_CORE_PROJECT"):
        if os.environ.get(env):
            return os.environ[env]
    adc = os.path.expanduser(ADC_PATH)
    if os.path.exists(adc):
        with open(adc) as f:
            data = json.load(f)
        if data.get("quota_project_id"):
            return data["quota_project_id"]
    if shutil.which("gcloud"):
        try:
            out = subprocess.run(
                ["gcloud", "config", "get-value", "project"],
                capture_output=True, text=True, timeout=10)
            proj = out.stdout.strip()
            if out.returncode == 0 and proj and proj != "(unset)":
                return proj
        except Exception:  # noqa: BLE001
            pass
    return None


def get_access_token() -> str:
    """Access token for REST calls, via gcloud (no SDK dependency)."""
    if shutil.which("gcloud"):
        out = subprocess.run(["gcloud", "auth", "print-access-token"],
                             capture_output=True, text=True, timeout=30)
        if out.returncode == 0:
            return out.stdout.strip()
    raise RuntimeError(
        "cannot obtain GCP access token: gcloud not available or not "
        "authenticated")
