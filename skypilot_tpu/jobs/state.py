"""Managed-jobs state DB.

Reference parity: sky/jobs/state.py (sqlite spot_jobs DB,
ManagedJobStatus :202-235).
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import paths


class ManagedJobStatus(enum.Enum):
    PENDING = "PENDING"
    SUBMITTED = "SUBMITTED"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    RECOVERING = "RECOVERING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    FAILED_PRECHECKS = "FAILED_PRECHECKS"
    FAILED_NO_RESOURCE = "FAILED_NO_RESOURCE"
    FAILED_CONTROLLER = "FAILED_CONTROLLER"
    CANCELLING = "CANCELLING"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                        ManagedJobStatus.FAILED_PRECHECKS,
                        ManagedJobStatus.FAILED_NO_RESOURCE,
                        ManagedJobStatus.FAILED_CONTROLLER,
                        ManagedJobStatus.CANCELLED)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS managed_jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    task_config TEXT,
    status TEXT,
    submitted_at REAL,
    started_at REAL,
    ended_at REAL,
    cluster_name TEXT,
    recovery_count INTEGER DEFAULT 0,
    recovery_strategy TEXT,
    controller_pid INTEGER,
    last_error TEXT
);
"""


def _db_path() -> str:
    return os.path.join(paths.home(), "managed_jobs.db")


@contextlib.contextmanager
def _db():
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.executescript(_SCHEMA)
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def add(name: Optional[str], task_config: Dict[str, Any],
        recovery_strategy: str) -> int:
    with _db() as c:
        cur = c.execute(
            "INSERT INTO managed_jobs (name, task_config, status,"
            " submitted_at, recovery_strategy) VALUES (?,?,?,?,?)",
            (name, json.dumps(task_config),
             ManagedJobStatus.PENDING.value, time.time(),
             recovery_strategy))
        return int(cur.lastrowid)


def set_status(job_id: int, status: ManagedJobStatus,
               error: Optional[str] = None) -> None:
    with _db() as c:
        if status == ManagedJobStatus.RUNNING:
            c.execute("UPDATE managed_jobs SET status=?, started_at="
                      "COALESCE(started_at, ?) WHERE job_id=?",
                      (status.value, time.time(), job_id))
        elif status.is_terminal():
            c.execute("UPDATE managed_jobs SET status=?, ended_at=?,"
                      " last_error=COALESCE(?, last_error) WHERE job_id=?",
                      (status.value, time.time(), error, job_id))
        else:
            c.execute("UPDATE managed_jobs SET status=?,"
                      " last_error=COALESCE(?, last_error) WHERE job_id=?",
                      (status.value, error, job_id))


def set_cluster(job_id: int, cluster_name: str) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET cluster_name=? WHERE job_id=?",
                  (cluster_name, job_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET controller_pid=? WHERE job_id=?",
                  (pid, job_id))


def bump_recovery(job_id: int) -> int:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET recovery_count=recovery_count+1"
                  " WHERE job_id=?", (job_id,))
        return int(c.execute("SELECT recovery_count FROM managed_jobs"
                             " WHERE job_id=?", (job_id,)).fetchone()[0])


def get(job_id: int) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(_SELECT + " WHERE job_id=?", (job_id,)).fetchone()
    return _rec(row) if row else None


def list_jobs() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(_SELECT + " ORDER BY job_id DESC").fetchall()
    return [_rec(r) for r in rows]


def count_alive() -> int:
    with _db() as c:
        return int(c.execute(
            "SELECT COUNT(*) FROM managed_jobs WHERE status IN (?,?,?,?,?)",
            (ManagedJobStatus.SUBMITTED.value,
             ManagedJobStatus.STARTING.value,
             ManagedJobStatus.RUNNING.value,
             ManagedJobStatus.RECOVERING.value,
             ManagedJobStatus.CANCELLING.value)).fetchone()[0])


_SELECT = ("SELECT job_id, name, task_config, status, submitted_at,"
           " started_at, ended_at, cluster_name, recovery_count,"
           " recovery_strategy, controller_pid, last_error FROM managed_jobs")


def _rec(row) -> Dict[str, Any]:
    (jid, name, cfg, status, sub, start, end, cluster, rec_n, strat, pid,
     err) = row
    return {"job_id": jid, "name": name,
            "task_config": json.loads(cfg),
            "status": ManagedJobStatus(status),
            "submitted_at": sub, "started_at": start, "ended_at": end,
            "cluster_name": cluster, "recovery_count": rec_n,
            "recovery_strategy": strat, "controller_pid": pid,
            "last_error": err}
