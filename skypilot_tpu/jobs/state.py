"""Managed-jobs state DB.

Reference parity: sky/jobs/state.py (sqlite spot_jobs DB,
ManagedJobStatus :202-235).
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.utils import db, paths

MANAGED_TERMINAL = obs_metrics.counter(
    "skytpu_managed_jobs_terminal_total",
    "Managed jobs reaching a terminal status in this process, by "
    "status (first-wins: only the write that applied counts)",
    labelnames=("status",))


class ManagedJobStatus(enum.Enum):
    PENDING = "PENDING"
    SUBMITTED = "SUBMITTED"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    RECOVERING = "RECOVERING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    FAILED_PRECHECKS = "FAILED_PRECHECKS"
    FAILED_NO_RESOURCE = "FAILED_NO_RESOURCE"
    FAILED_CONTROLLER = "FAILED_CONTROLLER"
    # The recovery budget (jobs.max_recovery_attempts) ran out: the job
    # kept being preempted and the controller gave up — distinct from
    # FAILED (the task itself failed on a healthy cluster).
    FAILED_RECOVERY = "FAILED_RECOVERY"
    CANCELLING = "CANCELLING"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                        ManagedJobStatus.FAILED_PRECHECKS,
                        ManagedJobStatus.FAILED_NO_RESOURCE,
                        ManagedJobStatus.FAILED_CONTROLLER,
                        ManagedJobStatus.FAILED_RECOVERY,
                        ManagedJobStatus.CANCELLED)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS managed_jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    task_config TEXT,
    status TEXT,
    submitted_at REAL,
    started_at REAL,
    ended_at REAL,
    cluster_name TEXT,
    recovery_count INTEGER DEFAULT 0,
    recovery_strategy TEXT,
    controller_pid INTEGER,
    last_error TEXT,
    launch_started_at REAL,
    launch_ended_at REAL
);
"""

MAX_JOB_LIMIT = 2000  # reference: sky/jobs/scheduler.py:70
LAUNCHES_PER_CPU = 4  # reference: sky/jobs/scheduler.py:72


def alive_limit() -> int:
    try:
        mem_bytes = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        by_mem = int(mem_bytes / (350 * 1024 * 1024))
    except (ValueError, OSError):
        by_mem = MAX_JOB_LIMIT
    return min(by_mem, MAX_JOB_LIMIT)


def launch_limit() -> int:
    override = os.environ.get("SKYTPU_JOBS_MAX_LAUNCHES")
    if override:
        return int(override)
    return LAUNCHES_PER_CPU * (os.cpu_count() or 1)


def _db_path() -> str:
    return os.path.join(paths.home(), "managed_jobs.db")


# Columns added after the first release: CREATE TABLE IF NOT EXISTS is
# a no-op on existing DBs, so they need explicit ALTERs (idempotent —
# duplicate-column errors are expected and ignored).
_MIGRATIONS = (
    "ALTER TABLE managed_jobs ADD COLUMN launch_started_at REAL",
    "ALTER TABLE managed_jobs ADD COLUMN launch_ended_at REAL",
    # Pipeline position (multi-task managed jobs run sequentially).
    "ALTER TABLE managed_jobs ADD COLUMN current_task INTEGER DEFAULT 0",
)


@contextlib.contextmanager
def _db():
    conn = db.connect(_db_path(), timeout=10)
    conn.executescript(_SCHEMA)
    for mig in _MIGRATIONS:
        try:
            conn.execute(mig)
        except sqlite3.OperationalError:
            pass  # column already exists
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def add(name: Optional[str], task_config: Dict[str, Any],
        recovery_strategy: str) -> int:
    with _db() as c:
        cur = c.execute(
            "INSERT INTO managed_jobs (name, task_config, status,"
            " submitted_at, recovery_strategy) VALUES (?,?,?,?,?)",
            (name, json.dumps(task_config),
             ManagedJobStatus.PENDING.value, time.time(),
             recovery_strategy))
        return int(cur.lastrowid)


def set_status(job_id: int, status: ManagedJobStatus,
               error: Optional[str] = None) -> bool:
    """Write a status; forward (non-terminal) writes are guarded.

    Provisioning/recovery takes minutes; a ``jobs cancel`` that lands
    mid-flight sets CANCELLING, and an unconditional forward write
    (STARTING/RUNNING/RECOVERING) afterwards would silently resurrect
    the job — it would then run to completion despite a successful
    cancel reply. So every forward write applies only when the job is
    not already CANCELLING/terminal, and CANCELLING itself never
    overwrites a terminal state. Terminal writes are first-wins: a late
    SUCCEEDED/FAILED from _monitor must not overwrite a CANCELLED that
    the cancel path already recorded (CANCELLED still applies over
    CANCELLING, which is non-terminal).
    Returns False when the write did not apply — the caller should take
    the cancellation path.
    """
    terminal = [s.value for s in ManagedJobStatus if s.is_terminal()]
    if status.is_terminal():
        blocked = terminal
    elif status == ManagedJobStatus.CANCELLING:
        blocked = terminal
    else:
        blocked = [ManagedJobStatus.CANCELLING.value] + terminal
    guard = (f" AND status NOT IN ({','.join('?' * len(blocked))})"
             if blocked else "")
    with _db() as c:
        if status == ManagedJobStatus.RUNNING:
            cur = c.execute(
                "UPDATE managed_jobs SET status=?, started_at="
                f"COALESCE(started_at, ?) WHERE job_id=?{guard}",
                (status.value, time.time(), job_id, *blocked))
        elif status.is_terminal():
            cur = c.execute(
                "UPDATE managed_jobs SET status=?, ended_at=?,"
                " last_error=COALESCE(?, last_error)"
                f" WHERE job_id=?{guard}",
                (status.value, time.time(), error, job_id, *blocked))
        else:
            cur = c.execute(
                "UPDATE managed_jobs SET status=?, last_error="
                f"COALESCE(?, last_error) WHERE job_id=?{guard}",
                (status.value, error, job_id, *blocked))
        applied = cur.rowcount > 0
    if applied and status.is_terminal():
        MANAGED_TERMINAL.labels(status=status.value).inc()
    return applied


def transition_to_running(job_id: int) -> bool:
    """Conditionally move a job to RUNNING after a launch/recover (see
    the forward-write guard in set_status)."""
    return set_status(job_id, ManagedJobStatus.RUNNING)


def set_cluster(job_id: int, cluster_name: str) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET cluster_name=? WHERE job_id=?",
                  (cluster_name, job_id))


def set_current_task(job_id: int, task_index: int) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET current_task = ? "
                  "WHERE job_id = ?", (task_index, job_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET controller_pid=? WHERE job_id=?",
                  (pid, job_id))


def bump_recovery(job_id: int) -> int:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET recovery_count=recovery_count+1"
                  " WHERE job_id=?", (job_id,))
        return int(c.execute("SELECT recovery_count FROM managed_jobs"
                             " WHERE job_id=?", (job_id,)).fetchone()[0])


def get(job_id: int) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(_SELECT + " WHERE job_id=?", (job_id,)).fetchone()
    return _rec(row) if row else None


def list_jobs() -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(_SELECT + " ORDER BY job_id DESC").fetchall()
    return [_rec(r) for r in rows]


def reap_dead_controllers() -> int:
    """Mark non-terminal jobs whose controller PROCESS is gone as
    FAILED_CONTROLLER — a controller that dies hard (crash at import,
    OOM-kill) otherwise leaves its job non-terminal FOREVER (the
    reference reconciles the same way in its scheduler sweep). Runs on
    the controller head (same host as the PIDs), called from the
    jobs_list/jobs_get RPC so stale rows self-heal on observation.
    Returns the number of jobs reaped."""
    non_terminal = [s.value for s in ManagedJobStatus
                    if not s.is_terminal()]
    with _db() as c:
        rows = c.execute(
            "SELECT job_id, controller_pid, status FROM managed_jobs"
            f" WHERE status IN ({','.join('?' * len(non_terminal))})"
            " AND controller_pid IS NOT NULL",
            non_terminal).fetchall()
    reaped = 0
    for job_id, pid, _status in rows:
        try:
            os.kill(pid, 0)        # signal 0 = liveness probe
        except ProcessLookupError:
            if set_status(job_id, ManagedJobStatus.FAILED_CONTROLLER,
                          error="controller process died"):
                reaped += 1
        except PermissionError:
            pass                   # alive, different uid
    return reaped


def acquire_launch_slot(job_id: int, poll: float = 0.2,
                        timeout: float = 3600) -> None:
    """Block until a provisioning slot is free, then claim it.

    Bounds concurrent cluster launches across all managed jobs
    (reference: sky/jobs/scheduler.py:72 — launching <= 4x CPUs). The
    claim is atomic: count-and-set under BEGIN IMMEDIATE.
    """
    limit = launch_limit()
    deadline = time.time() + timeout
    while time.time() < deadline:
        with _db() as c:
            c.execute("BEGIN IMMEDIATE")
            # Reap inside the same IMMEDIATE transaction as the
            # count-and-claim, so a concurrent acquire can't count a
            # corpse we're about to free (or vice versa).
            _reap_dead_launch_slots(c)
            n = int(c.execute(
                "SELECT COUNT(*) FROM managed_jobs WHERE"
                " launch_started_at IS NOT NULL AND"
                " launch_ended_at IS NULL").fetchone()[0])
            if n < limit:
                c.execute(
                    "UPDATE managed_jobs SET launch_started_at=?,"
                    " launch_ended_at=NULL WHERE job_id=?",
                    (time.time(), job_id))
                return
        time.sleep(poll)
    raise TimeoutError(
        f"no launch slot for managed job {job_id} within {timeout}s")


# A slot whose controller_pid is still NULL may simply be newly spawned:
# jobs_submit records the pid only after Popen, so the controller can
# claim its slot before set_controller_pid commits. Give such rows a
# grace window before treating NULL pid as a corpse.
_NULL_PID_GRACE_SECONDS = 30.0


def _reap_dead_launch_slots(c) -> None:
    """Free slots whose controller process died between acquire and
    release (SIGKILL/OOM): the count must not include corpses, or dead
    slots eventually starve every new launch. Runs on the controller
    host, so pid liveness is a local check. Operates on the caller's
    open transaction."""
    rows = c.execute(
        "SELECT job_id, controller_pid, launch_started_at FROM"
        " managed_jobs WHERE launch_started_at IS NOT NULL AND"
        " launch_ended_at IS NULL").fetchall()
    for job_id, pid, started in rows:
        if pid is None:
            age = time.time() - (started or 0)
            dead = age > _NULL_PID_GRACE_SECONDS
        else:
            try:
                os.kill(pid, 0)
                dead = False
            except OSError:
                dead = True
        if dead:
            c.execute(
                "UPDATE managed_jobs SET launch_ended_at=?"
                " WHERE job_id=? AND launch_ended_at IS NULL",
                (time.time(), job_id))


def release_launch_slot(job_id: int) -> None:
    with _db() as c:
        c.execute("UPDATE managed_jobs SET launch_ended_at=?"
                  " WHERE job_id=? AND launch_ended_at IS NULL",
                  (time.time(), job_id))


def launch_window(job_id: int):
    with _db() as c:
        row = c.execute(
            "SELECT launch_started_at, launch_ended_at FROM managed_jobs"
            " WHERE job_id=?", (job_id,)).fetchone()
    return tuple(row) if row else (None, None)


def count_alive() -> int:
    with _db() as c:
        return int(c.execute(
            "SELECT COUNT(*) FROM managed_jobs WHERE status IN (?,?,?,?,?)",
            (ManagedJobStatus.SUBMITTED.value,
             ManagedJobStatus.STARTING.value,
             ManagedJobStatus.RUNNING.value,
             ManagedJobStatus.RECOVERING.value,
             ManagedJobStatus.CANCELLING.value)).fetchone()[0])


_SELECT = ("SELECT job_id, name, task_config, status, submitted_at,"
           " started_at, ended_at, cluster_name, recovery_count,"
           " recovery_strategy, controller_pid, last_error,"
           " launch_started_at, launch_ended_at, current_task"
           " FROM managed_jobs")


def _rec(row) -> Dict[str, Any]:
    (jid, name, cfg, status, sub, start, end, cluster, rec_n, strat, pid,
     err, launch_start, launch_end, cur_task) = row
    cfg = json.loads(cfg)
    num_tasks = len(cfg["pipeline"]) if "pipeline" in cfg else 1
    return {"job_id": jid, "name": name,
            "task_config": cfg,
            "status": ManagedJobStatus(status),
            "submitted_at": sub, "started_at": start, "ended_at": end,
            "cluster_name": cluster, "recovery_count": rec_n,
            "recovery_strategy": strat, "controller_pid": pid,
            "launch_started_at": launch_start,
            "launch_ended_at": launch_end,
            "current_task": cur_task or 0, "num_tasks": num_tasks,
            # Single display form for "which pipeline step" — the CLI
            # queue and the dashboard both render this field.
            "task": (f"{(cur_task or 0) + 1}/{num_tasks}"
                     if num_tasks > 1 else "-"),
            "last_error": err}
