"""Managed-job controller: one monitor process per job.

Reference parity: sky/jobs/controller.py (JobsController:53,
_run_one_task:120 — launch via strategy, poll, detect preemption,
recover, cleanup). Runs as a detached process per job ON the jobs
controller cluster head (spawned by the rpc ``jobs_submit`` method,
with the head's own state home and provider env), recursively calling
the framework's launch path to manage the per-job cluster — the
reference's controller-as-task recursion (jobs-controller.yaml.j2).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from skypilot_tpu import exceptions, provision
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.jobs import recovery_strategy, state
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task

POLL_SECONDS = float(os.environ.get("SKYTPU_JOBS_POLL", "2"))


class JobsController:
    def __init__(self, managed_job_id: int):
        self.job_id = managed_job_id
        rec = state.get(managed_job_id)
        if rec is None:
            raise exceptions.ManagedJobError(f"no managed job {managed_job_id}")
        self.task = Task.from_yaml_config(rec["task_config"])
        self.cluster_name = f"sky-jobs-{managed_job_id}"
        self.strategy = recovery_strategy.StrategyExecutor.make(
            rec["recovery_strategy"], self.task, self.cluster_name)
        self.backend = TpuVmBackend()

    def _log(self, msg: str) -> None:
        print(f"[managed job {self.job_id}] {msg}", flush=True)

    def run(self) -> None:
        try:
            self._log(f"starting; cluster {self.cluster_name}, "
                      f"strategy {type(self.strategy).__name__}")
            if not state.set_status(self.job_id,
                                    state.ManagedJobStatus.STARTING):
                # Cancel landed between submit and controller startup.
                self._log("cancelled before launch")
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return
            state.set_cluster(self.job_id, self.cluster_name)
            # Launching-parallelism gate (reference: sky/jobs/
            # scheduler.py:72 — at most 4 concurrent launches per CPU).
            state.acquire_launch_slot(self.job_id)
            try:
                job_id, handle = self.strategy.launch()
            finally:
                state.release_launch_slot(self.job_id)
            self._log(f"cluster up; job {job_id} running")
            if not state.transition_to_running(self.job_id):
                # A cancel landed while we were provisioning — honor it
                # instead of resurrecting the job (the cluster is torn
                # down by _cleanup in the finally block).
                self._log("cancelled during launch; tearing down")
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return
            # _monitor returns the FINAL (job_id, handle) — recovery may
            # have moved the job to a fresh cluster in another zone.
            job_id, handle = self._monitor(job_id, handle)
            self._snapshot_output(job_id, handle)
            final = state.get(self.job_id)
            if final:
                self._log(f"finished: {final['status'].value}")
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
        except Exception as e:  # noqa: BLE001 — controller records failure
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_CONTROLLER,
                             error=f"{type(e).__name__}: {e}")
        finally:
            self._cleanup()

    def _snapshot_output(self, job_id: int, handle: ClusterHandle) -> None:
        """Persist the job's output logs before the per-job cluster is
        torn down, so `jobs logs` works after completion (reference:
        the controller's log download at sky/jobs/controller.py)."""
        from skypilot_tpu.utils import paths
        out_path = os.path.join(paths.logs_dir(),
                                f"jobs-output-{self.job_id}.log")
        try:
            with open(out_path, "w") as f:
                self.backend.tail_logs(handle, job_id, follow=False, out=f)
        except exceptions.SkyTpuError as e:
            self._log(f"output snapshot failed: {e}")

    # -- monitor loop ------------------------------------------------------
    def _monitor(self, job_id: int, handle: ClusterHandle):
        """Returns the final (job_id, handle) — possibly a recovered
        cluster, which is the one whose logs are worth snapshotting."""
        while True:
            time.sleep(POLL_SECONDS)
            rec = state.get(self.job_id)
            if rec["status"] == state.ManagedJobStatus.CANCELLING:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return job_id, handle
            js = self._cluster_job_status(handle, job_id)
            if js == JobStatus.SUCCEEDED:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.SUCCEEDED)
                return job_id, handle
            if js == JobStatus.CANCELLED:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return job_id, handle
            if js is None or js in (JobStatus.FAILED,
                                    JobStatus.FAILED_SETUP):
                # Cluster gone (slice preempted) or job died with the
                # cluster unhealthy -> recover; genuine user failure on a
                # healthy cluster -> FAILED.
                if js == JobStatus.FAILED and self._cluster_healthy(handle):
                    state.set_status(self.job_id,
                                     state.ManagedJobStatus.FAILED,
                                     error="task failed on healthy cluster")
                    return job_id, handle
                recovered = self._recover()
                if recovered is None:
                    return job_id, handle
                job_id, handle = recovered

    def _recover(self):
        """Recover the cluster+job; returns (job_id, handle) or None if
        the managed job reached a terminal state instead."""
        n = state.bump_recovery(self.job_id)
        if n > recovery_strategy.MAX_RECOVERY_ATTEMPTS:
            state.set_status(self.job_id, state.ManagedJobStatus.FAILED,
                             error="max recovery attempts exceeded")
            return None
        if not state.set_status(self.job_id,
                                state.ManagedJobStatus.RECOVERING):
            # Cancel landed while _monitor was probing — don't relaunch.
            self._log("cancelled during recovery; tearing down")
            state.set_status(self.job_id, state.ManagedJobStatus.CANCELLED)
            return None
        try:
            state.acquire_launch_slot(self.job_id)
            try:
                job_id, handle = self.strategy.recover()
            finally:
                state.release_launch_slot(self.job_id)
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
            return None
        if not state.transition_to_running(self.job_id):
            self._log("cancelled during recovery; tearing down")
            state.set_status(self.job_id, state.ManagedJobStatus.CANCELLED)
            return None
        return job_id, handle

    # -- probes ------------------------------------------------------------
    def _cluster_healthy(self, handle: ClusterHandle) -> bool:
        try:
            return provision.query_instances(
                handle.provider, handle.cluster_name, handle.zone) == "UP"
        except exceptions.SkyTpuError:
            return False

    def _cluster_job_status(self, handle: ClusterHandle,
                            job_id: int) -> Optional[JobStatus]:
        if not self._cluster_healthy(handle):
            return None
        try:
            for j in self.backend.queue(handle):
                if j["job_id"] == job_id:
                    return j["status"]
        except exceptions.SkyTpuError:
            return None
        return None

    def _cleanup(self) -> None:
        rec = cluster_state.get_cluster(self.cluster_name)
        if rec is not None:
            try:
                self.backend.teardown(ClusterHandle(rec["handle"]))
            except exceptions.SkyTpuError:
                cluster_state.remove_cluster(self.cluster_name)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job-id", type=int, required=True)
    args = ap.parse_args()
    try:
        controller = JobsController(args.job_id)
    except Exception as e:  # noqa: BLE001 — init errors must be recorded
        state.set_status(args.job_id,
                         state.ManagedJobStatus.FAILED_CONTROLLER,
                         error=f"{type(e).__name__}: {e}")
        raise
    controller.run()


if __name__ == "__main__":
    main()
