"""Managed-job controller: one monitor process per job.

Reference parity: sky/jobs/controller.py (JobsController:53,
_run_one_task:120 — launch via strategy, poll, detect preemption,
recover, cleanup). Runs as a detached process per job ON the jobs
controller cluster head (spawned by the rpc ``jobs_submit`` method,
with the head's own state home and provider env), recursively calling
the framework's launch path to manage the per-job cluster — the
reference's controller-as-task recursion (jobs-controller.yaml.j2).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from skypilot_tpu import exceptions, provision
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.jobs import recovery_strategy, state
from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task

POLL_SECONDS = float(os.environ.get("SKYTPU_JOBS_POLL", "2"))

PREEMPTIONS = obs_metrics.counter(
    "skytpu_jobs_preemptions_total",
    "Managed-job cluster losses detected by the monitor (slice "
    "preempted or job died with the cluster unhealthy)")
RECOVERY_ATTEMPTS = obs_metrics.counter(
    "skytpu_jobs_recovery_attempts_total",
    "Managed-job recovery attempts, by outcome",
    labelnames=("outcome",))


class JobsController:
    def __init__(self, managed_job_id: int):
        self.job_id = managed_job_id
        rec = state.get(managed_job_id)
        if rec is None:
            raise exceptions.ManagedJobError(f"no managed job {managed_job_id}")
        cfg = rec["task_config"]
        # A pipeline ({"pipeline": [cfg, ...]}) runs its tasks
        # SEQUENTIALLY under one managed job, each on its own cluster
        # with its own recovery (reference: sky/jobs/controller.py:68
        # iterates dag.tasks; task i+1 starts only after i SUCCEEDED).
        configs = (cfg["pipeline"] if "pipeline" in cfg else [cfg])
        self.tasks = [Task.from_yaml_config(c) for c in configs]
        self.default_strategy = rec["recovery_strategy"]
        self.backend = TpuVmBackend()
        # Current-task slots, (re)bound by _bind_task per pipeline step.
        self.task = None
        self.cluster_name = None
        self.strategy = None

    def _bind_task(self, index: int) -> None:
        self.task = self.tasks[index]
        suffix = f"-t{index}" if len(self.tasks) > 1 else ""
        self.cluster_name = f"sky-jobs-{self.job_id}{suffix}"
        strat = self.default_strategy
        for r in self.task.resources:       # per-task override
            strat = r.job_recovery or strat
        self.strategy = recovery_strategy.StrategyExecutor.make(
            strat, self.task, self.cluster_name)
        # Recovery budget is PER TASK: step A burning its allowance on
        # preemptions must not strand step B with zero attempts (the DB
        # recovery_count stays cumulative for display).
        self.task_recoveries = 0
        state.set_current_task(self.job_id, index)
        state.set_cluster(self.job_id, self.cluster_name)

    def _log(self, msg: str) -> None:
        print(f"[managed job {self.job_id}] {msg}", flush=True)

    def run(self) -> None:
        try:
            if not state.set_status(self.job_id,
                                    state.ManagedJobStatus.STARTING):
                # Cancel landed between submit and controller startup.
                self._log("cancelled before launch")
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return
            for i in range(len(self.tasks)):
                self._bind_task(i)
                if not self._run_one_task(i):
                    return          # terminal status already recorded
            final = state.get(self.job_id)
            if final:
                self._log(f"finished: {final['status'].value}")
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
        except Exception as e:  # noqa: BLE001 — controller records failure
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_CONTROLLER,
                             error=f"{type(e).__name__}: {e}")
        finally:
            self._cleanup()

    def _run_one_task(self, index: int) -> bool:
        """One pipeline step: launch, monitor to completion, snapshot
        logs, tear the step's cluster down. True = task succeeded and
        the pipeline may continue; False = a terminal status (FAILED /
        CANCELLED / ...) was recorded (reference:
        sky/jobs/controller.py:119 _run_one_task)."""
        n = len(self.tasks)
        step = f"task {index + 1}/{n}: " if n > 1 else ""
        # A cancel that landed between steps (inter-step teardown takes
        # minutes on real clusters) must be honored BEFORE the next
        # slice is provisioned and billed — the pre-launch analog of
        # the STARTING guard that protects task 0.
        rec = state.get(self.job_id)
        if rec and rec["status"] == state.ManagedJobStatus.CANCELLING:
            self._log(f"{step}cancelled before launch")
            state.set_status(self.job_id,
                             state.ManagedJobStatus.CANCELLED)
            return False
        self._log(f"{step}starting; cluster {self.cluster_name}, "
                  f"strategy {type(self.strategy).__name__}")
        # Launching-parallelism gate (reference: sky/jobs/
        # scheduler.py:72 — at most 4 concurrent launches per CPU).
        state.acquire_launch_slot(self.job_id)
        try:
            job_id, handle = self.strategy.launch()
        finally:
            state.release_launch_slot(self.job_id)
        self._log(f"{step}cluster up; job {job_id} running")
        if not state.transition_to_running(self.job_id):
            # A cancel landed while we were provisioning — honor it
            # instead of resurrecting the job (the cluster is torn
            # down by _cleanup in the finally block).
            self._log("cancelled during launch; tearing down")
            state.set_status(self.job_id,
                             state.ManagedJobStatus.CANCELLED)
            return False
        # _monitor returns the FINAL (job_id, handle) — recovery may
        # have moved the job to a fresh cluster in another zone.
        ok, job_id, handle = self._monitor(job_id, handle)
        if ok and index == n - 1:
            # Record SUCCEEDED at DETECTION time — the log snapshot
            # below can take minutes on real clusters, and a cancel
            # acknowledged in that window must not be silently
            # overwritten by a late terminal write.
            state.set_status(self.job_id,
                             state.ManagedJobStatus.SUCCEEDED)
        self._snapshot_output(job_id, handle, task_index=index)
        if ok and index < n - 1:
            # Inter-step teardown: the next task gets its own cluster;
            # this one must not keep billing under it.
            self._cleanup()
        return ok

    def _snapshot_output(self, job_id: int, handle: ClusterHandle,
                         task_index: int = 0) -> None:
        """Persist the job's output logs before the per-job cluster is
        torn down, so `jobs logs` works after completion (reference:
        the controller's log download at sky/jobs/controller.py).
        Pipeline steps append to one file, separated by headers."""
        from skypilot_tpu.utils import paths
        out_path = os.path.join(paths.logs_dir(),
                                f"jobs-output-{self.job_id}.log")
        mode = "w" if task_index == 0 else "a"
        try:
            with open(out_path, mode) as f:
                if len(self.tasks) > 1:
                    f.write(f"===== task {task_index + 1}/"
                            f"{len(self.tasks)}"
                            f" ({self.task.name or 'unnamed'}) =====\n")
                self.backend.tail_logs(handle, job_id, follow=False, out=f)
        except exceptions.SkyTpuError as e:
            self._log(f"output snapshot failed: {e}")

    # -- monitor loop ------------------------------------------------------
    def _monitor(self, job_id: int, handle: ClusterHandle):
        """Returns (succeeded, job_id, handle) — possibly a recovered
        cluster, which is the one whose logs are worth snapshotting.
        Terminal FAILED/CANCELLED states are recorded here; SUCCEEDED
        is NOT (the pipeline loop records it after the LAST task —
        intermediate task successes leave the job RUNNING)."""
        while True:
            time.sleep(POLL_SECONDS)
            rec = state.get(self.job_id)
            if rec["status"] == state.ManagedJobStatus.CANCELLING:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return False, job_id, handle
            js = self._cluster_job_status(handle, job_id)
            if js == JobStatus.SUCCEEDED:
                return True, job_id, handle
            if js == JobStatus.CANCELLED:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return False, job_id, handle
            if js is None or js in (JobStatus.FAILED,
                                    JobStatus.FAILED_SETUP):
                # Cluster gone (slice preempted) or job died with the
                # cluster unhealthy -> recover; genuine user failure on a
                # healthy cluster -> FAILED.
                if js == JobStatus.FAILED and self._cluster_healthy(handle):
                    state.set_status(self.job_id,
                                     state.ManagedJobStatus.FAILED,
                                     error="task failed on healthy cluster")
                    return False, job_id, handle
                PREEMPTIONS.inc()
                recovered = self._recover()
                if recovered is None:
                    return False, job_id, handle
                job_id, handle = recovered

    def _recover(self):
        """Recover the cluster+job; returns (job_id, handle) or None if
        the managed job reached a terminal state instead."""
        state.bump_recovery(self.job_id)     # cumulative, for display
        self.task_recoveries += 1            # per-task budget
        budget = recovery_strategy.max_recovery_attempts()
        if self.task_recoveries > budget:
            RECOVERY_ATTEMPTS.labels(outcome="exhausted").inc()
            # A typed terminal state + event, not a bare exception: the
            # giving-up decision must be visible in `skytpu jobs queue`
            # (FAILED_RECOVERY != the task failing) and in the trace.
            tracing.add_event(
                "jobs.recovery_gave_up",
                attrs={"managed_job_id": self.job_id,
                       "cluster": self.cluster_name,
                       "attempts": self.task_recoveries - 1,
                       "max_attempts": budget},
                echo=True)
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_RECOVERY,
                             error=f"recovery budget exhausted after "
                                   f"{budget} attempts")
            return None
        if not state.set_status(self.job_id,
                                state.ManagedJobStatus.RECOVERING):
            # Cancel landed while _monitor was probing — don't relaunch.
            RECOVERY_ATTEMPTS.labels(outcome="cancelled").inc()
            self._log("cancelled during recovery; tearing down")
            state.set_status(self.job_id, state.ManagedJobStatus.CANCELLED)
            return None
        # Backoff BEFORE the relaunch (attempt 2 onwards): a slice in a
        # preemption loop must not re-provision at poll speed. Routed
        # through the shared retry policy so the pause is configurable
        # and jittered like every other retry in the tree. AFTER the
        # RECOVERING write: the cancel check above runs pre-sleep, and
        # the queue shows RECOVERING (not a stale RUNNING) during the
        # pause.
        if self.task_recoveries > 1:
            from skypilot_tpu.utils import retry
            retry.pause(recovery_strategy.recovery_backoff_policy(),
                        self.task_recoveries - 2)
        try:
            state.acquire_launch_slot(self.job_id)
            try:
                job_id, handle = self.strategy.recover()
            finally:
                state.release_launch_slot(self.job_id)
        except exceptions.ResourcesUnavailableError as e:
            RECOVERY_ATTEMPTS.labels(outcome="no_resource").inc()
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
            return None
        if not state.transition_to_running(self.job_id):
            RECOVERY_ATTEMPTS.labels(outcome="cancelled").inc()
            self._log("cancelled during recovery; tearing down")
            state.set_status(self.job_id, state.ManagedJobStatus.CANCELLED)
            return None
        RECOVERY_ATTEMPTS.labels(outcome="recovered").inc()
        return job_id, handle

    # -- probes ------------------------------------------------------------
    def _cluster_healthy(self, handle: ClusterHandle) -> bool:
        try:
            return provision.query_instances(
                handle.provider, handle.cluster_name, handle.zone) == "UP"
        except exceptions.SkyTpuError:
            return False

    def _cluster_job_status(self, handle: ClusterHandle,
                            job_id: int) -> Optional[JobStatus]:
        if not self._cluster_healthy(handle):
            return None
        try:
            for j in self.backend.queue(handle):
                if j["job_id"] == job_id:
                    return j["status"]
        except exceptions.SkyTpuError:
            return None
        return None

    def _cleanup(self) -> None:
        if self.cluster_name is None:     # cancelled before any task
            return
        rec = cluster_state.get_cluster(self.cluster_name)
        if rec is not None:
            try:
                self.backend.teardown(ClusterHandle(rec["handle"]))
            except exceptions.SkyTpuError:
                cluster_state.remove_cluster(self.cluster_name)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job-id", type=int, required=True)
    args = ap.parse_args()
    try:
        controller = JobsController(args.job_id)
    except Exception as e:  # noqa: BLE001 — init errors must be recorded
        state.set_status(args.job_id,
                         state.ManagedJobStatus.FAILED_CONTROLLER,
                         error=f"{type(e).__name__}: {e}")
        raise
    controller.run()


if __name__ == "__main__":
    main()
