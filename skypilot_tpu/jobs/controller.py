"""Managed-job controller: one monitor process per job.

Reference parity: sky/jobs/controller.py (JobsController:53,
_run_one_task:120 — launch via strategy, poll, detect preemption,
recover, cleanup). Runs as a detached local process per job (the
reference runs it on a jobs-controller *cluster*; controller-as-task
recursion is wired through jobs/core.py the same way once a remote
controller cluster is configured — the control logic here is identical
either way).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from skypilot_tpu import exceptions, provision
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.jobs import recovery_strategy, state
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task

POLL_SECONDS = float(os.environ.get("SKYTPU_JOBS_POLL", "2"))


class JobsController:
    def __init__(self, managed_job_id: int):
        self.job_id = managed_job_id
        rec = state.get(managed_job_id)
        if rec is None:
            raise exceptions.ManagedJobError(f"no managed job {managed_job_id}")
        self.task = Task.from_yaml_config(rec["task_config"])
        self.cluster_name = f"sky-jobs-{managed_job_id}"
        self.strategy = recovery_strategy.StrategyExecutor.make(
            rec["recovery_strategy"], self.task, self.cluster_name)
        self.backend = TpuVmBackend()

    def run(self) -> None:
        try:
            state.set_status(self.job_id, state.ManagedJobStatus.STARTING)
            state.set_cluster(self.job_id, self.cluster_name)
            job_id, handle = self.strategy.launch()
            state.set_status(self.job_id, state.ManagedJobStatus.RUNNING)
            self._monitor(job_id, handle)
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
        except Exception as e:  # noqa: BLE001 — controller records failure
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_CONTROLLER,
                             error=f"{type(e).__name__}: {e}")
        finally:
            self._cleanup()

    # -- monitor loop ------------------------------------------------------
    def _monitor(self, job_id: int, handle: ClusterHandle) -> None:
        while True:
            time.sleep(POLL_SECONDS)
            rec = state.get(self.job_id)
            if rec["status"] == state.ManagedJobStatus.CANCELLING:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return
            js = self._cluster_job_status(handle, job_id)
            if js == JobStatus.SUCCEEDED:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.SUCCEEDED)
                return
            if js == JobStatus.CANCELLED:
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return
            if js is None or js in (JobStatus.FAILED,
                                    JobStatus.FAILED_SETUP):
                # Cluster gone (slice preempted) or job died with the
                # cluster unhealthy -> recover; genuine user failure on a
                # healthy cluster -> FAILED.
                if js == JobStatus.FAILED and self._cluster_healthy(handle):
                    state.set_status(self.job_id,
                                     state.ManagedJobStatus.FAILED,
                                     error="task failed on healthy cluster")
                    return
                recovered = self._recover()
                if recovered is None:
                    return
                job_id, handle = recovered

    def _recover(self):
        """Recover the cluster+job; returns (job_id, handle) or None if
        the managed job reached a terminal state instead."""
        n = state.bump_recovery(self.job_id)
        if n > recovery_strategy.MAX_RECOVERY_ATTEMPTS:
            state.set_status(self.job_id, state.ManagedJobStatus.FAILED,
                             error="max recovery attempts exceeded")
            return None
        state.set_status(self.job_id, state.ManagedJobStatus.RECOVERING)
        try:
            job_id, handle = self.strategy.recover()
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(self.job_id,
                             state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             error=str(e))
            return None
        state.set_status(self.job_id, state.ManagedJobStatus.RUNNING)
        return job_id, handle

    # -- probes ------------------------------------------------------------
    def _cluster_healthy(self, handle: ClusterHandle) -> bool:
        try:
            return provision.query_instances(
                handle.provider, handle.cluster_name, handle.zone) == "UP"
        except exceptions.SkyTpuError:
            return False

    def _cluster_job_status(self, handle: ClusterHandle,
                            job_id: int) -> Optional[JobStatus]:
        if not self._cluster_healthy(handle):
            return None
        try:
            for j in self.backend.queue(handle):
                if j["job_id"] == job_id:
                    return j["status"]
        except exceptions.SkyTpuError:
            return None
        return None

    def _cleanup(self) -> None:
        rec = cluster_state.get_cluster(self.cluster_name)
        if rec is not None:
            try:
                self.backend.teardown(ClusterHandle(rec["handle"]))
            except exceptions.SkyTpuError:
                cluster_state.remove_cluster(self.cluster_name)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job-id", type=int, required=True)
    args = ap.parse_args()
    try:
        controller = JobsController(args.job_id)
    except Exception as e:  # noqa: BLE001 — init errors must be recorded
        state.set_status(args.job_id,
                         state.ManagedJobStatus.FAILED_CONTROLLER,
                         error=f"{type(e).__name__}: {e}")
        raise
    controller.run()


if __name__ == "__main__":
    main()
