"""Recovery strategies for managed jobs on preemptible TPU slices.

Reference parity: sky/jobs/recovery_strategy.py (StrategyExecutor :45,
FAILOVER :382, EAGER_NEXT_REGION :466 — the NSDI'24 spot-policy home).
TPU-first delta: preemption is *slice-wide* (the queued-resource API
preempts whole slices, never single hosts), so recovery is always a full
relaunch — there is no partial-gang repair case, which removes the
reference's hardest edge cases by construction. EAGER_NEXT_ZONE is the
TPU analog of EAGER_NEXT_REGION: capacity pools are per-zone, so on
preemption we immediately blocklist the zone we were just evicted from.
"""

from __future__ import annotations

import os
from typing import Optional, Set, Tuple

from skypilot_tpu import chaos, exceptions, execution
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, RetryingProvisioner, TpuVmBackend
from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.task import Task
from skypilot_tpu.utils import retry
from skypilot_tpu.utils.registry import JOBS_RECOVERY_STRATEGY_REGISTRY

DEFAULT_STRATEGY = "EAGER_NEXT_ZONE"
MAX_RECOVERY_ATTEMPTS = 10           # default; see max_recovery_attempts()
DEFAULT_RECOVERY_BACKOFF_SECONDS = 1.0


def _tunable(env_var: str, config_key: str, default, cast):
    """env > config > default, with malformed values FALLING BACK (plus
    a typed event) instead of raising: these knobs exist for an
    operator rescuing a job mid-incident — a typo'd export must not
    turn the next recovery into FAILED_CONTROLLER."""
    from skypilot_tpu.observability import tracing
    env = os.environ.get(env_var)
    if env:
        try:
            return cast(env)
        except ValueError:
            # Fall THROUGH to the config layer — the next-best value
            # the operator expressed, not a silent jump to defaults.
            tracing.add_event(
                "jobs.config_invalid",
                attrs={"source": env_var, "value": env[:100]},
                echo=True)
    from skypilot_tpu import config
    raw = config.get_nested(("jobs", config_key), default)
    try:
        return cast(raw)
    except (TypeError, ValueError):
        tracing.add_event(
            "jobs.config_invalid",
            attrs={"source": f"jobs.{config_key}",
                   "value": str(raw)[:100], "fallback": default},
            echo=True)
        return default


def max_recovery_attempts() -> int:
    """Per-task recovery budget: ``SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS``
    env > ``jobs.max_recovery_attempts`` in ``~/.skypilot_tpu/
    config.yaml`` > the default (10). Read per recovery, not at import:
    the controller is a long-lived process and an operator raising the
    budget mid-incident should win."""
    return _tunable("SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS",
                    "max_recovery_attempts", MAX_RECOVERY_ATTEMPTS, int)


def recovery_backoff_policy() -> retry.RetryPolicy:
    """Backoff between recovery attempts (env
    ``SKYTPU_JOBS_RECOVERY_BACKOFF`` > config
    ``jobs.recovery_backoff_seconds`` > 1s base), jittered exponential
    capped at 60s — a slice stuck in a preemption loop must not hammer
    the provisioning API at poll speed."""
    base = _tunable("SKYTPU_JOBS_RECOVERY_BACKOFF",
                    "recovery_backoff_seconds",
                    DEFAULT_RECOVERY_BACKOFF_SECONDS, float)
    return retry.RetryPolicy(max_attempts=max_recovery_attempts(),
                             backoff_base_s=base,
                             backoff_multiplier=2.0, backoff_max_s=60.0)

RECOVERY_LAUNCHES = obs_metrics.counter(
    "skytpu_jobs_recovery_launches_total",
    "Cluster relaunches performed by recovery strategies (the eager "
    "strategy's blocked-zone fallback counts as a second launch)",
    labelnames=("strategy",))


class StrategyExecutor:
    """Launch + recover one managed job's cluster."""

    def __init__(self, task: Task, cluster_name: str):
        self.task = task
        self.cluster_name = cluster_name
        self.backend = TpuVmBackend()

    @classmethod
    def make(cls, name: Optional[str], task: Task,
             cluster_name: str) -> "StrategyExecutor":
        name = name or DEFAULT_STRATEGY
        strat_cls = JOBS_RECOVERY_STRATEGY_REGISTRY.get(name)
        if strat_cls is None:
            raise exceptions.ManagedJobError(
                f"unknown recovery strategy {name!r}; known: "
                f"{sorted(JOBS_RECOVERY_STRATEGY_REGISTRY)}")
        return strat_cls(task, cluster_name)

    # -- hooks -------------------------------------------------------------
    def launch(self) -> Tuple[int, ClusterHandle]:
        """First launch: retry_until_up across the full candidate set."""
        handle = self.backend.provision(self.task, self.cluster_name,
                                        retry_until_up=True)
        job_id = self.backend.execute(handle, self.task, detach_run=True)
        return job_id, handle

    def recover(self) -> Tuple[int, ClusterHandle]:
        raise NotImplementedError

    # -- shared ------------------------------------------------------------
    def _terminate_cluster(self) -> None:
        rec = cluster_state.get_cluster(self.cluster_name)
        if rec is not None:
            try:
                self.backend.teardown(ClusterHandle(rec["handle"]))
            except exceptions.SkyTpuError:
                cluster_state.remove_cluster(self.cluster_name)

    def _relaunch(self, blocked: Set) -> Tuple[int, ClusterHandle]:
        chaos.point("jobs.recovery", strategy=type(self).__name__,
                    cluster=self.cluster_name)
        RECOVERY_LAUNCHES.labels(strategy=type(self).__name__).inc()
        provisioner = RetryingProvisioner(retry_until_up=True)
        handle = provisioner.provision(self.task, self.cluster_name,
                                       initial_blocked=blocked)
        job_id = self.backend.execute(handle, self.task, detach_run=True)
        return job_id, handle


class FailoverStrategy(StrategyExecutor):
    """Retry the *same* zone first (it may recover), then fail over.
    Reference: FAILOVER :382."""

    def recover(self) -> Tuple[int, ClusterHandle]:
        self._terminate_cluster()
        return self._relaunch(blocked=set())


class EagerNextZoneStrategy(StrategyExecutor):
    """Immediately blocklist the zone that just preempted us.
    Reference: EAGER_NEXT_REGION :466, zone-granular for TPU pools."""

    def recover(self) -> Tuple[int, ClusterHandle]:
        rec = cluster_state.get_cluster(self.cluster_name)
        blocked = set()
        if rec is not None:
            h = rec["handle"]
            blocked.add((h.get("provider"), h.get("region"), h.get("zone")))
        self._terminate_cluster()
        try:
            return self._relaunch(blocked=blocked)
        except exceptions.ResourcesUnavailableError:
            # Everything else exhausted: the evicted zone is fair game.
            return self._relaunch(blocked=set())


JOBS_RECOVERY_STRATEGY_REGISTRY.register("FAILOVER", FailoverStrategy)
JOBS_RECOVERY_STRATEGY_REGISTRY.register("EAGER_NEXT_ZONE",
                                         EagerNextZoneStrategy)
# Alias keeps reference YAMLs working verbatim.
JOBS_RECOVERY_STRATEGY_REGISTRY.register("EAGER_NEXT_REGION",
                                         EagerNextZoneStrategy)
