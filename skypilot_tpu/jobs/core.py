"""Managed-jobs client ops: launch/queue/cancel/logs.

Reference parity: sky/jobs/server/core.py + scheduler limits
(sky/jobs/scheduler.py:66-72 — launching <= 4x CPUs, alive <= mem/350MB,
hard cap 2000).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import state
from skypilot_tpu.task import Task
from skypilot_tpu.utils import paths

MAX_JOB_LIMIT = 2000  # reference: sky/jobs/scheduler.py:70


def _alive_limit() -> int:
    try:
        mem_bytes = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        by_mem = int(mem_bytes / (350 * 1024 * 1024))
    except (ValueError, OSError):
        by_mem = MAX_JOB_LIMIT
    return min(by_mem, MAX_JOB_LIMIT)


def launch(task: Task, name: Optional[str] = None) -> int:
    """Submit a managed job; a detached controller process owns it."""
    if state.count_alive() >= _alive_limit():
        raise exceptions.ManagedJobError(
            f"managed-job limit reached ({_alive_limit()}); wait for "
            f"running jobs to finish")
    strategy = None
    for r in task.resources:
        strategy = r.job_recovery or strategy
    job_id = state.add(name or task.name, task.to_yaml_config(),
                       strategy or "EAGER_NEXT_ZONE")
    log = os.path.join(paths.logs_dir(), f"jobs-controller-{job_id}.log")
    with open(log, "ab") as f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.jobs.controller",
             "--job-id", str(job_id)],
            stdout=f, stderr=subprocess.STDOUT, start_new_session=True,
            env={**os.environ, "SKYPILOT_TPU_HOME": paths.home()})
    state.set_controller_pid(job_id, proc.pid)
    state.set_status(job_id, state.ManagedJobStatus.SUBMITTED)
    return job_id


def queue() -> List[Dict[str, Any]]:
    return state.list_jobs()


def cancel(job_id: int) -> None:
    rec = state.get(job_id)
    if rec is None:
        raise exceptions.ManagedJobError(f"no managed job {job_id}")
    if rec["status"].is_terminal():
        return
    state.set_status(job_id, state.ManagedJobStatus.CANCELLING)
    # Controller notices CANCELLING and tears the cluster down; if the
    # controller itself died, finalize here.
    pid = rec["controller_pid"]
    if pid is not None:
        try:
            os.kill(pid, 0)
            return  # alive; it will finish the cancellation
        except OSError:
            pass
    state.set_status(job_id, state.ManagedJobStatus.CANCELLED)


def wait(job_id: int, timeout: float = 600) -> state.ManagedJobStatus:
    deadline = time.time() + timeout
    while time.time() < deadline:
        rec = state.get(job_id)
        if rec and rec["status"].is_terminal():
            return rec["status"]
        time.sleep(0.3)
    raise TimeoutError(f"managed job {job_id} not terminal in {timeout}s")


def tail_controller_log(job_id: int, out=None) -> None:
    out = out or sys.stdout
    p = os.path.join(paths.logs_dir(), f"jobs-controller-{job_id}.log")
    if os.path.exists(p):
        out.write(open(p).read())
