"""Managed-jobs client ops: launch/queue/cancel/logs.

Controller-as-task (reference: sky/jobs/server/core.py — the client
fills jobs-controller.yaml.j2 and sky.launches a controller cluster,
then codegen-RPCs into it): here the controller cluster is provisioned
through the same framework launch path (controller_utils), and every
operation below is one typed RPC to its head, where the managed-jobs
state DB and the per-job controller processes live. Managed jobs
therefore survive this client and are visible to every client.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import controller_utils, exceptions
from skypilot_tpu.backend import ClusterHandle
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.task import Task


def _controller_handle(create_for: Optional[Task] = None) -> ClusterHandle:
    return controller_utils.get_or_create_controller(
        controller_utils.JOBS_CONTROLLER_CLUSTER, "jobs",
        exceptions.ManagedJobError, create_for)


def _rpc(handle: ClusterHandle):
    return controller_utils.controller_rpc(handle)


def launch(task, name: Optional[str] = None) -> int:
    """Submit a managed job; a controller process on the jobs controller
    cluster owns it end to end.

    ``task`` may be a list of Tasks — a PIPELINE: the controller runs
    them sequentially, each on its own cluster with its own recovery
    (reference: multi-document job YAMLs, sky/jobs/controller.py:68)."""
    tasks = task if isinstance(task, list) else [task]
    handle = _controller_handle(create_for=tasks[0])
    tasks = [controller_utils.translate_local_file_mounts(t, handle)
             for t in tasks]
    strategy = None
    if len(tasks) == 1:
        for r in tasks[0].resources:
            strategy = r.job_recovery or strategy
        task_config = tasks[0].to_yaml_config()
    else:
        # Pipelines: recovery is PER TASK — each step's job_recovery
        # rides in its own config and controller._bind_task applies it;
        # aggregating across steps would leak one task's choice into
        # siblings that set none (they get the job default instead).
        task_config = {"pipeline": [t.to_yaml_config() for t in tasks]}
    result = _rpc(handle).call(
        "jobs_submit", name=name or tasks[0].name,
        task_config=task_config,
        strategy=strategy or "EAGER_NEXT_ZONE")
    return result["job_id"]


def _rehydrate(rec: Dict[str, Any]) -> Dict[str, Any]:
    rec = dict(rec)
    rec["status"] = ManagedJobStatus(rec["status"])
    return rec


def queue() -> List[Dict[str, Any]]:
    return [_rehydrate(r)
            for r in _rpc(_controller_handle()).call("jobs_list")]


def get(job_id: int) -> Optional[Dict[str, Any]]:
    rec = _rpc(_controller_handle()).call("jobs_get", job_id=job_id)
    return _rehydrate(rec) if rec else None


def cancel(job_id: int) -> None:
    _rpc(_controller_handle()).call("jobs_cancel", job_id=job_id)


def wait(job_id: int, timeout: float = 600,
         poll: Optional[float] = None) -> ManagedJobStatus:
    handle = _controller_handle()
    rpc = _rpc(handle)
    # Each poll is a full RPC round trip (SSH exec on cloud
    # controllers): poll gently there, snappily on local ones.
    if poll is None:
        poll = 0.3 if handle.provider == "local" else 3.0
    deadline = time.time() + timeout
    while time.time() < deadline:
        rec = rpc.call("jobs_get", job_id=job_id)
        if rec and ManagedJobStatus(rec["status"]).is_terminal():
            return ManagedJobStatus(rec["status"])
        time.sleep(poll)
    raise TimeoutError(f"managed job {job_id} not terminal in {timeout}s")


def tail_job_output(job_id: int, out=None) -> None:
    """Fetch the managed job's task output logs via the controller
    cluster (which holds the per-job cluster handle)."""
    out = out or sys.stdout
    r = _rpc(_controller_handle()).call("jobs_tail", job_id=job_id)
    if r["text"]:
        out.write(r["text"])
    if r.get("note"):
        print(r["note"], file=sys.stderr)


def tail_controller_log(job_id: int, out=None, follow: bool = False,
                        poll: Optional[float] = None) -> None:
    """Stream the controller log for one managed job from the controller
    cluster (reference: sky jobs logs --controller)."""
    out = out or sys.stdout
    handle = _controller_handle()
    rpc = _rpc(handle)
    if poll is None:
        poll = 0.5 if handle.provider == "local" else 3.0
    offset = 0
    while True:
        r = rpc.call("jobs_log", job_id=job_id, offset=offset)
        if r["text"]:
            out.write(r["text"])
        offset = r["offset"]
        if not follow:
            return
        rec = rpc.call("jobs_get", job_id=job_id)
        if rec and ManagedJobStatus(rec["status"]).is_terminal():
            r = rpc.call("jobs_log", job_id=job_id, offset=offset)
            if r["text"]:
                out.write(r["text"])
            return
        time.sleep(poll)
