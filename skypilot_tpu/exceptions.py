"""Error taxonomy. Mirrors the failover-driving design of the reference
(reference: sky/exceptions.py — ResourcesUnavailableError carries
failover_history / no_failover so the provisioner can re-optimize)."""

from __future__ import annotations

from typing import List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


class ResourcesUnavailableError(SkyTpuError):
    """A candidate (cloud, region, zone, accelerator) could not be
    provisioned. Drives the failover loop: the provisioner adds the
    candidate to the blocklist and re-optimizes."""

    def __init__(self, message: str, no_failover: bool = False,
                 failover_history: Optional[List[Exception]] = None):
        super().__init__(message)
        self.no_failover = no_failover
        self.failover_history: List[Exception] = failover_history or []

    def with_failover_history(self, history):
        self.failover_history = list(history)
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not fit an existing cluster."""


class QuotaExceededError(ResourcesUnavailableError):
    """Provider quota error — block the whole region, not just a zone."""


class CapacityError(ResourcesUnavailableError):
    """Stockout / capacity error — block the zone."""


class ClusterNotUpError(SkyTpuError):
    pass


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    pass


class CommandError(SkyTpuError):
    """Remote command failed."""

    def __init__(self, returncode: int, command: str, error_msg: str = "",
                 detailed_reason: str = ""):
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        super().__init__(
            f"Command failed with code {returncode}: {command}\n{error_msg}")


class JobNotFoundError(SkyTpuError):
    pass


class ProvisionTimeoutError(ResourcesUnavailableError):
    pass


class NoCloudAccessError(SkyTpuError):
    pass


class StorageError(SkyTpuError):
    pass


class ServeError(SkyTpuError):
    pass


class ManagedJobError(SkyTpuError):
    pass


class ProvisionError(ResourcesUnavailableError):
    """Provider-level instance CRUD failure (drives failover)."""


class NotSupportedError(SkyTpuError):
    """The provider cannot perform the requested operation."""


class InvalidTaskError(SkyTpuError):
    pass
