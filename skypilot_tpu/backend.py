"""TpuVmBackend: provision -> sync -> exec -> logs -> down, with the
failover-retry provisioner.

Reference parity: sky/backends/cloud_vm_ray_backend.py — but the 5,100-LoC
monolith decomposes here because two big reference subsystems vanish by
design: (a) no Ray codegen (runtime/driver.py is a real program, not
generated source), (b) no SSH-string-codegen RPC (the cluster-side job
queue/driver/skylet are reached through the typed JSON RPC in
runtime/rpc.py, executed on the head via the command runner).

All job state lives ON THE CLUSTER HEAD (reference: on-head sqlite at
sky/skylet/job_lib.py:204-276): a launched cluster is autonomous —
running jobs, log capture, and autostop survive the client, and any
client that can reach the head can queue/cancel/tail.

The failover engine (RetryingProvisioner) keeps the reference's proven
shape (reference :1988 provision_with_retries): iterate candidates from
the optimizer, convert provider errors to blocklist entries at the right
scope (zone for capacity, region for quota), re-optimize, and optionally
loop until up.
"""

from __future__ import annotations

import os
import shlex
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, optimizer, provision, state
from skypilot_tpu.provision.common import ClusterInfo, ProvisionConfig
from skypilot_tpu.resources import Resources
from skypilot_tpu.runtime import job_queue, topology
from skypilot_tpu.runtime.rpc_client import ClusterRpc
from skypilot_tpu.task import Task
from skypilot_tpu.utils import paths, retry, timeline

# Head-side location of the intra-cluster SSH key (pushed by
# instance_setup for ssh-reachable hosts).
_HEAD_SSH_KEY = "~/.skypilot_tpu/ssh/sky-key"


def cluster_lock(cluster_name: str) -> timeline.FileLockEvent:
    """Per-cluster lifecycle lock (reference:
    sky/backends/cloud_vm_ray_backend.py:2846 locks every provision).
    Two clients racing ``launch -c same`` must produce ONE cluster and
    one provision; same for concurrent start/stop/teardown."""
    return timeline.FileLockEvent(
        os.path.join(paths.home(), "locks",
                     f"cluster-{cluster_name}.lock"))


class ClusterHandle(dict):
    """JSON-serializable cluster descriptor stored in the state DB."""

    @property
    def cluster_name(self) -> str:
        return self["cluster_name"]

    @property
    def provider(self) -> str:
        return self["provider"]

    @property
    def zone(self) -> str:
        return self["zone"]

    @property
    def resources(self) -> Resources:
        return Resources.from_yaml_config(self["resources"])

    @classmethod
    def create(cls, cluster_name: str, launchable: Resources,
               num_nodes: int) -> "ClusterHandle":
        return cls(
            cluster_name=cluster_name,
            provider=launchable.cloud,
            zone=launchable.zone,
            region=launchable.region,
            num_nodes=num_nodes,
            hosts_per_node=launchable.hosts_per_node,
            resources=launchable.to_yaml_config(),
            price_per_hour=(launchable.price or 0.0) * num_nodes,
        )


def _blocklist_scope(err: exceptions.ResourcesUnavailableError,
                     launchable: Resources):
    """Error type -> blocklist granularity (reference:
    FailoverCloudErrorHandlerV2 semantics, cloud_vm_ray_backend.py:940)."""
    if isinstance(err, exceptions.QuotaExceededError):
        return (launchable.cloud, launchable.region, None)
    return (launchable.cloud, launchable.region, launchable.zone)


def check_owner_identity(cluster_name: str) -> None:
    """Refuse to operate on another identity's cluster.

    Reference parity: sky/backends/backend_utils.py:1509
    check_owner_identity. Pre-ownership records (owner NULL, from a v1
    state DB) stay operable by everyone — matching the reference's
    grandfathering of old clusters.
    """
    rec = state.get_cluster(cluster_name)
    if rec is None or not rec.get("owner"):
        return
    from skypilot_tpu import authentication
    me = authentication.get_user_identity()
    if rec["owner"] != me["id"]:
        owner = state.get_user(rec["owner"]) or {}
        who = owner.get("name") or rec["owner"]
        raise exceptions.ClusterOwnerIdentityMismatchError(
            f"cluster {cluster_name!r} is owned by {who} "
            f"(id {rec['owner']}), not the current user {me['name']} "
            f"(id {me['id']}). Pick a different cluster name, or set "
            "SKYPILOT_TPU_USER to the owning identity if this is "
            "really you.")


class RetryingProvisioner:
    """Optimize -> provision -> on failure, blocklist + re-optimize."""

    def __init__(self, retry_until_up: bool = False,
                 backoff_seconds: float = 5.0,
                 max_rounds: int = 3):
        self.retry_until_up = retry_until_up
        self.backoff_seconds = backoff_seconds
        self.max_rounds = max_rounds

    def provision(self, task: Task, cluster_name: str,
                  initial_blocked: Optional[set] = None) -> ClusterHandle:
        blocked: set = set(initial_blocked or set())
        history: List[Exception] = []

        def sweep() -> ClusterHandle:
            """One full failover pass: keep blocklisting + re-optimizing
            until a candidate provisions or every candidate is blocked
            (ResourcesUnavailableError carrying the failover history)."""
            while True:
                try:
                    launchable = optimizer.optimize_task(task, blocked)
                except exceptions.ResourcesUnavailableError as e:
                    raise e.with_failover_history(history)
                try:
                    return self._provision_one(task, cluster_name,
                                               launchable)
                except exceptions.ResourcesUnavailableError as e:
                    history.append(e)
                    blocked.add(_blocklist_scope(e, launchable))
                    print(f"Provision failed on {launchable}: {e}; "
                          f"failing over ({len(blocked)} blocked)",
                          file=sys.stderr)

        if not self.retry_until_up:
            return sweep()
        # retry_until_up: between sweeps, clear the blocklist and back
        # off (capacity comes back) — the backoff/attempt budget rides
        # the shared retry policy so chaos runs can assert it.
        return retry.call(
            sweep, name="provision.sweep",
            policy=retry.RetryPolicy(
                max_attempts=self.max_rounds,
                backoff_base_s=self.backoff_seconds,
                backoff_multiplier=2.0, backoff_max_s=300.0,
                retry_on=(exceptions.ResourcesUnavailableError,)),
            on_retry=lambda attempt, exc, pause: blocked.clear())

    def _provision_one(self, task: Task, cluster_name: str,
                       launchable: Resources) -> ClusterHandle:
        # Capability gates BEFORE any instance is created — a cluster
        # that can never run its gang must not be provisioned and
        # billed first (reference: requested_features collection at
        # sky/execution.py:209-244).
        provider = launchable.cloud or "gcp"
        if task.num_nodes > 1 and not provision.supports(
                provider, provision.Feature.MULTI_NODE):
            raise exceptions.NotSupportedError(
                f"{provider} cannot provision multi-node clusters "
                f"(Feature.MULTI_NODE)")
        if (task.num_nodes * launchable.hosts_per_node > 1
                and not provision.supports(
                    provider, provision.Feature.MULTI_NODE_EXEC)):
            raise exceptions.NotSupportedError(
                f"{provider} cannot gang-execute across "
                f"{task.num_nodes * launchable.hosts_per_node} hosts yet "
                f"(Feature.MULTI_NODE_EXEC)")
        handle = ClusterHandle.create(cluster_name, launchable,
                                      task.num_nodes)
        from skypilot_tpu import authentication
        state.set_cluster(cluster_name, dict(handle), state.ClusterStatus.INIT,
                          handle["price_per_hour"],
                          owner=authentication.get_user_identity())
        config = ProvisionConfig(
            cluster_name=cluster_name,
            num_nodes=task.num_nodes,
            hosts_per_node=launchable.hosts_per_node,
            zone=launchable.zone,
            region=launchable.region,
            accelerator=launchable.accelerator_name,
            accelerator_count=launchable.accelerator_count,
            instance_type=launchable.instance_type,
            use_spot=launchable.use_spot,
            runtime_version=launchable.runtime_version,
            disk_size=launchable.disk_size,
            image_id=launchable.image_id,
            ports=list(launchable.ports) if launchable.ports else None,
        )
        provision.run_instances(handle.provider, config)
        provision.wait_instances(handle.provider, cluster_name, handle.zone)
        _setup_and_init_runtime(handle.provider, cluster_name, handle.zone,
                                docker_image=launchable.docker_image)
        state.set_cluster(cluster_name, dict(handle), state.ClusterStatus.UP,
                          handle["price_per_hour"])
        return handle


def _setup_and_init_runtime(provider: str, cluster_name: str,
                            zone: str,
                            docker_image: Optional[str] = None
                            ) -> ClusterInfo:
    """Post-provision: wait for hosts, push the framework + cluster key,
    and write the head-side cluster.json through the RPC so the cluster
    runtime (driver/skylet/job DB) is self-sufficient from here on."""
    from skypilot_tpu.provision import instance_setup
    info = provision.get_cluster_info(provider, cluster_name, zone)
    instance_setup.wait_for_ssh(info)
    instance_setup.setup_runtime_on_cluster(info)
    if docker_image and any(h.runner_kind == "k8s" for h in info.hosts):
        # On kubernetes the POD already runs this image (pod_manifest
        # uses it as the pod image): no docker-in-pod setup, and the
        # gang driver must NOT wrap jobs in docker exec.
        docker_image = None
    if docker_image:
        # image_id: docker:<img> — pull + start the task container on
        # every host; the gang driver will exec jobs inside it.
        instance_setup.setup_docker_on_cluster(info, docker_image)
    uses_ssh = any(h.runner_kind == "ssh" for h in info.hosts)
    agent_token = None
    if any(h.runner_kind == "k8s" for h in info.hosts):
        # Pods have no sshd: the head's gang driver reaches peers via
        # the per-pod hostd agent.
        import secrets
        # start_host_agents returns the token in force (an existing
        # cluster token wins — live agents only know the one they
        # started with).
        agent_token = instance_setup.start_host_agents(
            info, secrets.token_hex(16))
    meta = topology.from_cluster_info(
        info,
        provider_env=info.metadata.get("provider_env"),
        ssh_key_path=_HEAD_SSH_KEY if uses_ssh else None,
        launched_at=time.time(),
        agent_token=agent_token,
        docker_image=docker_image)
    _rpc_for_info(info, cluster_name).init_cluster(meta)
    return info


def _rpc_for_info(info: ClusterInfo, cluster_name: str) -> ClusterRpc:
    head_runner = provision.get_command_runners(info)[0]
    return ClusterRpc(head_runner, cluster_name)


class TpuVmBackend:
    """The production backend (name kept honest: it drives TPU-VM slices
    on GCP and plain VMs/local hosts through the same path)."""

    # -- provisioning ------------------------------------------------------
    def provision(self, task: Task, cluster_name: str,
                  retry_until_up: bool = False) -> ClusterHandle:
        # The existing-cluster check and the create must be atomic
        # against other clients of this state DB, or a race provisions
        # the same name twice (cloud-side duplicate or clobbered
        # handle).
        with cluster_lock(cluster_name):
            check_owner_identity(cluster_name)
            existing = state.get_cluster(cluster_name)
            if existing is not None:
                handle = ClusterHandle(existing["handle"])
                if existing["status"] == state.ClusterStatus.UP:
                    self.check_resources_fit(task, handle)
                    return handle
                if existing["status"] == state.ClusterStatus.STOPPED:
                    return self._start_locked(cluster_name)
            return RetryingProvisioner(retry_until_up).provision(
                task, cluster_name)

    def check_resources_fit(self, task: Task, handle: ClusterHandle) -> None:
        cluster_res = handle.resources
        for r in task.resources:
            if r.less_demanding_than(cluster_res):
                return
        raise exceptions.ResourcesMismatchError(
            f"task {task} does not fit cluster {handle.cluster_name} "
            f"({cluster_res})")

    # -- sync --------------------------------------------------------------
    def sync_workdir(self, handle: ClusterHandle, workdir: str) -> None:
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        from skypilot_tpu.data import storage_utils
        excludes = ([".git"] + storage_utils.read_ignore_patterns(
            os.path.expanduser(workdir)))
        for runner, host in zip(provision.get_command_runners(info),
                                info.hosts):
            dst = (os.path.join(host.workspace, "sky_workdir")
                   if host.workspace else "~/sky_workdir")
            runner.rsync(workdir, dst, up=True, excludes=excludes)

    def sync_file_mounts(self, handle: ClusterHandle,
                         file_mounts: Dict[str, str]) -> None:
        if not file_mounts:
            return
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        runners = provision.get_command_runners(info)
        from skypilot_tpu.data import cloud_stores
        for dst, src in file_mounts.items():
            if src.startswith(cloud_stores.BUCKET_URL_PREFIXES):
                from skypilot_tpu.data import storage as storage_lib
                storage_lib.mount_or_copy(handle, dst, src)
                continue
            for runner, host in zip(runners, info.hosts):
                tgt = (os.path.join(host.workspace, dst.lstrip("/~"))
                       if host.workspace else dst)
                runner.rsync(os.path.expanduser(src), tgt, up=True)

    def sync_storage_mounts(self, handle: ClusterHandle,
                            storage_mounts: Dict[str, Any]) -> None:
        """Create/upload buckets, then mount (gcsfuse) or copy down on
        every host. Values may be data.storage.Storage objects or their
        YAML dicts."""
        if not storage_mounts:
            return
        from skypilot_tpu.data import storage as storage_lib
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        runners = provision.get_command_runners(info)
        ephemeral = list(handle.get("ephemeral_storage", []))
        for dst, spec in storage_mounts.items():
            store = (spec if isinstance(spec, storage_lib.Storage)
                     else storage_lib.Storage.from_yaml_config(spec))
            store.sync_up()
            state.add_storage(store.name, store.to_yaml_config())
            if not store.persistent:
                cfg = store.to_yaml_config()
                if cfg not in ephemeral:
                    ephemeral.append(cfg)
            for runner, host in zip(runners, info.hosts):
                tgt = (os.path.join(host.workspace, dst.lstrip("/~"))
                       if host.workspace else dst)
                for cmd in store.attach_commands(tgt):
                    rc, out, err = runner.run(cmd)
                    if rc != 0:
                        raise exceptions.StorageError(
                            f"storage mount {dst} failed on host "
                            f"{runner.host_id}: {out}{err}")
        if ephemeral:
            # Persist on the handle so teardown can delete the buckets.
            handle["ephemeral_storage"] = ephemeral
            rec = state.get_cluster(handle.cluster_name)
            if rec is not None:
                state.set_cluster(handle.cluster_name, dict(handle),
                                  rec["status"],
                                  rec.get("price_per_hour", 0.0))

    # -- execution ---------------------------------------------------------
    def _rpc(self, handle: ClusterHandle) -> ClusterRpc:
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        return _rpc_for_info(info, handle.cluster_name)

    def execute(self, handle: ClusterHandle, task: Task,
                detach_run: bool = True) -> int:
        n_hosts = (handle.get("num_nodes", 1)
                   * handle.get("hosts_per_node", 1))
        if n_hosts > 1 and not provision.supports(
                handle.provider, provision.Feature.MULTI_NODE_EXEC):
            # Refuse at submit time with the contract's words, not at
            # runtime inside the head-side driver.
            raise exceptions.NotSupportedError(
                f"{handle.provider} cannot gang-execute across "
                f"{n_hosts} hosts yet (Feature.MULTI_NODE_EXEC)")
        setup = f"{task.setup}\n" if task.setup else ""
        if task.run is None:
            run_cmd = "true"
        elif isinstance(task.run, str):
            run_cmd = task.run
        else:
            raise exceptions.InvalidTaskError(
                "callable run is resolved by execution.launch before "
                "reaching the backend")
        env_exports = "".join(
            f"export {k}={shlex.quote(str(v))}\n"
            for k, v in task.envs.items())
        script = f"{env_exports}{setup}{run_cmd}"
        # The job runs inside ~/sky_workdir when a workdir was synced —
        # directly (sync_workdir) or via the controller's bucket
        # translation, which rewrites workdir into a ~/sky_workdir
        # file mount (controller_utils.translate_local_file_mounts).
        has_workdir = bool(task.workdir) or "~/sky_workdir" in (
            task.file_mounts or {})
        job_id = self._rpc(handle).submit(
            task.name, script, task.num_nodes, workdir=has_workdir)
        if not detach_run:
            self.wait_job(handle, job_id)
        return job_id

    def wait_job(self, handle: ClusterHandle, job_id: int,
                 timeout: float = 3600,
                 poll_interval: float = 0.3) -> job_queue.JobStatus:
        rpc = self._rpc(handle)
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = rpc.get_job(job_id)
            if job and job["status"].is_terminal():
                return job["status"]
            time.sleep(poll_interval)
        raise TimeoutError(f"job {job_id} did not finish in {timeout}s")

    # -- job ops -----------------------------------------------------------
    def queue(self, handle: ClusterHandle) -> List[Dict[str, Any]]:
        return self._rpc(handle).list_jobs()

    def cancel(self, handle: ClusterHandle, job_id: int) -> None:
        self._rpc(handle).cancel(job_id)

    def set_autostop(self, handle: ClusterHandle,
                     idle_minutes: Optional[int], down: bool) -> None:
        """Arm (or disarm) cluster-side autostop: the skylet on the head
        stops/downs the cluster itself (reference: skylet/events.py:102
        AutostopEvent calls the cloud API from the VM)."""
        if (idle_minutes is not None and idle_minutes >= 0 and not down
                and handle.provider == "gcp"
                and str(handle.resources.accelerator_name or
                        "").startswith("tpu-")
                and (handle.get("num_nodes", 1) > 1
                     or handle.get("hosts_per_node", 1) > 1)):
            # Fail at arm time, not when the skylet eventually tries:
            # multi-host/multislice TPUs cannot stop (reference carries
            # the same restriction, clouds/gcp.py:206-212).
            raise exceptions.NotSupportedError(
                "autostop (stop mode) is not supported on multi-host/"
                "multislice TPU clusters — use autostop --down")
        self._rpc(handle).set_autostop(idle_minutes, down)

    def job_log_paths(self, handle: ClusterHandle, job_id: int) -> List[str]:
        """Sync job logs down from the head; returns client-local paths
        (reference: sync_down_logs, cloud_vm_ray_backend.py:3740)."""
        _, chunks, _ = self._rpc(handle).read_logs(job_id, {})
        d = os.path.join(paths.cluster_dir(handle.cluster_name), "logs",
                         f"job_{job_id}")
        os.makedirs(d, exist_ok=True)
        for fname, text in chunks.items():
            with open(os.path.join(d, fname), "w") as f:
                f.write(text)
        return sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.startswith("rank-"))

    def tail_logs(self, handle: ClusterHandle, job_id: int,
                  follow: bool = False, out=None,
                  poll_interval: float = 0.4) -> None:
        """Stream job logs from the head. Bounded: for a terminal job the
        server reads status before log bytes, so the read that observes
        a terminal status already carries every byte the job wrote — no
        unbounded final-drain loop (a background child that keeps a rank
        log growing cannot wedge the client)."""
        out = out if out is not None else sys.stdout
        rpc = self._rpc(handle)
        offsets: Dict[str, int] = {}
        while True:
            status, chunks, offsets = rpc.read_logs(job_id, offsets)
            for fname in sorted(chunks):
                prefix = fname.replace(".log", "")
                for line in chunks[fname].splitlines():
                    print(f"({prefix}) {line}", file=out)
            if not follow or status.is_terminal():
                return
            time.sleep(poll_interval)

    # -- lifecycle ---------------------------------------------------------
    def stop(self, handle: ClusterHandle) -> None:
        if not provision.supports(handle.provider,
                                  provision.Feature.STOP):
            raise exceptions.NotSupportedError(
                f"{handle.provider} instances cannot stop; use down "
                f"(Feature.STOP)")
        with cluster_lock(handle.cluster_name):
            provision.stop_instances(handle.provider, handle.cluster_name,
                                     handle.zone)
            state.set_cluster_status(handle.cluster_name,
                                     state.ClusterStatus.STOPPED)

    def start(self, cluster_name: str) -> ClusterHandle:
        with cluster_lock(cluster_name):
            return self._start_locked(cluster_name)

    def _start_locked(self, cluster_name: str) -> ClusterHandle:
        rec = state.get_cluster(cluster_name)
        if rec is None:
            raise exceptions.ClusterNotUpError(f"no cluster {cluster_name}")
        handle = ClusterHandle(rec["handle"])
        # Rebuild the FULL config: run_instances dispatches TPU-vs-
        # Compute on the accelerator field, and the resume paths read
        # ports/image/runtime_version — a bare config here would send a
        # stopped TPU node down the Compute Engine path.
        res = handle.resources
        config = ProvisionConfig(
            cluster_name=cluster_name,
            num_nodes=handle["num_nodes"],
            hosts_per_node=handle["hosts_per_node"],
            zone=handle.zone, region=handle["region"],
            accelerator=res.accelerator_name,
            accelerator_count=res.accelerator_count,
            instance_type=res.instance_type,
            use_spot=res.use_spot,
            runtime_version=res.runtime_version,
            disk_size=res.disk_size,
            image_id=res.image_id,
            ports=list(res.ports) if res.ports else None)
        provision.run_instances(handle.provider, config)
        provision.wait_instances(handle.provider, cluster_name, handle.zone)
        # Re-run runtime init: restarted VMs may have new IPs, and the
        # head needs a fresh cluster.json (autostop config and job
        # history persist on the head's disk across stop/start).
        _setup_and_init_runtime(handle.provider, cluster_name, handle.zone,
                                docker_image=res.docker_image)
        state.set_cluster_status(cluster_name, state.ClusterStatus.UP)
        return handle

    def teardown(self, handle: ClusterHandle) -> None:
        with cluster_lock(handle.cluster_name):
            self._teardown_locked(handle)

    def _teardown_locked(self, handle: ClusterHandle) -> None:
        provision.terminate_instances(handle.provider, handle.cluster_name,
                                      handle.zone)
        # Ephemeral (persistent: false) buckets die with the cluster.
        for cfg in handle.get("ephemeral_storage", []):
            from skypilot_tpu.data import storage as storage_lib
            try:
                store = storage_lib.Storage.from_yaml_config(cfg)
                store.delete()
                state.remove_storage(store.name)
            except Exception as e:  # noqa: BLE001 — teardown must proceed
                print(f"WARNING: deleting ephemeral storage {cfg} "
                      f"failed: {e}", file=sys.stderr)
        state.remove_cluster(handle.cluster_name)
        # Clear the client-side cluster dir (job queue, logs, scripts) so
        # a future cluster reusing the name starts clean.
        import shutil
        shutil.rmtree(paths.cluster_dir(handle.cluster_name),
                      ignore_errors=True)

    def refresh_status(self, cluster_name: str) -> Optional[state.ClusterStatus]:
        rec = state.get_cluster(cluster_name)
        if rec is None:
            return None
        handle = ClusterHandle(rec["handle"])
        raw = provision.query_instances(handle.provider, cluster_name,
                                        handle.zone)
        mapping = {
            "UP": state.ClusterStatus.UP,
            "STOPPED": state.ClusterStatus.STOPPED,
        }
        if raw == "NOT_FOUND":
            state.remove_cluster(cluster_name)
            import shutil
            shutil.rmtree(paths.cluster_dir(cluster_name),
                          ignore_errors=True)
            return None
        new = mapping.get(raw, state.ClusterStatus.INIT)
        state.set_cluster_status(cluster_name, new)
        return new
