"""Shared ring-buffer + atomic-flush machinery for the recorders.

``tracing.py`` (the structured event log) and ``flight.py`` (the
engine flight recorder) grew the same idiom independently — a bounded
in-process ring, a per-process-incarnation log name, an atexit flush,
the snapshot-under-ring-lock / serialize-outside / write-under-flush-
lock discipline (the PR 2 fix), and a daemon heartbeat thread — and
PR 10 deliberately deferred unifying them. This module is that
extraction: one :class:`Ring` owns the state machine, and the
recorders keep only their record *shapes* and public APIs. The
training goodput recorder (``goodput.py``) is the third consumer.

Invariants the Ring guarantees for every consumer:

* recording is a container append under ``_lock`` — no filesystem
  touch, no serialization;
* a flush snapshots under ``_lock``, serializes OUTSIDE it, and
  writes under ``_flush_lock`` via tempfile + ``os.replace`` so a
  reader never sees a torn file and recorder threads never block on
  an O(ring) ``json.dumps`` pass;
* a stale flush (a newer one landed while this one serialized) is
  dropped by the ``seq`` guard rather than clobbering newer data;
* the log name is minted once per process incarnation
  (``<prefix>-<pid>-<start ms>``) so a recycled pid can never clobber
  a dead process's log.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class Ring:
    """Bounded record ring with atomic whole-buffer flush.

    ``capacity`` bounds the ring; ``halve_on_overflow=True`` keeps a
    plain list and drops the oldest half when full (the tracing event
    log's amortized-O(1) trim), ``False`` uses a ``deque(maxlen)``
    (the flight recorder's shape). ``name_fn`` mints the per-process
    log filename on first append; ``dir_fn`` resolves the flush
    directory at flush time (it can change under tests); ``seq_field``
    names a record key to stamp with the ring sequence number (the
    flight cursor contract); ``atexit_extra`` runs after the atexit
    flush (tracing's event-log GC).
    """

    def __init__(self, capacity: int, name_fn: Callable[[], str],
                 dir_fn: Callable[[], str], *,
                 halve_on_overflow: bool = False,
                 seq_field: Optional[str] = None,
                 atexit_extra: Optional[Callable[[], None]] = None,
                 thread_name: str = "ring-flush"):
        self.capacity = capacity
        self._name_fn = name_fn
        self._dir_fn = dir_fn
        self._halve = halve_on_overflow
        self._seq_field = seq_field
        self._atexit_extra = atexit_extra
        self._thread_name = thread_name
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()  # serializes log-file writers
        if halve_on_overflow:
            self._records: Any = []                    # guarded-by: _lock
        else:
            self._records = collections.deque(
                maxlen=capacity)                       # guarded-by: _lock
        self._seq = 0                                  # guarded-by: _lock
        self._flushed_seq = 0                          # guarded-by: _lock
        self._last_flush_s = 0.0                       # guarded-by: _lock
        self._registered = False                       # guarded-by: _lock
        # Stable per process incarnation.              # guarded-by: _lock
        self._log_name: Optional[str] = None
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock

    # -- recording (the hot path) ------------------------------------------

    def append(self, rec: Dict[str, Any]) -> None:
        """Append one record: atexit registration + log-name minting on
        first use, seq stamping when configured, overflow trim. The
        caller owns enablement/suppression checks — the Ring never
        reads the environment on the record path."""
        with self._lock:
            if not self._registered:
                atexit.register(self._flush_atexit)
                self._registered = True
            if self._log_name is None:
                self._log_name = self._name_fn()
            self._seq += 1
            if self._seq_field is not None:
                rec[self._seq_field] = self._seq
            self._records.append(rec)
            if self._halve and len(self._records) > self.capacity:
                del self._records[:self.capacity // 2]

    # -- introspection -----------------------------------------------------

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    @property
    def log_name(self) -> Optional[str]:
        with self._lock:
            return self._log_name

    # -- flushing ----------------------------------------------------------

    def flush(self) -> None:
        """Atomically rewrite this process's log file with the whole
        ring. Crash-safe and torn-read-safe: sibling temp file, then
        ``os.replace``; serialization runs outside the ring lock."""
        with self._lock:
            if not self._records or self._seq == self._flushed_seq:
                return
            seq_snapshot = self._seq
            # Snapshot only — serialization happens OUTSIDE the lock
            # so recorder threads never block on an O(ring) dumps.
            snapshot = list(self._records)
            name = self._log_name
        lines = [json.dumps(r, default=str) for r in snapshot]
        with self._flush_lock:
            with self._lock:
                if seq_snapshot <= self._flushed_seq:
                    return       # a newer flush already landed
            d = self._dir_fn()
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=name + ".")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    f.write("\n".join(lines) + "\n")
                os.replace(tmp, os.path.join(d, name))
                with self._lock:
                    self._flushed_seq = seq_snapshot
                    self._last_flush_s = time.monotonic()
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise

    def flush_periodic(self, min_new_records: int = 256,
                       max_age_s: Optional[float] = None) -> None:
        """Throttled :meth:`flush` for per-tick callers: every flush
        re-serializes the whole buffer, so flush only once enough
        records accumulated — or, when ``max_age_s`` is given, when
        the last flush went stale with anything pending."""
        with self._lock:
            if not self._records or self._seq == self._flushed_seq:
                return
            pending = self._seq - self._flushed_seq
            fresh = (max_age_s is None
                     or time.monotonic() - self._last_flush_s < max_age_s)
        if pending < min_new_records and fresh:
            return
        self.flush()

    def _flush_atexit(self) -> None:
        try:
            self.flush()
            if self._atexit_extra is not None:
                self._atexit_extra()
        except OSError:
            pass   # best-effort: exit must stay quiet on unwritable paths

    # -- the durability heartbeat ------------------------------------------

    def ensure_flush_thread(self, interval_s: float = 5.0,
                            min_new_records: int = 256,
                            max_age_s: Optional[float] = None) -> None:
        """Start (once) a daemon thread running :meth:`flush_periodic`
        every ``interval_s`` — durability for latency-critical loops
        without paying a whole-ring serialization inline."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            t = threading.Thread(
                target=self._flush_loop,
                args=(interval_s, min_new_records, max_age_s),
                name=self._thread_name, daemon=True)
            self._thread = t
        t.start()

    def _flush_loop(self, interval_s: float, min_new_records: int,
                    max_age_s: Optional[float]) -> None:
        while True:
            time.sleep(interval_s)
            try:
                self.flush_periodic(min_new_records=min_new_records,
                                    max_age_s=max_age_s)
            except OSError:
                pass   # unwritable events dir: keep trying quietly

    # -- tests -------------------------------------------------------------

    def reset_for_tests(self) -> None:
        """Drop the buffer and per-process log identity (tests only —
        a fresh tmp home must get a fresh log file)."""
        with self._lock:
            self._records.clear()
            self._seq = 0
            self._flushed_seq = 0
            self._log_name = None
