"""Engine flight recorder + compile watch: burst-level serving
introspection and the runtime retrace guard.

Two complementary pieces close the gap between the static retrace lint
(``skytpu lint`` promises the compiled-program surface is bounded) and
what actually happens on a live replica:

* :class:`FlightRecorder` — a bounded, lock-disciplined ring of
  per-burst records. Every device dispatch the serving engine makes
  (admission wave, prefill chunk, decode burst, speculative verify,
  single-step decode) appends ONE host-side record: which compiled
  program ran (span rung, bucket, draft K, KV layout), which slots and
  requests rode it, how long the host waited dispatch-to-fetch, how
  many tokens committed, and what block-management events (COW copies,
  prefix evictions, lazy grows) it caused. Recording is a dict append
  under a lock — ZERO device fetches — so when TPOT spikes in
  production the last thousands of bursts answer "which program ran
  this burst and did anything compile?" without re-running anything.

* :class:`CompileWatch` — a program registry keyed on
  ``(entry point, static args)`` wrapped around every jit entry point
  the engine dispatches. First dispatch of a new key records the
  trace+compile wall time (``skytpu_compile_seconds{program}``,
  ``skytpu_programs_compiled_total``); after the engine declares
  warmup complete, any NEW key is the silent mid-traffic XLA compile
  the whole static-shape design exists to prevent — it emits a typed
  ``engine.unexpected_compile`` event (``echo=True``) and increments
  ``skytpu_unexpected_compiles_total``, which the SLO watchdog alarms
  on (the ``unexpected-compiles`` default rule).

Records flush to per-process JSONL files (``flight-<proc>-<pid>-<ms>
.jsonl``) in the tracing events dir via the same atomic
tempfile+``os.replace`` idiom, so ``skytpu flight --local`` and
``skytpu trace <req>`` (burst records carry member requests' trace
ids) assemble them cross-process. Same design constraints as
``tracing.py``: stdlib + host-only on the record path, cheap when
idle, safe under concurrency, and a disabled recorder
(``SKYTPU_FLIGHT=0`` or ``recorder.enabled = False``) is a no-op
guard — the hot path pays one attribute check, exactly like
``metrics.suppress``.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.observability import _ringflush, metrics, tracing

COMPILE_SECONDS = metrics.histogram(
    "skytpu_compile_seconds",
    "First-dispatch wall time (trace + XLA compile) per engine program "
    "identity — jit compilation is synchronous at first call, so this "
    "is what a request stalled behind that dispatch experienced",
    labelnames=("program",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0))
PROGRAMS_COMPILED = metrics.counter(
    "skytpu_programs_compiled_total",
    "Engine programs compiled (distinct (entry point, static args) "
    "keys first-dispatched through the compile watch)")
UNEXPECTED_COMPILES = metrics.counter(
    "skytpu_unexpected_compiles_total",
    "Engine programs compiled AFTER warmup was declared complete — "
    "each one is a mid-traffic XLA compile stalling live requests; "
    "the retrace-safety invariant says this stays 0")

# Ring bound: at a production burst cadence (~100 bursts/s across
# groups) 8192 records is over a minute of history, and one flush
# serializes at most this many lines.
_MAX_RECORDS = 8192

_FILE_PREFIX = "flight-"


def enabled() -> bool:
    """The flight recorder is on unless explicitly disabled
    (``SKYTPU_FLIGHT=0``)."""
    return os.environ.get("SKYTPU_FLIGHT", "1") != "0"


class FlightRecorder:
    """Bounded ring of per-burst flight records.

    One recorder per process is the normal shape (:data:`RECORDER`);
    engines take an injectable instance so tests and the bench can
    observe an isolated window. ``enabled`` is a plain attribute the
    owner may flip at runtime — a disabled recorder's :meth:`record`
    returns before touching the lock (the recorder-off no-op guard).
    """

    def __init__(self, capacity: int = _MAX_RECORDS,
                 file_prefix: str = _FILE_PREFIX):
        self.enabled = enabled()
        self.capacity = capacity
        self._ring = _ringflush.Ring(
            capacity,
            lambda: (f"{file_prefix}{tracing.process_name()}"
                     f"-{os.getpid()}-{int(time.time() * 1000)}.jsonl"),
            tracing.events_dir, seq_field="seq",
            thread_name="flight-flush")

    # -- recording (the hot path) ------------------------------------------

    def record(self, burst: str, **fields: Any) -> None:
        """Append one burst record. Host-side values ONLY — the engine
        hands in host bookkeeping (lists, ints, floats), never device
        arrays; fetching one here would stall the dispatch pipeline
        the recorder exists to observe. Honors :func:`metrics.suppress`
        (warmup work must not pollute the ring either)."""
        if not self.enabled or metrics.suppressed():
            return
        rec: Dict[str, Any] = {
            "kind": "flight", "burst": burst, "pid": os.getpid(),
            "proc": tracing.process_name(),
        }
        rec.update(fields)
        self._ring.append(rec)

    # -- introspection -----------------------------------------------------

    def seq(self) -> int:
        return self._ring.seq()

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Snapshot of the newest ``n`` records (all when None),
        oldest first."""
        recs = self._ring.snapshot()
        return recs[-n:] if n else recs

    def since(self, seq: int) -> List[Dict[str, Any]]:
        """Records appended after sequence number ``seq`` that are
        still in the ring (tests/bench window over the shared ring)."""
        return [r for r in self._ring.snapshot() if r["seq"] > seq]

    # -- flushing (the shared _ringflush atomic-replace idiom) -------------

    def flush(self) -> None:
        """Atomically rewrite this process's flight log with the whole
        ring. Serialization happens OUTSIDE the ring lock so recorder
        callers (the engine loop) never block on an O(ring) dumps."""
        self._ring.flush()

    def flush_periodic(self, min_new_records: int = 256) -> None:
        self._ring.flush_periodic(min_new_records=min_new_records)

    def ensure_flush_thread(self, interval_s: float = 5.0) -> None:
        """Start (once) a daemon thread flushing this recorder
        periodically — durability off the owner's hot loop."""
        self._ring.ensure_flush_thread(interval_s, min_new_records=256)

    def _reset_for_tests(self) -> None:
        self._ring.reset_for_tests()


RECORDER = FlightRecorder()


def ensure_flush_thread(interval_s: float = 5.0) -> None:
    """Start (once) a daemon thread flushing :data:`RECORDER`
    periodically — the model server's durability heartbeat, off the
    serving loop (same rationale as tracing.ensure_flush_thread)."""
    RECORDER.ensure_flush_thread(interval_s)


# ---------------------------------------------------------------------------
# Compile watch.

class CompileWatch:
    """Program registry over the engine's jit entry points.

    :meth:`wrap` returns a transparent wrapper that derives a program
    KEY from the call's static arguments (plus an optional ``key_fn``
    for shape-derived identity, e.g. the admission wave's row count —
    jit recompiles on new shapes even under an unchanged static key).
    A key's first dispatch is where jit traces and compiles
    SYNCHRONOUSLY, so that call's wall time is the compile cost a
    stalled request experienced; it lands in
    ``skytpu_compile_seconds{program}``. After :meth:`declare_warm`,
    a new key is a mid-traffic compile: typed
    ``engine.unexpected_compile`` event + counter.

    One watch per engine: program identity is engine-scoped (two
    engines in one process legitimately compile the same key twice).
    ``event_name`` is the typed event a post-warm compile emits — the
    serving engines keep the default ``engine.unexpected_compile``;
    the trainer's own watch emits ``train.unexpected_compile`` so the
    two alarm surfaces stay distinguishable in the event log.
    """

    def __init__(self, event_name: str = "engine.unexpected_compile"):
        self.event_name = event_name
        self._lock = threading.Lock()
        self._programs: Dict[str, float] = {}    # guarded-by: _lock
        self._unexpected: List[str] = []         # guarded-by: _lock
        self._new: List[str] = []                # guarded-by: _lock
        self._warm = False                       # guarded-by: _lock
        # Device-time calibration (attribution.DeviceTimeCalibrator,
        # attached by the engine): every Nth HIT dispatch of a key is
        # routed through the calibrator's timed bracket, maintaining
        # the per-program device-seconds EWMA. None = no calibration.
        self.calibrator = None
        # Key of the most recent dispatch through any wrapper. Loop-
        # thread discipline (the engine reads it right after the
        # dispatch it made), so a plain attribute suffices.
        self.last_key: Optional[str] = None

    def wrap(self, name: str, fn: Callable,
             static_argnames: Sequence[str] = (),
             key_fn: Optional[Callable[[tuple, dict],
                                       Sequence[Tuple[str, Any]]]]
             = None) -> Callable:
        def wrapped(*args, **kwargs):
            parts = [f"{a}={kwargs[a]}" for a in static_argnames
                     if a in kwargs]
            if key_fn is not None:
                parts.extend(f"{k}={v}" for k, v in key_fn(args, kwargs))
            key = name + (f"[{' '.join(parts)}]" if parts else "")
            self.last_key = key
            with self._lock:
                hit = key in self._programs
            if hit:
                # Sampled device-time calibration rides the HIT path
                # only: the first dispatch is the compile, whose wall
                # would poison a pure-execution EWMA.
                cal = self.calibrator
                if cal is not None and cal.tick(key):
                    return cal.timed_call(key, fn, *args, **kwargs)
                return fn(*args, **kwargs)
            t0 = time.monotonic()
            out = fn(*args, **kwargs)
            dt = time.monotonic() - t0
            with self._lock:
                if key in self._programs:    # racing first dispatches
                    return out
                self._programs[key] = dt
                self._new.append(key)
                warm = self._warm
                if warm:
                    self._unexpected.append(key)
            COMPILE_SECONDS.labels(program=key).observe(dt)
            PROGRAMS_COMPILED.inc()
            if warm:
                UNEXPECTED_COMPILES.inc()
                tracing.add_event(
                    self.event_name,
                    {"program": key, "compile_s": round(dt, 4)},
                    echo=True)
            return out
        return wrapped

    # -- warmup state ------------------------------------------------------

    def declare_warm(self) -> None:
        """The owner believes every program the live workload can
        reach is compiled; from here on a new key is an alarm."""
        with self._lock:
            self._warm = True

    @property
    def warm(self) -> bool:
        with self._lock:
            return self._warm

    # -- introspection -----------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._programs)

    @property
    def unexpected(self) -> List[str]:
        with self._lock:
            return list(self._unexpected)

    def drain_new(self) -> List[str]:
        """Keys compiled since the last drain — the engine attaches
        them to the flight record of the burst that paid for them."""
        with self._lock:
            new, self._new = self._new, []
        return new

    def summary(self) -> Dict[str, float]:
        """``{program key: first-dispatch wall seconds}``."""
        with self._lock:
            return dict(self._programs)

    def total_compile_s(self) -> float:
        with self._lock:
            return sum(self._programs.values())


# ---------------------------------------------------------------------------
# Loading + rendering (skytpu flight, /debug/flight consumers).

def load_records(dirs: Optional[List[str]] = None,
                 n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Flight records from every flushed per-process log under the
    event-log search dirs, oldest first by (ts, seq). Corrupt lines
    (crash mid-line predates the atomic flush; foreign files) are
    skipped, never fatal."""
    from skypilot_tpu.observability import trace_view
    records: List[Dict[str, Any]] = []
    for d in (dirs if dirs is not None else trace_view.search_dirs()):
        for path in sorted(glob.glob(
                os.path.join(d, _FILE_PREFIX + "*.jsonl"))):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if (isinstance(rec, dict)
                                and rec.get("kind") == "flight"):
                            records.append(rec)
            except OSError:
                continue
    records.sort(key=lambda r: (r.get("ts_s", 0.0), r.get("seq", 0)))
    return records[-n:] if n else records


def program_label(rec: Dict[str, Any]) -> str:
    """Compact program-identity string for one record, e.g.
    ``decode[k=8 span=256 paged]``."""
    prog = rec.get("program") or {}
    parts = [f"{k}={prog[k]}" for k in sorted(prog) if k != "layout"]
    layout = prog.get("layout")
    if layout:
        parts.append(str(layout))
    inner = " ".join(parts)
    return f"{rec.get('burst', '?')}[{inner}]" if inner \
        else str(rec.get("burst", "?"))


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per-program rollup over a record set: count, tokens committed,
    mean/max host dispatch-to-fetch wall, spec drafted/accepted."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in records:
        agg = out.setdefault(program_label(r), {
            "count": 0, "toks": 0, "total_s": 0.0, "max_s": 0.0,
            "drafted": 0, "accepted": 0, "compiled": 0,
            "dev_s": 0.0, "dev_samples": 0, "flops": 0,
            "hbm_bytes": 0})
        dur = max(float(r.get("dur_s", 0.0)), 0.0)
        agg["count"] += 1
        agg["toks"] += int(r.get("toks", 0))
        agg["total_s"] += dur
        agg["max_s"] = max(agg["max_s"], dur)
        agg["drafted"] += int(r.get("drafted", 0))
        agg["accepted"] += int(r.get("accepted", 0))
        agg["compiled"] += len(r.get("compiled", ()))
        # Device-truth attribution (calibrated estimates + analytical
        # roofline inputs); records predating the attribution layer
        # simply contribute nothing.
        if r.get("dev_ms_est") is not None:
            agg["dev_s"] += float(r["dev_ms_est"]) / 1e3
            agg["dev_samples"] += 1
        agg["flops"] += int(r.get("flops", 0))
        agg["hbm_bytes"] += int(r.get("hbm_bytes", 0))
    for agg in out.values():
        agg["mean_ms"] = round(agg["total_s"] / agg["count"] * 1e3, 3)
        agg["max_s"] = round(agg["max_s"], 6)
        agg["total_s"] = round(agg["total_s"], 6)
        agg["dev_ms"] = (round(agg["dev_s"] / agg["dev_samples"] * 1e3,
                               3)
                         if agg["dev_samples"] else None)
        agg["dev_s"] = round(agg["dev_s"], 6)
    return out


def render_table(records: List[Dict[str, Any]],
                 programs: Optional[Dict[str, float]] = None,
                 last: int = 32) -> str:
    """Human view: the last-N bursts table plus the per-program
    summary (and, when a compile-watch summary is supplied, each
    program's first-dispatch compile cost)."""
    if not records:
        return "no flight records (recorder off, or nothing flushed yet)"
    lines: List[str] = []
    shown = records[-last:]
    t0 = shown[0].get("ts_s", 0.0)
    lines.append(f"last {len(shown)} of {len(records)} bursts:")
    fmt = "{:>9}  {:<34} {:>5} {:>5} {:>9} {:>8}  {}"
    lines.append(fmt.format("T+MS", "PROGRAM", "SLOTS", "TOKS",
                            "HOST-MS", "DEV-MS", "FLAGS"))
    for r in shown:
        flags = []
        if r.get("stall"):
            flags.append("stall")
        if r.get("drafted"):
            flags.append(f"spec {r.get('accepted', 0)}"
                         f"/{r.get('drafted', 0)}")
        # Drafter attribution (PR 14): which drafter kind fed a verify
        # burst (model|ngram|mixed — "draft" records ARE the pipelined
        # predraft dispatch), and how much host wall the round spent
        # dispatching next-round draft work inside the verify's
        # dispatch->fetch window — draft and verify render as
        # OVERLAPPING spans under --perfetto, not a serial chain.
        if r.get("drafter"):
            flags.append(f"drafter={r['drafter']}")
        if r.get("overlap_ms"):
            flags.append(f"overlap={r['overlap_ms']:.2f}ms")
        for k in ("cow", "evictions", "lazy_grows"):
            if r.get(k):
                flags.append(f"{k}={r[k]}")
        # QoS attribution (engine bursts carry tenant composition when
        # a fair scheduler is installed; "preempt" records carry the
        # victim's lane): only non-default make-ups earn a flag.
        tenants = r.get("tenants") or {}
        if tenants and (len(tenants) > 1
                        or next(iter(tenants)) != "default"):
            flags.append("tenants=" + ",".join(
                f"{t}:{n}" for t, n in sorted(tenants.items())))
        # Adapter-catalog composition: which fine-tunes shared this
        # dispatch (base-model members carry no entry).
        ads = r.get("adapters") or {}
        if ads:
            flags.append("adapters=" + ",".join(
                f"{a}:{n}" for a, n in sorted(ads.items())))
        if r.get("burst") == "preempt":
            flags.append(f"prio={r.get('priority', 0)} "
                         f"retired={r.get('retired_rows', 0)}")
        if r.get("compiled"):
            flags.append(f"COMPILED={len(r['compiled'])}")
        dev = r.get("dev_ms_est")
        lines.append(fmt.format(
            f"+{(r.get('ts_s', t0) - t0) * 1e3:.1f}",
            program_label(r)[:34],
            len(r.get("slots", ())), r.get("toks", 0),
            f"{float(r.get('dur_s', 0.0)) * 1e3:.2f}",
            f"{float(dev):.2f}" if dev is not None else "-",
            " ".join(flags)))
    lines.append("")
    lines.append("per-program summary:")
    fmt2 = "{:<40} {:>6} {:>8} {:>9} {:>8} {:>9}  {}"
    lines.append(fmt2.format("PROGRAM", "BURSTS", "TOKS", "MEAN-MS",
                             "DEV-MS", "MAX-MS", "SPEC"))
    for label, agg in sorted(summarize(records).items()):
        spec = (f"{agg['accepted']}/{agg['drafted']}"
                if agg["drafted"] else "-")
        lines.append(fmt2.format(
            label[:40], agg["count"], agg["toks"], agg["mean_ms"],
            agg["dev_ms"] if agg["dev_ms"] is not None else "-",
            round(agg["max_s"] * 1e3, 3), spec))
    if programs:
        lines.append("")
        lines.append("compiled programs (first-dispatch wall):")
        for key in sorted(programs):
            lines.append(f"  {key:<44} {programs[key] * 1e3:9.1f}ms")
    return "\n".join(lines)


def as_spans(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Flight records reshaped as span records so
    ``trace_view.to_perfetto`` renders them as duration tracks
    (``skytpu flight --perfetto``)."""
    spans = []
    for r in records:
        ts = float(r.get("ts_s", 0.0))
        attrs = {k: r[k] for k in ("toks", "drafted", "accepted",
                                   "stall", "rids", "tenants",
                                   "adapters", "priority",
                                   "retired_rows", "drafter",
                                   "overlap_ms", "dev_ms_est",
                                   "dispatch_wall_ms",
                                   "fetch_wall_ms", "flops",
                                   "hbm_bytes")
                 if r.get(k)}
        attrs["slots"] = len(r.get("slots", ()))
        spans.append({
            "kind": "span", "name": program_label(r),
            "start_s": ts, "end_s": ts + float(r.get("dur_s", 0.0)),
            "pid": r.get("pid", 0), "tid": r.get("pid", 0),
            "proc": r.get("proc", "?"), "attrs": attrs,
        })
    return spans
