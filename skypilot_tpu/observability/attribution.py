"""Device-truth performance attribution: per-program device timing,
the HBM ledger, roofline FLOPs/bytes accounting, and bubble analysis.

Every observability layer before this one measured HOST wall time: a
flight record's dispatch->fetch window conflates device execution with
host scheduling, and under the async draft/verify pipeline that
conflation is structural. This module closes the host/device gap on
three axes, all host-side except one deliberate, sampled sync:

* :class:`DeviceTimeCalibrator` — a sampled calibration pass. Every
  Nth dispatch of a program identity (``SKYTPU_DEVTIME_EVERY``, 0 =
  off) is timed synchronously: dispatch -> ``block_until_ready``
  bracket, maintaining an EWMA of pure device seconds per program key
  in the compile-watch registry. Flight records then carry
  ``dev_ms_est`` (the EWMA at record time) next to host wall, so
  ``skytpu flight`` and the perfetto export render host-vs-device per
  burst and pipeline overlap becomes measured-calibrated instead of
  inferred. The bracket is the ONE sanctioned host sync of the
  attribution layer — it rides the lint baseline exactly like the
  engine's completion fetches, and at the default sampling rate its
  cost amortizes below the flight recorder's own overhead gate.

* :class:`HbmLedger` — analytical byte accounting of every
  device-resident tensor family (weights, KV pool + scales, draft
  pool, adapter pool, prefix-pinned blocks, workspace estimate),
  published as ``skytpu_hbm_bytes{component}`` gauges and
  cross-checked against ``device.memory_stats()`` where the backend
  provides one (CPU does not: typed ``attribution.memstats_
  unavailable`` event once, then analytical-only — never a crash, and
  never a zero gauge masquerading as truth). The ``hbm-headroom`` SLO
  rule alarms on ledger-total vs limit before the next admission
  would OOM.

* :class:`Roofline` + :func:`analyze_bubbles` — analytical FLOPs and
  HBM bytes per program identity (rows, span rung, K, bucket — all
  already in the record schema) turn each flight record into
  achieved-vs-roofline attribution; the counters feed the windowed
  serving MFU / bandwidth-utilization columns on ``skytpu top``, and
  the bubble analyzer attributes inter-dispatch device-idle gaps to
  named host causes (admission, qos_reorder, drafter_sync, stall,
  dispatch_overhead).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import metrics, tracing

DEVICE_FLOPS = metrics.counter(
    "skytpu_device_flops_total",
    "Analytical FLOPs dispatched to the device, accumulated per flight "
    "record from the roofline model — rate / skytpu_roofline_peak_flops "
    "is the windowed serving MFU on skytpu top")
DEVICE_HBM_MOVED = metrics.counter(
    "skytpu_device_hbm_moved_bytes_total",
    "Analytical HBM bytes moved (weight streams + KV reads/writes) "
    "accumulated per flight record — rate / "
    "skytpu_roofline_peak_hbm_bytes_per_s is bandwidth utilization")
DEVICE_SECONDS = metrics.counter(
    "skytpu_device_seconds_total",
    "Estimated pure device-busy seconds (calibrated EWMA per program, "
    "accumulated per flight record) — the device-truth numerator the "
    "host-wall histograms cannot provide")
DEVTIME_CALIBRATIONS = metrics.counter(
    "skytpu_devtime_calibrations_total",
    "Sampled device-time calibration brackets taken (each is one "
    "deliberate dispatch->block_until_ready sync)")
DEVTIME_EWMA_MS = metrics.gauge(
    "skytpu_devtime_ewma_ms",
    "Calibrated EWMA of pure device milliseconds per compiled program "
    "identity",
    labelnames=("program",))
HBM_BYTES = metrics.gauge(
    "skytpu_hbm_bytes",
    "Analytical HBM ledger: bytes each device-resident tensor family "
    "holds (weights, kv_pool, kv_used, draft_pool, adapter_pool, "
    "prefix_pinned, workspace)",
    labelnames=("component",))
HBM_LIMIT = metrics.gauge(
    "skytpu_hbm_limit_bytes",
    "Device HBM capacity the ledger is checked against "
    "(device.memory_stats bytes_limit when the backend reports one, "
    "else SKYTPU_HBM_LIMIT_BYTES)")
HBM_DEVICE_IN_USE = metrics.gauge(
    "skytpu_hbm_device_bytes_in_use",
    "device.memory_stats() bytes_in_use — the runtime's own view, "
    "published only when the backend reports it (the analytical "
    "ledger's cross-check)")
ROOFLINE_PEAK_FLOPS = metrics.gauge(
    "skytpu_roofline_peak_flops",
    "Peak device FLOP/s the MFU column divides by "
    "(SKYTPU_PEAK_TFLOPS, else a device-kind table, else a CPU "
    "placeholder)")
ROOFLINE_PEAK_BW = metrics.gauge(
    "skytpu_roofline_peak_hbm_bytes_per_s",
    "Peak HBM bandwidth (bytes/s) the bandwidth-utilization column "
    "divides by (SKYTPU_PEAK_GBPS, else a device-kind table)")

# Peak FLOP/s (bf16) and HBM bytes/s per device kind — the roofline
# denominators. Matched by substring against jax device_kind; the CPU
# fallback is a deliberately modest placeholder so MFU stays a
# meaningful nonzero ratio in tests and local runs.
_PEAKS: Dict[str, tuple] = {
    "v6e": (918e12, 1638e9),
    "v5p": (459e12, 2765e9),
    "v5e": (394e12, 819e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
    "cpu": (0.5e12, 50e9),
}


def devtime_every(default: int = 64) -> int:
    """Calibration sampling period (bursts per program key between
    brackets). ``SKYTPU_DEVTIME_EVERY=0`` disables calibration."""
    try:
        return int(os.environ.get("SKYTPU_DEVTIME_EVERY", str(default))
                   or 0)
    except ValueError:
        return default


def device_peaks(device=None) -> tuple:
    """(peak FLOP/s, peak HBM bytes/s) for the local device, env
    overrides first (SKYTPU_PEAK_TFLOPS / SKYTPU_PEAK_GBPS)."""
    kind = ""
    if device is not None:
        kind = str(getattr(device, "device_kind", "")).lower()
    else:
        try:
            import jax
            kind = str(getattr(jax.devices()[0], "device_kind",
                               "")).lower()
        except Exception:              # no backend at all: placeholder
            kind = "cpu"
    flops, bw = _PEAKS["cpu"]
    for k, peaks in _PEAKS.items():
        if k in kind:
            flops, bw = peaks
            break
    env_f = os.environ.get("SKYTPU_PEAK_TFLOPS")
    if env_f:
        try:
            flops = float(env_f) * 1e12
        except ValueError:
            pass
    env_b = os.environ.get("SKYTPU_PEAK_GBPS")
    if env_b:
        try:
            bw = float(env_b) * 1e9
        except ValueError:
            pass
    return flops, bw


def tensor_bytes(tree: Any) -> int:
    """Total ``nbytes`` over a pytree of arrays — metadata reads only,
    never a device fetch (``nbytes`` is shape x itemsize)."""
    if tree is None:
        return 0
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


# ---------------------------------------------------------------------------
# (a) Per-program device timing: the sampled calibration pass.

class DeviceTimeCalibrator:
    """EWMA of pure device seconds per compiled-program identity.

    Attached to a :class:`~skypilot_tpu.observability.flight.
    CompileWatch` (``watch.calibrator = cal``): the watch's hit path
    asks :meth:`tick` whether THIS dispatch of the key should be the
    sampled one and, when it is, routes through :meth:`timed_call` —
    the dispatch -> ``block_until_ready`` bracket that turns one burst
    per key per period into a device-truth sample. Everything else is
    lock-guarded host dicts.

    Staleness bound: a key redispatched every burst is recalibrated
    every ``every`` bursts, so the EWMA (alpha 0.25) lags a step
    change by ~4*every bursts; :meth:`summary` reports each key's
    ``age_s`` so consumers can see exactly how stale an estimate is.
    """

    def __init__(self, every: Optional[int] = None, alpha: float = 0.25):
        self._every = every          # None: read the env per tick
        self.alpha = alpha
        self._lock = threading.Lock()
        self._ewma: Dict[str, float] = {}      # guarded-by: _lock
        self._counts: Dict[str, int] = {}      # guarded-by: _lock
        self._stamp: Dict[str, float] = {}     # guarded-by: _lock
        self.samples = 0                       # guarded-by: _lock

    @property
    def every(self) -> int:
        return self._every if self._every is not None else devtime_every()

    def tick(self, key: str) -> bool:
        """Count one dispatch of ``key``; True when this one should be
        calibration-timed (the first post-compile dispatch, then every
        ``every``-th). Suppressed contexts (warmup sweeps) never
        sample — a warm-grid bracket would serialize the sweep."""
        n = self.every
        if n <= 0 or metrics.suppressed():
            return False
        with self._lock:
            c = self._counts.get(key, 0) + 1
            self._counts[key] = c
        return c % n == 1 or n == 1

    def timed_call(self, key: str, fn, *args, **kwargs):
        """The calibration bracket: one synchronous dispatch of ``fn``
        timed to completion. Deliberate host sync — the ONE the
        attribution layer owns (lint-baselined); everything downstream
        of the returned arrays is already materialized, so the caller's
        own fetch is then free."""
        import jax
        t0 = time.monotonic()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        dt = time.monotonic() - t0
        self.update(key, dt)
        return out

    def update(self, key: str, dev_s: float) -> None:
        dev_s = max(float(dev_s), 0.0)
        with self._lock:
            prev = self._ewma.get(key)
            cur = (dev_s if prev is None
                   else prev + self.alpha * (dev_s - prev))
            self._ewma[key] = cur
            self._stamp[key] = time.monotonic()
            self.samples += 1
        DEVTIME_CALIBRATIONS.inc()
        DEVTIME_EWMA_MS.labels(program=key).set(cur * 1e3)

    def estimate(self, key: Optional[str]) -> Optional[float]:
        """Calibrated device seconds for one program key (None when the
        key has never been bracketed)."""
        if key is None:
            return None
        with self._lock:
            return self._ewma.get(key)

    def summary(self) -> Dict[str, Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return {
                k: {"dev_ms": round(v * 1e3, 4),
                    "samples": self._counts.get(k, 0),
                    "age_s": round(now - self._stamp.get(k, now), 3)}
                for k, v in sorted(self._ewma.items())
            }


# ---------------------------------------------------------------------------
# (b) The HBM ledger.

class HbmLedger:
    """Analytical byte accounting of device-resident tensor families.

    ``set_bytes`` is absolute (the owner recomputes each component
    from its own authoritative host bookkeeping — allocator block
    counts, prefix payloads, array nbytes), so the ledger can never
    drift from the structures it mirrors: a leak in the ledger IS a
    leak in the structure. Publishing happens inline through the
    ``skytpu_hbm_bytes{component}`` gauge.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._components: Dict[str, int] = {}   # guarded-by: _lock
        self._memstats_warned = False           # guarded-by: _lock

    def set_bytes(self, component: str, n: int) -> None:
        n = max(int(n), 0)
        with self._lock:
            self._components[component] = n
        HBM_BYTES.labels(component=component).set(n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._components)

    def total(self) -> int:
        with self._lock:
            return sum(self._components.values())

    def clear(self) -> None:
        with self._lock:
            comps = list(self._components)
            self._components.clear()
        for c in comps:
            HBM_BYTES.labels(component=c).set(0)

    def set_limit(self, n: int) -> None:
        HBM_LIMIT.set(max(int(n), 0))

    def cross_check(self, device=None) -> Optional[Dict[str, int]]:
        """The runtime's own view: ``device.memory_stats()`` where the
        backend provides one. Publishes bytes_in_use (and bytes_limit
        when reported) and returns the stats; on CPU / missing backend
        support it emits ``attribution.memstats_unavailable`` ONCE and
        returns None — the analytical ledger stays the only truth, and
        no zero gauge ever masquerades as a measurement."""
        stats = None
        try:
            if device is None:
                import jax
                device = jax.devices()[0]
            ms = getattr(device, "memory_stats", None)
            stats = ms() if callable(ms) else None
        except Exception:
            stats = None
        if not isinstance(stats, dict) or "bytes_in_use" not in stats:
            with self._lock:
                warned, self._memstats_warned = self._memstats_warned, True
            if not warned:
                tracing.add_event(
                    "attribution.memstats_unavailable",
                    {"platform": str(getattr(device, "platform",
                                             "unknown")),
                     "fallback": "analytical_ledger_only"})
            return None
        out = {"bytes_in_use": int(stats["bytes_in_use"])}
        HBM_DEVICE_IN_USE.set(out["bytes_in_use"])
        limit = stats.get("bytes_limit")
        if limit:
            out["bytes_limit"] = int(limit)
            self.set_limit(int(limit))
        return out


# ---------------------------------------------------------------------------
# (c) Roofline FLOPs / bytes per program identity.

class Roofline:
    """Analytical cost model over the engine's burst kinds.

    Built from the serving model's dims plus the engine's ACTUAL
    resident byte counts (quantized weights count at their quantized
    size; int8 KV counts its scales). Every input a record needs is
    already in the record schema — rows, span rung, K, bucket — so
    record cost is pure host arithmetic at record time.

    Formulas (P = param count, d = d_model, L = layers, nh/hd =
    heads/head_dim, W = weight bytes, kvt = KV bytes per token):

    * matmul FLOPs   = 2 * P * tokens_computed
    * attn FLOPs     = 4 * L * nh * hd * span * tokens_computed
    * bytes moved    = passes * W  +  passes * rows * span * kvt
                       + tokens_written * kvt
      where ``passes`` is how many times the program streams the
      weights (decode burst: k sequential steps; wave/chunk/verify:
      one forward).
    """

    def __init__(self, *, param_count: int, weight_bytes: int,
                 kv_token_bytes: int, d_model: int, n_layers: int,
                 n_heads: int, head_dim: int, max_len: int,
                 chunk_tokens: Optional[int] = None):
        self.param_count = int(param_count)
        self.weight_bytes = int(weight_bytes)
        self.kv_token_bytes = int(kv_token_bytes)
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.head_dim = head_dim
        self.max_len = max_len
        self.chunk_tokens = chunk_tokens or 0

    def _attn_flops(self, tokens: int, span: int) -> int:
        return 4 * self.n_layers * self.n_heads * self.head_dim \
            * int(span) * int(tokens)

    def _cost(self, tokens: int, rows: int, span: int,
              passes: int = 1) -> tuple:
        flops = 2 * self.param_count * tokens \
            + self._attn_flops(tokens, span)
        moved = passes * self.weight_bytes \
            + passes * rows * int(span) * self.kv_token_bytes \
            + tokens * self.kv_token_bytes
        return int(flops), int(moved)

    def record_cost(self, burst: str, program: Dict[str, Any],
                    n_slots: int, toks: int) -> tuple:
        """(FLOPs, HBM bytes) DISPATCHED for one flight record — the
        work the device was asked for (a decode burst costs its full
        k x rows grid even when a retirement discards the tail),
        which is what achieved-vs-roofline must charge."""
        prog = program or {}
        rows = max(int(n_slots), 1)
        span = int(prog.get("span") or self.max_len)
        if burst == "wave":
            rows = int(prog.get("rows") or rows)
            bucket = int(prog.get("bucket") or self.max_len)
            # Causal prefill: the average key span of a bucket-wide
            # prompt is bucket/2.
            return self._cost(rows * bucket, rows,
                              max(bucket // 2, 1))
        if burst == "chunk":
            c = self.chunk_tokens or span
            return self._cost(c, 1, span)
        if burst == "decode":
            k = int(prog.get("k") or 1)
            return self._cost(k * rows, rows, span, passes=k)
        if burst == "decode1":
            return self._cost(rows, rows, span)
        if burst == "verify":
            k = int(prog.get("k") or 1)
            return self._cost((k + 1) * rows, rows, span)
        if burst == "draft":
            # The draft model's pipelined rollout: k sequential steps
            # per row, exactly a decode burst — the caller passes the
            # Roofline built on the DRAFT config.
            k = int(prog.get("k") or 1)
            return self._cost(k * rows, rows, span, passes=k)
        return 0, 0


# ---------------------------------------------------------------------------
# Bubble analysis: where the serving loop left the device idle.

# Every cause the analyzer can name. ``host_other`` is the residue —
# the acceptance bar (>= 90% attributed) counts everything above it.
BUBBLE_CAUSES = ("admission", "qos_reorder", "drafter_sync", "stall",
                 "dispatch_overhead", "host_other")

# Gaps below this are dispatch jitter, not bubbles worth a span.
_MIN_GAP_MS = 0.01


def _gap_cause(prev: Dict[str, Any], nxt: Dict[str, Any]) -> str:
    """Name the host-side cause of a device-idle gap between two
    consecutive flight records."""
    if prev.get("burst") == "preempt" or nxt.get("burst") == "preempt" \
            or nxt.get("priorities") or prev.get("priorities"):
        return "qos_reorder"
    if nxt.get("burst") in ("wave", "chunk"):
        # The host was assembling prompts / claiming blocks /
        # running admission before this dispatch.
        return "admission"
    if nxt.get("burst") in ("verify", "draft") \
            or prev.get("burst") in ("draft", "verify"):
        # Host drafting (n-gram walks, draft batch assembly, predraft
        # reconcile) between device dispatches of the spec path.
        return "drafter_sync"
    if nxt.get("stall") or prev.get("stall"):
        return "stall"
    if nxt.get("burst") in ("decode", "decode1") \
            and prev.get("burst") in ("decode", "decode1", "wave",
                                      "chunk"):
        # Steady-state decode chaining: the gap is host bookkeeping
        # (token append/retire/stream framing) between bursts.
        return "dispatch_overhead"
    return "host_other"


def analyze_bubbles(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Attribute device-idle time over a window of flight records.

    Two idle populations: gaps BETWEEN consecutive records (the host
    ran admission/drafting/QoS/bookkeeping with nothing dispatched)
    and the slack WITHIN a record (host wall beyond the calibrated
    ``dev_ms_est`` — dispatch overhead plus the completion fetch wait
    after the device finished). Returns totals, a per-cause
    breakdown, the individual bubbles (for the perfetto export), and
    ``coverage`` — the fraction of idle attributed to a cause other
    than ``host_other``.
    """
    recs = sorted((r for r in records
                   if r.get("kind") == "flight" and "ts_s" in r),
                  key=lambda r: (r.get("ts_s", 0.0), r.get("seq", 0)))
    out: Dict[str, Any] = {
        "n_records": len(recs), "window_ms": 0.0,
        "device_busy_ms": 0.0, "device_idle_ms": 0.0,
        "by_cause": {}, "bubbles": [], "coverage": 1.0,
    }
    if len(recs) < 2:
        return out
    by_cause: Dict[str, float] = {}
    bubbles: List[Dict[str, Any]] = []
    busy = 0.0
    prev_end = float(recs[0].get("ts_s", 0.0))
    prev = None
    for r in recs:
        ts = float(r.get("ts_s", 0.0))
        dur_ms = max(float(r.get("dur_s", 0.0)), 0.0) * 1e3
        dev_ms = r.get("dev_ms_est")
        dev_ms = (min(float(dev_ms), dur_ms) if dev_ms is not None
                  else dur_ms)
        busy += dev_ms
        if prev is not None:
            gap_ms = (ts - prev_end) * 1e3
            if gap_ms > _MIN_GAP_MS:
                cause = _gap_cause(prev, r)
                by_cause[cause] = by_cause.get(cause, 0.0) + gap_ms
                bubbles.append({
                    "start_s": prev_end, "end_s": ts,
                    "gap_ms": round(gap_ms, 4), "cause": cause,
                    "next": r.get("burst"), "pid": r.get("pid", 0),
                    "proc": r.get("proc", "?"),
                })
        # Within-record slack: host wall past the device estimate is
        # device idle spent in dispatch overhead + the fetch wait.
        slack = dur_ms - dev_ms
        if slack > _MIN_GAP_MS:
            by_cause["dispatch_overhead"] = \
                by_cause.get("dispatch_overhead", 0.0) + slack
        prev_end = max(prev_end, ts + dur_ms / 1e3)
        prev = r
    first = float(recs[0].get("ts_s", 0.0))
    window_ms = max((prev_end - first) * 1e3, 0.0)
    idle = sum(by_cause.values())
    named = idle - by_cause.get("host_other", 0.0)
    out.update({
        "window_ms": round(window_ms, 3),
        "device_busy_ms": round(busy, 3),
        "device_idle_ms": round(idle, 3),
        "by_cause": {c: round(v, 3)
                     for c, v in sorted(by_cause.items(),
                                        key=lambda kv: -kv[1])},
        "bubbles": bubbles,
        "coverage": round(named / idle, 4) if idle > 0 else 1.0,
    })
    return out


def idle_spans(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Inter-dispatch bubbles reshaped as synthetic spans so the
    perfetto export renders device-idle gaps as named tracks next to
    the burst spans."""
    report = analyze_bubbles(records)
    return [{
        "kind": "span", "name": f"bubble:{b['cause']}",
        "start_s": b["start_s"], "end_s": b["end_s"],
        "pid": b["pid"], "tid": b["pid"], "proc": b["proc"],
        "attrs": {"gap_ms": b["gap_ms"], "next": b["next"]},
    } for b in report["bubbles"]]


def render_bubbles(report: Dict[str, Any], last: int = 16) -> str:
    """Human view of a bubble report (``skytpu flight --bubbles``)."""
    lines = [
        f"window {report['window_ms']:.1f}ms over "
        f"{report['n_records']} records: device busy "
        f"{report['device_busy_ms']:.1f}ms, idle "
        f"{report['device_idle_ms']:.1f}ms "
        f"({report['coverage'] * 100:.1f}% of idle attributed)",
    ]
    if report["by_cause"]:
        lines.append("")
        lines.append("idle by cause:")
        total = max(report["device_idle_ms"], 1e-9)
        for cause, ms in report["by_cause"].items():
            lines.append(f"  {cause:<20} {ms:>9.2f}ms "
                         f"{ms / total * 100:>5.1f}%")
    biggest = sorted(report["bubbles"], key=lambda b: -b["gap_ms"])
    if biggest:
        lines.append("")
        lines.append(f"largest bubbles (top {min(last, len(biggest))}):")
        for b in biggest[:last]:
            lines.append(f"  +{b['gap_ms']:>8.2f}ms  {b['cause']:<18} "
                         f"before {b['next']}")
    return "\n".join(lines)
