"""Assemble cross-process span trees from the structured event logs.

``skytpu trace <request_id>`` backend: scan every per-process JSONL
event log (the API server's, each worker's, head-side rpc/skylet
daemons' — whatever shares the events dir), pick the records of one
trace, and render them as a duration-annotated tree or a Perfetto
(chrome trace-format) export.

Stdlib-only and CLI-independent so tests and other surfaces can reuse
the assembly.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import tracing


def search_dirs() -> List[str]:
    """Event-log directories to scan: this home's ``events/`` plus any
    colon-separated extras in ``SKYTPU_TRACE_EXTRA_DIRS`` (how a trace
    spanning homes — e.g. local-cloud host workspaces — is assembled)."""
    dirs = [tracing.events_dir()]
    extra = os.environ.get("SKYTPU_TRACE_EXTRA_DIRS", "")
    dirs.extend(d for d in extra.split(":") if d)
    return dirs


def load_trace(trace_id: str,
               dirs: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    """All records of ``trace_id`` across every log file in ``dirs``.
    Besides spans/events owned by the trace, flight-recorder burst
    records (``kind="flight"``) whose ``traces`` list names the trace
    match too — that is how ``skytpu trace <req>`` shows which engine
    bursts the request rode. Corrupt lines (a crash mid-line predates
    the atomic flush; foreign files) are skipped, never fatal."""
    records: List[Dict[str, Any]] = []
    for d in (dirs if dirs is not None else search_dirs()):
        for path in sorted(glob.glob(os.path.join(d, "*.jsonl"))):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        # Substring pre-filter before json.loads: the
                        # trace id is a 32-hex literal embedded in any
                        # matching line, and parsing every record of
                        # every log to find one trace would make the
                        # CLI O(all records ever) in JSON decoding.
                        if trace_id not in line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if not isinstance(rec, dict):
                            continue
                        if (rec.get("trace") == trace_id
                                or trace_id in (rec.get("traces")
                                                or ())):
                            records.append(rec)
            except OSError:
                continue
    return records


def _dur_ms(rec: Dict[str, Any]) -> float:
    return max(float(rec["end_s"]) - float(rec["start_s"]), 0.0) * 1e3


def build_tree(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Parent/child assembly. Returns root nodes; each node is
    ``{"rec": record, "children": [nodes]}``. A span whose parent id
    never made it into any log (that process crashed pre-flush, or its
    log lives in an unscanned home) roots its own subtree rather than
    vanishing. Events attach to their parent span like child nodes."""
    spans = {r["span"]: {"rec": r, "children": []}
             for r in records if r.get("kind") == "span"}
    roots: List[Dict[str, Any]] = []
    for node in spans.values():
        parent = node["rec"].get("parent")
        if parent and parent in spans:
            spans[parent]["children"].append(node)
        else:
            roots.append(node)
    for r in records:
        if r.get("kind") != "event":
            continue
        node = {"rec": r, "children": []}
        parent = r.get("parent")
        if parent and parent in spans:
            spans[parent]["children"].append(node)
        else:
            roots.append(node)

    def _key(n):
        rec = n["rec"]
        return float(rec.get("start_s", rec.get("ts_s", 0.0)))

    for node in spans.values():
        node["children"].sort(key=_key)
    roots.sort(key=_key)
    return roots


def render(records: List[Dict[str, Any]],
           trace_id: Optional[str] = None) -> str:
    """Human tree view: one line per span (duration, process/pid,
    error marker) with events inlined under their parent span."""
    if not records:
        return "no events recorded for this trace"
    trace_id = trace_id or records[0].get("trace", "?")
    n_spans = sum(1 for r in records if r.get("kind") == "span")
    procs = {(r.get("proc"), r.get("pid")) for r in records}
    lines = [f"trace {trace_id} — {n_spans} span"
             f"{'s' if n_spans != 1 else ''}, {len(procs)} process"
             f"{'es' if len(procs) != 1 else ''}"]

    def walk(node, prefix: str, is_last: bool) -> None:
        rec = node["rec"]
        branch = "└─ " if is_last else "├─ "
        where = f"[{rec.get('proc', '?')}/{rec.get('pid', '?')}]"
        if rec.get("kind") == "span":
            flag = ""
            if rec.get("status") == "error":
                flag = f"  !ERROR {rec.get('error_type') or ''}".rstrip()
            attrs = rec.get("attrs") or {}
            attr_s = ""
            if attrs:
                attr_s = " " + " ".join(
                    f"{k}={v}" for k, v in sorted(attrs.items()))
            lines.append(f"{prefix}{branch}{rec['name']}  "
                         f"{_dur_ms(rec):.1f}ms  {where}{attr_s}{flag}")
        else:
            attrs = rec.get("attrs") or {}
            attr_s = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            lines.append(f"{prefix}{branch}• {rec['name']}  {where}"
                         f"{(' ' + attr_s) if attr_s else ''}")
        kids = node["children"]
        child_prefix = prefix + ("   " if is_last else "│  ")
        for i, child in enumerate(kids):
            walk(child, child_prefix, i == len(kids) - 1)

    roots = build_tree(records)
    for i, root in enumerate(roots):
        walk(root, "", i == len(roots) - 1)

    # Flight-recorder bursts the trace's request(s) rode: not part of
    # the span tree (one burst serves many requests), rendered as a
    # timeline section under it instead.
    flights = sorted((r for r in records if r.get("kind") == "flight"),
                     key=lambda r: float(r.get("ts_s", 0.0)))
    if flights:
        from skypilot_tpu.observability import flight as flight_lib
        t0 = float(flights[0].get("ts_s", 0.0))
        lines.append("")
        lines.append(f"bursts ridden ({len(flights)}):")
        for r in flights:
            where = f"[{r.get('proc', '?')}/{r.get('pid', '?')}]"
            extra = []
            if r.get("drafted"):
                extra.append(f"spec {r.get('accepted', 0)}"
                             f"/{r.get('drafted', 0)}")
            if r.get("stall"):
                extra.append("stall")
            if r.get("compiled"):
                extra.append(f"COMPILED={len(r['compiled'])}")
            lines.append(
                f"  +{(float(r.get('ts_s', t0)) - t0) * 1e3:8.1f}ms  "
                f"{flight_lib.program_label(r):<36} "
                f"slots={len(r.get('slots', ()))} "
                f"toks={r.get('toks', 0)}"
                f"{('  ' + ' '.join(extra)) if extra else ''}  {where}")
    return "\n".join(lines)


def to_perfetto(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-format export (Perfetto/chrome://tracing loadable):
    spans as complete ('X') events, lifecycle events as instants, plus
    process_name metadata so tracks are labeled by process."""
    events: List[Dict[str, Any]] = []
    named_pids = set()
    for r in records:
        pid = r.get("pid", 0)
        tid = r.get("tid", pid)
        if pid not in named_pids:
            named_pids.add(pid)
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{r.get('proc', '?')}/{pid}"}})
        args = dict(r.get("attrs") or {})
        if r.get("status") == "error":
            args["status"] = "error"
            if r.get("error_type"):
                args["error_type"] = r["error_type"]
        if r.get("kind") == "span":
            events.append({
                "name": r["name"], "ph": "X",
                "ts": float(r["start_s"]) * 1e6,
                "dur": max(float(r["end_s"]) - float(r["start_s"]), 0.0)
                * 1e6,
                "pid": pid, "tid": tid, "args": args})
        elif r.get("kind") == "event":
            events.append({
                "name": r["name"], "ph": "i",
                "ts": float(r["ts_s"]) * 1e6,
                "pid": pid, "tid": tid, "s": "p", "args": args})
        elif r.get("kind") == "flight":
            from skypilot_tpu.observability import flight as flight_lib
            args = dict(args)
            args.update({k: r[k] for k in ("toks", "rids", "drafted",
                                           "accepted", "compiled",
                                           "adapters")
                         if r.get(k)})
            args["slots"] = len(r.get("slots", ()))
            events.append({
                "name": flight_lib.program_label(r), "ph": "X",
                "ts": float(r.get("ts_s", 0.0)) * 1e6,
                "dur": max(float(r.get("dur_s", 0.0)), 0.0) * 1e6,
                "pid": pid, "tid": tid, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
