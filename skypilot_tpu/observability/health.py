"""Component health model: one cheap answer per fleet process.

Every participating process answers a ``healthz`` probe — HTTP on the
servers (API server, model server, load balancer all serve ``GET
/healthz``), RPC on the skylet (the ``healthz`` method reads the
heartbeat the skylet's tick loop persists) — and every answer has the
same shape::

    {"status": "healthy" | "degraded" | "dead",
     "reason": <human string>,
     "last_seen_s": <seconds since the component last showed life>}

``healthy`` serves traffic; ``degraded`` is up but impaired (model
server warming/engine-reset-failed, LB with zero ready replicas, stale
skylet heartbeat); ``dead`` is unreachable or known-gone. Derivations
reuse what already exists — the ``skytpu_skylet_last_tick_timestamp_
seconds`` heartbeat gauge and the serve DB's replica probe state — no
new wire protocol.

Stdlib-only (the rpc ``healthz`` method runs under ``python -S``).
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

HEALTHY = "healthy"
# Draining: deliberately refusing NEW work while in-flight requests
# finish (graceful drain ahead of a rolling-update kill). Healthier
# than degraded — it is a planned state, not an impairment — but the
# rollup still surfaces it so an operator sees the drain in progress.
# A replica draining PAST its deadline self-reports degraded instead.
DRAINING = "draining"
DEGRADED = "degraded"
DEAD = "dead"

# Heartbeat older than this marks a daemon degraded (it ticks every
# ~10s by default); a dead process file-ages past it within a minute.
DEFAULT_STALE_AFTER_S = 60.0


def component(comp: str, instance: str, status: str, reason: str = "",
              last_seen_s: Optional[float] = None) -> Dict[str, Any]:
    return {"component": comp, "instance": instance, "status": status,
            "reason": reason, "last_seen_s": last_seen_s}


def healthz_payload(status: str, reason: str = "",
                    last_seen_s: float = 0.0) -> Dict[str, Any]:
    """The body every ``GET /healthz`` returns."""
    return {"status": status, "reason": reason,
            "last_seen_s": round(last_seen_s, 3)}


def write_healthz(handler, status: str, reason: str = "",
                  last_seen_s: float = 0.0) -> None:
    """Serve ``GET /healthz`` on a ``BaseHTTPRequestHandler``: 200 for
    healthy/degraded (the probe succeeded; the STATUS carries the
    verdict), so only an unreachable process reads as dead."""
    body = json.dumps(healthz_payload(status, reason,
                                      last_seen_s)).encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def probe_http(url: str, timeout: float = 2.0,
               comp: str = "", instance: str = "") -> Dict[str, Any]:
    """Probe a ``/healthz`` URL. Connection failure = dead; a 200 with
    a payload passes the payload's own verdict through; a non-200
    (e.g. the model server's 503-while-warming ``/health``) = degraded."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            raw = r.read().decode("utf-8", errors="replace")
        try:
            payload = json.loads(raw)
        except ValueError:
            payload = {}
        status = payload.get("status", HEALTHY)
        if status not in (HEALTHY, DRAINING, DEGRADED, DEAD):
            # /health-style {"status": "ok"} answers map onto the model.
            status = HEALTHY if status in ("ok", "healthy") else DEGRADED
        return component(comp, instance, status,
                         reason=payload.get("reason", ""),
                         last_seen_s=payload.get("last_seen_s", 0.0))
    except urllib.error.HTTPError as e:
        # last_seen_s stays unknown (None): an error reply proves the
        # port answers, not that the COMPONENT showed life — reporting
        # the probe's own latency here would render as "seen 0s ago"
        # for a server that has been failing for an hour.
        return component(comp, instance, DEGRADED,
                         reason=f"HTTP {e.code}", last_seen_s=None)
    except Exception as e:  # noqa: BLE001 — unreachable = dead
        return component(comp, instance, DEAD,
                         reason=f"{type(e).__name__}: {e}",
                         last_seen_s=None)


def pid_running(pid: Optional[int]) -> bool:
    """The one pid-liveness check the health model uses (signal 0)."""
    if pid is None:
        return False
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _pid_alive(pidfile: str) -> Optional[bool]:
    """Pidfile liveness: None = no/unreadable pidfile (distinct from a
    recorded-but-dead pid)."""
    try:
        pid = int(open(pidfile).read().strip())
    except (OSError, ValueError):
        return None
    return pid_running(pid)


def skylet_expected(cdir: str) -> bool:
    """Whether a live skylet SHOULD exist for this cluster dir: armed
    autostop that has not fired yet, or a recorded still-running pid.
    Shared by :func:`skylet_health` and endpoint discovery — a skylet
    that exited by design (unarmed, or after successfully firing
    autostop) must read as idle, not dead, and its frozen heartbeat
    must not feed the staleness SLO rule forever."""
    if _pid_alive(os.path.join(cdir, "skylet.pid")):
        return True
    return (os.path.exists(os.path.join(cdir, "autostop.json"))
            and not os.path.exists(os.path.join(cdir,
                                                "autostop_fired")))


def skylet_health(cdir: str,
                  stale_after_s: float = DEFAULT_STALE_AFTER_S
                  ) -> Dict[str, Any]:
    """Skylet liveness from what the head's disk already holds: the
    pidfile and the heartbeat gauge inside the per-tick exposition
    file. Answerable by the rpc ``healthz`` method without touching
    the daemon itself."""
    from skypilot_tpu.observability import aggregate, metrics
    name = os.path.basename(cdir.rstrip(os.sep))
    pidfile = os.path.join(cdir, "skylet.pid")
    alive = _pid_alive(pidfile)
    last_tick: Optional[float] = None
    try:
        with open(os.path.join(cdir, aggregate.METRICS_FILENAME),
                  encoding="utf-8") as f:
            fams = metrics.parse_exposition(f.read())
        last_tick = aggregate.sample_value(
            fams, "skytpu_skylet_last_tick_timestamp_seconds", agg="max")
    except (OSError, ValueError):
        pass
    age = time.time() - last_tick if last_tick else None
    if not alive and not skylet_expected(cdir):
        # The skylet exits by design when autostop is unset (and after
        # a SUCCESSFUL autostop fire — autostop.json stays behind but
        # the autostop_fired marker records the outcome); absence is
        # health, not death.
        fired = os.path.exists(os.path.join(cdir, "autostop_fired"))
        return component("skylet", name, HEALTHY,
                         reason=("autostop fired; cluster stopped"
                                 if fired else
                                 "idle (autostop not armed)"),
                         last_seen_s=age)
    if alive:
        if age is not None and age > stale_after_s:
            return component(
                "skylet", name, DEGRADED,
                reason=f"heartbeat stale ({age:.0f}s since last tick)",
                last_seen_s=age)
        return component("skylet", name, HEALTHY, last_seen_s=age)
    return component("skylet", name, DEAD,
                     reason="autostop armed but skylet process gone",
                     last_seen_s=age)


def _probe_replica(r: Dict[str, Any], name: str,
                   timeout: float) -> Dict[str, Any]:
    inst = f"{name}/{r['replica_id']}"
    status_v = r["status"].value
    if status_v in ("PROVISIONING", "STARTING"):
        return component("model-server", inst, DEGRADED,
                         reason=status_v.lower())
    if status_v in ("FAILED", "PREEMPTED", "SHUTDOWN", "SHUTTING_DOWN"):
        return component("model-server", inst, DEAD,
                         reason=status_v.lower())
    if status_v == "DRAINING":
        # Probe the replica itself: within its drain deadline it
        # self-reports "draining"; past it, "degraded" — which is what
        # flips `skytpu status --health` to exit 2. A gone replica
        # reads dead as usual.
        if not r["url"]:
            return component("model-server", inst, DRAINING,
                             reason="draining")
        return probe_http(f"{r['url']}/healthz", timeout=timeout,
                          comp="model-server", instance=inst)
    if not r["url"]:
        return component("model-server", inst, DEGRADED,
                         reason="no url yet")
    probed = probe_http(f"{r['url']}/healthz", timeout=timeout,
                        comp="model-server", instance=inst)
    if probed["status"] == HEALTHY and status_v == "NOT_READY":
        # The controller's prober disagrees: trust the pessimist (the
        # LB is not routing there).
        probed = component("model-server", inst, DEGRADED,
                           reason="controller marked NOT_READY",
                           last_seen_s=probed["last_seen_s"])
    return probed


def fleet_health(api_self: Optional[Dict[str, Any]] = None,
                 host: str = "127.0.0.1",
                 timeout: float = 2.0) -> List[Dict[str, Any]]:
    """Assemble the component table this host can see: serve
    controllers (pid liveness), load balancers (HTTP healthz),
    replicas (HTTP healthz cross-checked against the serve DB's probe
    state), and local-home skylets. ``api_self`` prepends the calling
    API server's own entry. HTTP probes run on a small thread pool —
    sequential probing of N down components would cost N x timeout
    exactly when the table matters (an outage)."""
    import functools
    jobs: List[Any] = []   # resolved components OR callables to probe
    if api_self is not None:
        jobs.append(api_self)
    try:
        from skypilot_tpu.serve import serve_state
        for svc in serve_state.list_services():
            if svc["status"].is_terminal():
                continue
            name = svc["name"]
            ctrl_alive = pid_running(svc.get("controller_pid"))
            jobs.append(component(
                "serve-controller", name,
                HEALTHY if ctrl_alive else DEAD,
                reason="" if ctrl_alive else "controller process gone"))
            if svc.get("lb_port"):
                jobs.append(functools.partial(
                    probe_http,
                    f"http://{host}:{svc['lb_port']}/healthz",
                    timeout=timeout, comp="load-balancer",
                    instance=name))
            for r in serve_state.list_replicas(name):
                jobs.append(functools.partial(_probe_replica, r, name,
                                              timeout))
    except Exception:  # noqa: BLE001 — no serve DB is a healthy fleet
        pass
    try:
        from skypilot_tpu.observability import aggregate
        from skypilot_tpu.utils import paths
        clusters_root = os.path.join(paths.home(), "clusters")
        if os.path.isdir(clusters_root):
            for cname in sorted(os.listdir(clusters_root)):
                cdir = os.path.join(clusters_root, cname)
                if os.path.exists(os.path.join(
                        cdir, aggregate.METRICS_FILENAME)):
                    jobs.append(functools.partial(skylet_health, cdir))
    except OSError:
        pass
    callables = [j for j in jobs if callable(j)]
    if len(callables) > 1:
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(callables))) as pool:
            futures = {id(j): pool.submit(j) for j in jobs
                       if callable(j)}
            return [futures[id(j)].result() if callable(j) else j
                    for j in jobs]
    return [j() if callable(j) else j for j in jobs]


def worst(components: List[Dict[str, Any]]) -> str:
    """Fleet-level rollup: dead beats degraded beats draining beats
    healthy. A fleet whose worst component is merely draining is
    executing a planned drain — visible, but not an incident (the CLI
    exits 0 on it; degraded/dead exit 2)."""
    rank = {HEALTHY: 0, DRAINING: 1, DEGRADED: 2, DEAD: 3}
    status = HEALTHY
    for c in components:
        if rank.get(c["status"], 0) > rank[status]:
            status = c["status"]
    return status
