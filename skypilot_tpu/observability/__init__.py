"""Live observability: process-global metrics registry + exposition.

``from skypilot_tpu.observability import metrics`` is the one import an
instrumentation site needs; declare families at module import with
``metrics.counter/gauge/histogram`` and record on the hot path. Any
HTTP surface serves ``metrics.render()`` as ``GET /metrics``
(Content-Type :data:`metrics.CONTENT_TYPE`).

Traces and metrics correlate by name: a ``timeline.Event`` given a
``histogram=`` child double-records the same span into Perfetto (when
``SKYTPU_TIMELINE_FILE_PATH`` is set) and into the histogram (always).
"""

from skypilot_tpu.observability.metrics import (  # noqa: F401
    CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    Metric,
    REGISTRY,
    Registry,
    counter,
    gauge,
    histogram,
    render,
)
