"""Live observability: process-global metrics registry + exposition.

``from skypilot_tpu.observability import metrics`` is the one import an
instrumentation site needs; declare families at module import with
``metrics.counter/gauge/histogram`` and record on the hot path. Any
HTTP surface serves ``metrics.render()`` as ``GET /metrics``
(Content-Type :data:`metrics.CONTENT_TYPE`).

Traces and metrics correlate by name: a ``timeline.Event`` given a
``histogram=`` child double-records the same span into Perfetto (when
``SKYTPU_TIMELINE_FILE_PATH`` is set) and into the histogram (always).

Per-request distributed tracing lives in ``tracing.py`` (W3C-style
traceparent context + structured JSONL event log; ``skytpu trace``
assembles the cross-process tree) and ``trace_view.py`` (assembly/
rendering). See docs/observability.md §Distributed tracing.
"""

from skypilot_tpu.observability.metrics import (  # noqa: F401
    CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    Metric,
    REGISTRY,
    Registry,
    counter,
    gauge,
    histogram,
    render,
)
