"""Dependency-free distributed tracing + structured flight-recorder log.

The per-request complement to ``metrics.py``'s aggregates: a Dapper-style
trace context (``trace_id``/``span_id``/``parent_span_id``, W3C
traceparent wire format) rides the request id the stack already mints,
crossing every process boundary of the request lifecycle:

  CLI/SDK --traceparent header--> API server --requests_db ``trace``
  column--> worker subprocess (``SKYTPU_TRACEPARENT`` env) --``trace``
  RPC param--> head-side rpc/skylet daemons.

Completed spans and typed lifecycle events land in a per-process
structured JSONL event log under ``<home>/events/`` — a bounded
in-process ring buffer flushed atomically (tempfile + ``os.replace``,
the ``utils/timeline.py`` pattern), so a reader never sees a torn file
and a crash loses at most the unflushed tail. ``skytpu trace
<request_id>`` reassembles the cross-process span tree from these logs.

Design constraints, in order (same as metrics.py):
  * stdlib only — head-side daemons run under ``python -S``;
  * cheap when idle — recording is a dict append under a lock; nothing
    touches the filesystem until a flush point;
  * safe under concurrency — handler threads, the executor thread and
    the engine loop record into one buffer; the context stack is
    thread-local.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.observability import _ringflush

ENV_VAR = "SKYTPU_TRACEPARENT"
EVENTS_DIR_ENV_VAR = "SKYTPU_EVENTS_DIR"

# version 00, lowercase hex, all-zero ids invalid (W3C trace-context).
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# Flight-recorder bound: a long-lived daemon must not grow its buffer
# (or each flush's serialization cost) forever. Oldest records drop.
_MAX_RECORDS = 20_000


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """Identity of one span within one trace (ids are lowercase hex)."""

    trace_id: str   # 32 hex chars
    span_id: str    # 16 hex chars


# Sentinel for add_event(ctx=DETACHED): record the event with NO trace
# attachment, overriding the ambient-context fallback. For daemons whose
# persisted context is missing (e.g. a pre-upgrade autostop.json): an
# unattributed event beats one misattributed to the spawn-time root.
DETACHED = SpanContext("", "")


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def format_traceparent(ctx: SpanContext) -> str:
    """W3C-style ``00-<trace_id>-<span_id>-01`` (sampled flag set)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent header; ``None`` on anything malformed (the
    caller then starts a fresh trace — a bad peer must never break
    request handling)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


# ---------------------------------------------------------------------------
# Context: thread-local span stack over a process root from the env.

_tls = threading.local()


def _stack() -> List[SpanContext]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current() -> Optional[SpanContext]:
    """The active span context: this thread's innermost open span, else
    the process root injected via ``SKYTPU_TRACEPARENT`` (how a parent
    process parents every span of a child it spawns), else None."""
    stack = _stack()
    if stack:
        return stack[-1]
    return parse_traceparent(os.environ.get(ENV_VAR))


def traceparent() -> Optional[str]:
    ctx = current()
    return format_traceparent(ctx) if ctx else None


_process_name: Optional[str] = None


def set_process_name(name: str) -> None:
    """Human label for this process in assembled trace trees (defaults
    to the argv-0 basename)."""
    global _process_name
    _process_name = name


def process_name() -> str:
    if _process_name:
        return _process_name
    base = os.path.basename(sys.argv[0] or "") or "python"
    return base[:-3] if base.endswith(".py") else base


# ---------------------------------------------------------------------------
# The event log: bounded ring buffer + atomic whole-buffer flush.
# The state machine lives in observability/_ringflush.py (shared with
# the flight and goodput recorders); this module keeps the record
# shapes and the enablement/suppression policy.


def enabled() -> bool:
    """The flight recorder is on unless explicitly disabled."""
    return os.environ.get("SKYTPU_EVENT_LOG", "1") != "0"


def events_dir() -> str:
    d = os.environ.get(EVENTS_DIR_ENV_VAR)
    if not d:
        from skypilot_tpu.utils import paths
        d = os.path.join(paths.home(), "events")
    return d


def _mint_log_name() -> str:
    # pid + start-ms: unique per process incarnation, so a recycled
    # pid can never clobber a dead process's log.
    return (f"{process_name()}-{os.getpid()}"
            f"-{int(time.time() * 1000)}.jsonl")


def _gc_on_exit() -> None:
    # Self-cleaning: every recording process prunes the dir on the
    # way out (one cheap listdir against a GC-bounded dir). This is
    # what keeps the HEAD's events dir bounded too — short-lived
    # rpc processes are its main writers and nothing else up there
    # runs a GC loop.
    gc_event_logs()


_RING = _ringflush.Ring(_MAX_RECORDS, _mint_log_name, events_dir,
                        halve_on_overflow=True,
                        atexit_extra=_gc_on_exit,
                        thread_name="tracing-flush")


def _append(rec: Dict[str, Any]) -> None:
    if not enabled():
        return
    from skypilot_tpu.observability import metrics
    if metrics.suppressed():
        return   # e.g. the model server's warmup generation
    _RING.append(rec)


def flush() -> None:
    """Atomically rewrite this process's event-log file with the whole
    buffer (crash-safe tempfile + ``os.replace``; see ``_ringflush``)."""
    if not enabled():
        return
    _RING.flush()


def flush_periodic(min_new_records: int = 128,
                   max_age_s: float = 60.0) -> None:
    """Throttled :func:`flush` for per-tick daemon callers: every flush
    re-serializes the whole buffer, so flush only once enough records
    accumulated or the last flush went stale."""
    _RING.flush_periodic(min_new_records=min_new_records,
                         max_age_s=max_age_s)


def ensure_flush_thread(interval_s: float = 5.0) -> None:
    """Start (once) a daemon thread that runs :func:`flush_periodic`
    every ``interval_s``. For latency-critical loops (the model
    server's serving thread): each flush re-serializes the whole ring,
    and paying tens of ms inline between decode waves is a recurring
    tail-latency spike — off-thread, the same durability costs the hot
    path nothing (the buffer lock is only held to snapshot)."""
    _RING.ensure_flush_thread(interval_s, min_new_records=256,
                              max_age_s=interval_s)


def gc_event_logs(max_files: int = 256,
                  max_age_s: float = 7 * 24 * 3600.0) -> int:
    """Prune old per-process log files (every process incarnation writes
    its own file; without GC a busy server's events dir grows forever).
    Keeps the newest ``max_files`` AND anything younger than
    ``max_age_s`` — a file is deleted only when it fails both, so a
    request burst can never GC away minutes-old logs whose requests the
    requests DB still serves. Returns the number of files removed."""
    d = events_dir()
    try:
        all_names = os.listdir(d)
    except OSError:
        return 0
    removed = 0
    entries = []
    for n in all_names:
        try:
            mtime = os.path.getmtime(os.path.join(d, n))
        except OSError:
            continue
        if n.endswith(".jsonl"):
            entries.append((mtime, n))
        elif ".jsonl." in n and mtime < time.time() - max_age_s:
            # Orphaned mkstemp temp (a SIGKILL between mkstemp and
            # os.replace skips the except-cleanup): invisible to the
            # '*.jsonl' readers, so without this it accumulates forever.
            try:
                os.remove(os.path.join(d, n))
                removed += 1
            except OSError:
                pass
    entries.sort(reverse=True)
    cutoff = time.time() - max_age_s
    for i, (mtime, n) in enumerate(entries):
        if i < max_files or mtime >= cutoff:
            continue
        try:
            os.remove(os.path.join(d, n))
            removed += 1
        except OSError:
            pass
    return removed


# ---------------------------------------------------------------------------
# Recording: spans (durations) and events (points in time).

def record_span(name: str, start_s: float, end_s: float, *,
                ctx: Optional[SpanContext] = None,
                parent_id: Optional[str] = None,
                parent: Optional[SpanContext] = None,
                attrs: Optional[Dict[str, Any]] = None,
                status: str = "ok",
                error_type: Optional[str] = None) -> SpanContext:
    """Append one completed span.

    Identity resolution, in order: an explicit ``ctx`` (a pre-minted
    identity, e.g. the request span persisted in requests_db) with an
    optional explicit ``parent_id``; else a fresh child of ``parent``;
    else a fresh child of :func:`current` (or a fresh root trace when
    no context is active). Returns the span's context so callers can
    parent further spans to it."""
    if ctx is None:
        if parent is None:
            parent = current()
        if parent is not None:
            ctx = SpanContext(parent.trace_id, new_span_id())
            if parent_id is None:
                parent_id = parent.span_id
        else:
            ctx = SpanContext(new_trace_id(), new_span_id())
    rec: Dict[str, Any] = {
        "kind": "span", "name": name,
        "trace": ctx.trace_id, "span": ctx.span_id,
        "parent": parent_id,
        "start_s": start_s, "end_s": end_s,
        "pid": os.getpid(), "tid": threading.get_ident(),
        "proc": process_name(),
    }
    if attrs:
        rec["attrs"] = attrs
    if status != "ok":
        rec["status"] = status
    if error_type:
        rec["error_type"] = error_type
    _append(rec)
    return ctx


def add_event(name: str, attrs: Optional[Dict[str, Any]] = None, *,
              ctx: Optional[SpanContext] = None,
              echo: bool = False) -> None:
    """Append one typed lifecycle event (state transition, retry,
    error, ...) attached to ``ctx`` when given, else to the active
    span/trace when one exists. Explicit ``ctx`` is for long-lived
    daemons attributing an event to a PERSISTED context (e.g. the
    skylet attaching autostop outcomes to the request that armed
    autostop) rather than their own process root; ``ctx=DETACHED``
    records with no trace attachment at all. ``echo=True`` also
    writes the record as one JSON line to stderr — the structured
    replacement for a daemon's bare ``print``."""
    if ctx is DETACHED:
        ctx = None
    elif ctx is None:
        ctx = current()
    rec: Dict[str, Any] = {
        "kind": "event", "name": name, "ts_s": time.time(),
        "pid": os.getpid(), "tid": threading.get_ident(),
        "proc": process_name(),
    }
    if ctx is not None:
        rec["trace"] = ctx.trace_id
        rec["parent"] = ctx.span_id
    if attrs:
        rec["attrs"] = attrs
    _append(rec)
    if echo:
        try:
            sys.stderr.write(json.dumps(rec, default=str) + "\n")
            sys.stderr.flush()
        except (OSError, ValueError):
            pass   # a closed stderr must not take the caller down


class start_span:
    """Context manager opening a child span of the active context (or a
    fresh root). The span is recorded on exit; an exception marks it
    ``status=error`` with the exception class and re-raises."""

    def __init__(self, name: str,
                 attrs: Optional[Dict[str, Any]] = None):
        self._name = name
        self._attrs = attrs
        self.ctx: Optional[SpanContext] = None
        self._parent_id: Optional[str] = None
        self._t0 = 0.0

    def __enter__(self) -> "start_span":
        parent = current()
        if parent is not None:
            self.ctx = SpanContext(parent.trace_id, new_span_id())
            self._parent_id = parent.span_id
        else:
            self.ctx = SpanContext(new_trace_id(), new_span_id())
        _stack().append(self.ctx)
        self._t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        stack = _stack()
        if stack and stack[-1] == self.ctx:
            stack.pop()
        elif self.ctx in stack:           # tolerate unbalanced exits
            stack.remove(self.ctx)
        record_span(
            self._name, self._t0, time.time(), ctx=self.ctx,
            parent_id=self._parent_id, attrs=self._attrs,
            status="error" if exc_type is not None else "ok",
            error_type=exc_type.__name__ if exc_type is not None
            else None)


# ---------------------------------------------------------------------------
# Introspection (bench --emit-trace, tests).

def span_summary() -> Dict[str, Dict[str, Any]]:
    """Aggregate the in-memory buffer's spans by name:
    ``{name: {count, total_s, mean_s, max_s}}`` — the per-request span
    summary BENCH artifacts carry under ``--emit-trace``."""
    spans = [r for r in _RING.snapshot() if r.get("kind") == "span"]
    out: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        dur = max(float(s["end_s"]) - float(s["start_s"]), 0.0)
        agg = out.setdefault(s["name"],
                             {"count": 0, "total_s": 0.0, "max_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += dur
        agg["max_s"] = max(agg["max_s"], dur)
    for agg in out.values():
        agg["mean_s"] = round(agg["total_s"] / agg["count"], 6)
        agg["total_s"] = round(agg["total_s"], 6)
        agg["max_s"] = round(agg["max_s"], 6)
    return out


def buffered_records() -> List[Dict[str, Any]]:
    """Snapshot of the in-memory buffer (tests)."""
    return [dict(r) for r in _RING.snapshot()]


def _reset_for_tests() -> None:
    """Drop the buffer and per-process log identity (tests only — a
    fresh tmp home must get a fresh log file, not the previous test's
    name)."""
    global _process_name
    _RING.reset_for_tests()
    _process_name = None
    _tls.stack = []
