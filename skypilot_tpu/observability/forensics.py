"""Request forensics: the layer that turns raw telemetry into answers.

Five PRs of instrumentation — spans, flight records, typed events,
device-truth attribution — record everything that happens on a serving
replica, but none of them answer the operator's actual question: *why
was THIS request slow?* This module does, three ways:

* :func:`build_ledger` — a per-request critical-path LEDGER assembled
  from existing artifacts (the flight records whose member-rid lists
  include the request, the retirement record's stall episodes, the
  calibrated ``dev_ms_est`` from the attribution layer). The ledger
  decomposes end-to-end latency into named phases — queue wait,
  admission stalls (pool-dry / kv-quota / adapter-pin), prefill
  chunks, per-burst decode device time vs host slack, speculative
  draft/verify, stream delivery — by a cursor sweep that partitions
  ``[submit, end]`` EXACTLY: phases sum to the measured wall by
  construction, and a tier-1 gate (the counter-deltas family) holds
  the decomposition to it over the mixed bench workload. ``skytpu
  why <rid>`` renders it.

* :class:`TailDetector` + :class:`ExemplarStore` — streaming P²
  quantile estimators (five markers per metric, no unbounded
  reservoirs) on TTFT/TPOT per engine. A request crossing the
  configured quantile (default p99.9) pins its FULL evidence — the
  retirement record, every flight record it rode, its ledger — into a
  bounded exemplar store that survives flight-ring rollover. The one
  p99.9 outlier per ten thousand requests keeps its flight records
  long after the 8192-record ring has rolled past them.

* :func:`capture_incident` — the SLO watchdog's breach transition
  triggers an atomic capture bundle (flight-ring tail, recent event
  log, merged metrics snapshot, pinned exemplars, the alert itself)
  into a timestamped, GC'd directory under ``<home>/incidents/``,
  surfaced by ``skytpu incidents list/show`` and linked from the
  ``slo.breach`` event. A breach names a rule; the bundle preserves
  the cause.

Same design constraints as the rest of the observability stack:
stdlib-only, host-side only (assembly happens OFF the hot path — at
retirement, never per burst), bounded memory, and everything the
engine does for forensics sits behind one ``engine.forensics`` flag
whose off-path is bit-identical (gated ≤1.01x overhead on, like the
flight recorder itself).

Knobs: ``SKYTPU_FORENSICS`` (default on), ``SKYTPU_TAIL_QUANTILE``
(default 0.999), ``SKYTPU_TAIL_MIN_SAMPLES`` (default 32),
``SKYTPU_TAIL_EXEMPLARS`` (default 64), ``SKYTPU_INCIDENTS``
(default on), ``SKYTPU_INCIDENTS_KEEP`` (default 16),
``SKYTPU_INCIDENT_MIN_INTERVAL_S`` (default 60).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.observability import metrics, tracing

TAIL_EXEMPLARS_PINNED = metrics.counter(
    "skytpu_tail_exemplars_pinned_total",
    "Requests whose TTFT/TPOT crossed the streaming tail quantile and "
    "had their full flight+ledger evidence pinned into the exemplar "
    "store", labelnames=("metric",))
INCIDENTS_CAPTURED = metrics.counter(
    "skytpu_slo_incidents_total",
    "SLO breach transitions that captured an incident snapshot bundle "
    "(flight tail, event log, metrics, exemplars)")


def forensics_enabled() -> bool:
    """Request forensics is on unless explicitly disabled
    (``SKYTPU_FORENSICS=0``)."""
    return os.environ.get("SKYTPU_FORENSICS", "1") != "0"


# ---------------------------------------------------------------------------
# The per-request critical-path ledger
# ---------------------------------------------------------------------------

# Canonical phase order (render order). Everything except
# ``host_other`` is a NAMED phase — the ledger gate holds named
# coverage to >= 90% of the wall.
PHASE_ORDER: Tuple[str, ...] = (
    "queue_wait", "stall_pool_dry", "stall_kv_quota",
    "stall_adapter_pin", "stall_recover", "preempt_requeue",
    "prefill_wave",
    "prefill_chunk", "prefill_interleave", "decode_device",
    "decode_host", "spec_draft", "spec_verify_device",
    "spec_verify_host", "deliver", "host_other")

_UNNAMED = frozenset({"host_other"})

# Stall causes in the retirement record's ``stalls`` dict, in the
# order queue-ish gaps consume them.
STALL_PHASES = {"pool_dry": "stall_pool_dry",
                "kv_quota": "stall_kv_quota",
                "adapter_pin": "stall_adapter_pin",
                # Engine crash recovery: the request sat requeued while
                # the engine reset and re-admitted its cohort.
                "recover": "stall_recover"}

_DECODEISH = frozenset({"decode", "decode1", "verify", "draft"})


def _device_split(rec: Dict[str, Any], seg_ms: float
                  ) -> Tuple[float, float]:
    """Split one burst record's clipped segment into (device, host)
    milliseconds using the calibrated ``dev_ms_est`` when the record
    carries one. Without an estimate the whole dispatch->fetch wall is
    credited to the device — the record IS a device call, and honest
    under-attribution of host slack beats inventing a split."""
    dev = rec.get("dev_ms_est")
    if dev is None:
        return seg_ms, 0.0
    dev = min(max(float(dev), 0.0), seg_ms)
    return dev, seg_ms - dev


def records_for(rid: int, records: Sequence[Dict[str, Any]]
                ) -> List[Dict[str, Any]]:
    """Every flight record whose member-rid list includes ``rid``,
    sorted by timestamp (ties: recorder sequence)."""
    out = [r for r in records if rid in (r.get("rids") or ())]
    out.sort(key=lambda r: (r.get("ts_s", 0.0), r.get("seq", 0)))
    return out


def build_ledger(retire: Dict[str, Any],
                 records: Sequence[Dict[str, Any]]
                 ) -> Dict[str, Any]:
    """Assemble the critical-path ledger for one retired request.

    ``retire`` is the request's ``burst == "retire"`` flight record
    (carries submit/first-token/end stamps and the stall-episode
    totals); ``records`` is any record set containing the request's
    burst records (extras for other rids are filtered out). The sweep
    partitions ``[submit_s, end_s]`` exactly: each burst record claims
    its (overlap-clipped) span, each gap between consecutive spans is
    classified by its neighbours, so ``sum(phases) == wall`` to float
    round-off BY CONSTRUCTION — the gate asserts it anyway, because an
    assembly bug (unsorted records, double-counted overlap) breaks
    exactly that invariant.
    """
    rid = retire["rids"][0]
    submit_s = float(retire["submit_s"])
    end_s = float(retire["end_s"])
    recs = [r for r in records_for(rid, records)
            if r.get("burst") != "retire"]
    phases: Dict[str, float] = {}
    spec_overlap_ms = 0.0
    computed_len = 0

    def add(phase: str, ms: float) -> None:
        if ms > 0.0:
            phases[phase] = phases.get(phase, 0.0) + ms

    # Queue-ish gaps consume the retirement record's stall-episode
    # totals first (the episodes HAPPENED inside those gaps); the
    # remainder is plain queue wait / post-preemption requeue.
    remaining_stalls = {c: max(float(v), 0.0)
                        for c, v in (retire.get("stalls") or {}).items()
                        if c in STALL_PHASES}

    def add_queueish(gap_ms: float, phase: str) -> None:
        for cause in ("pool_dry", "kv_quota", "adapter_pin", "recover"):
            left = remaining_stalls.get(cause, 0.0)
            if left <= 0.0 or gap_ms <= 0.0:
                continue
            take = min(left, gap_ms)
            add(STALL_PHASES[cause], take)
            remaining_stalls[cause] = left - take
            gap_ms -= take
        add(phase, gap_ms)

    cursor = submit_s
    prev_kind: Optional[str] = None
    for rec in recs:
        b = float(rec.get("ts_s", cursor))
        e = b + max(float(rec.get("dur_s", 0.0)), 0.0)
        kind = rec.get("burst", "")
        spec_overlap_ms += float(rec.get("overlap_ms", 0.0) or 0.0)
        if kind == "chunk":
            computed_len += 1
        e = min(e, end_s)
        if e <= cursor:
            # Fully inside an already-claimed span (a pipelined draft
            # dispatched during the previous verify's window): its
            # wall is accounted once, by whoever ran first.
            prev_kind = kind
            continue
        gap_ms = (b - cursor) * 1e3
        if gap_ms > 0.0:
            if prev_kind is None:
                add_queueish(gap_ms, "queue_wait")
            elif prev_kind in ("preempt", "recover"):
                # Crash recovery rides the preemption resume path, so
                # its post-reset wait classifies the same way (the
                # recover stall total is consumed first above).
                add_queueish(gap_ms, "preempt_requeue")
            elif prev_kind == "chunk" and kind == "chunk":
                # Interleaved decode bursts of OTHER slots ran between
                # this request's chunks — the interference chunked
                # prefill exists to bound.
                add("prefill_interleave", gap_ms)
            elif prev_kind in _DECODEISH and kind in _DECODEISH:
                add("decode_host", gap_ms)
            elif prev_kind in ("wave", "chunk") and kind in _DECODEISH:
                add("decode_host", gap_ms)
            else:
                add("host_other", gap_ms)
            cursor = b
        seg_ms = (e - cursor) * 1e3
        if kind == "wave":
            add("prefill_wave", seg_ms)
        elif kind == "chunk":
            add("prefill_chunk", seg_ms)
        elif kind in ("decode", "decode1"):
            dev, host = _device_split(rec, seg_ms)
            add("decode_device", dev)
            add("decode_host", host)
        elif kind == "verify":
            dev, host = _device_split(rec, seg_ms)
            add("spec_verify_device", dev)
            add("spec_verify_host", host)
        elif kind == "draft":
            add("spec_draft", seg_ms)
        else:
            add("host_other", seg_ms)
        cursor = e
        prev_kind = kind
    # Tail: last burst fetch -> retirement stamp (token delivery /
    # completion bookkeeping). With no records at all the whole wall
    # is unattributable.
    tail_ms = (end_s - cursor) * 1e3
    if recs:
        add("deliver", tail_ms)
    else:
        add("host_other", tail_ms)

    wall_ms = (end_s - submit_s) * 1e3
    named_ms = sum(v for k, v in phases.items() if k not in _UNNAMED)
    other_ms = sum(v for k, v in phases.items() if k in _UNNAMED)
    first = retire.get("first_token_s")
    ledger = {
        "rid": rid,
        "wall_ms": round(wall_ms, 4),
        "phases": [
            {"phase": k, "ms": round(phases[k], 4),
             "pct": round(100.0 * phases[k] / wall_ms, 2)
             if wall_ms > 0 else 0.0}
            for k in PHASE_ORDER if k in phases],
        "named_ms": round(named_ms, 4),
        "other_ms": round(other_ms, 4),
        "n_records": len(recs),
        "detail": {
            "ttft_ms": round((float(first) - submit_s) * 1e3, 4)
            if first else None,
            "prompt_len": retire.get("prompt_len"),
            "cached_len": retire.get("cached_len"),
            "resumed_len": retire.get("resumed_len"),
            "n_chunks": retire.get("n_chunks"),
            "computed_chunks": computed_len,
            "n_toks": retire.get("n_toks"),
            "spec_drafted": retire.get("spec_drafted"),
            "spec_accepted": retire.get("spec_accepted"),
            "spec_overlap_ms": round(spec_overlap_ms, 4),
            "preemptions": retire.get("preemptions"),
            "tenant": retire.get("tenants"),
            "adapter": retire.get("adapter"),
        },
    }
    return ledger


def ledger_from_records(rid: int,
                        records: Sequence[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """Find ``rid``'s retirement record in ``records`` and build its
    ledger; None when the request has not retired (or its retirement
    already rolled out of the window)."""
    retire = None
    for r in records:
        if r.get("burst") == "retire" and rid in (r.get("rids") or ()):
            retire = r   # keep the LAST (preempt-resume retires once)
    if retire is None:
        return None
    return build_ledger(retire, records)


def render_ledger(ledger: Dict[str, Any], width: int = 28) -> str:
    """Human-readable phase table for ``skytpu why``."""
    lines = [f"request {ledger['rid']}: "
             f"wall {ledger['wall_ms']:.1f} ms over "
             f"{ledger['n_records']} burst records"]
    det = ledger.get("detail") or {}
    bits = []
    if det.get("ttft_ms") is not None:
        bits.append(f"ttft {det['ttft_ms']:.1f} ms")
    if det.get("n_toks"):
        bits.append(f"{det['n_toks']} toks")
    if det.get("cached_len"):
        bits.append(f"cached {det['cached_len']}")
    if det.get("spec_drafted"):
        bits.append(f"spec {det['spec_accepted']}/{det['spec_drafted']}")
    if det.get("spec_overlap_ms"):
        bits.append(f"overlap {det['spec_overlap_ms']:.1f} ms")
    if det.get("preemptions"):
        bits.append(f"preempted x{det['preemptions']}")
    if bits:
        lines.append("  " + "  ".join(bits))
    lines.append(f"  {'phase':<{width}} {'ms':>10} {'%':>6}")
    for ph in ledger["phases"]:
        bar = "#" * max(int(round(ph["pct"] / 2.5)), 0)
        lines.append(f"  {ph['phase']:<{width}} {ph['ms']:>10.2f} "
                     f"{ph['pct']:>5.1f}% {bar}")
    named_pct = (100.0 * ledger["named_ms"] / ledger["wall_ms"]
                 if ledger["wall_ms"] else 0.0)
    lines.append(f"  {'sum (= wall)':<{width}} "
                 f"{sum(p['ms'] for p in ledger['phases']):>10.2f} "
                 f"{'':>6} named {named_pct:.1f}%")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Streaming tail detection (P-squared quantile estimation)
# ---------------------------------------------------------------------------


class P2Quantile:
    """Streaming quantile estimator (Jain & Chlamtac's P² algorithm):
    five markers track (min, q/2, q, (1+q)/2, max) with parabolic
    height adjustment — O(1) memory and O(1) per observation, no
    reservoir. At millions of requests an exact p99.9 needs the whole
    stream; five floats get within a fraction of a percent."""

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1): {q}")
        self.q = q
        self.count = 0
        self._init: List[float] = []
        self._heights: Optional[List[float]] = None
        self._pos: List[float] = []
        self._desired: List[float] = []
        self._dn = (0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0)

    def observe(self, x: float) -> None:
        self.count += 1
        h = self._heights
        if h is None:
            self._init.append(x + 0.0)
            if len(self._init) == 5:
                self._init.sort()
                self._heights = list(self._init)
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                q = self.q
                self._desired = [1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q,
                                 3.0 + 2.0 * q, 5.0]
            return
        pos = self._pos
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 3
            for i in range(1, 4):
                if x < h[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._desired[i] += self._dn[i]
        for i in range(1, 4):
            d = self._desired[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or \
                    (d <= -1.0 and pos[i - 1] - pos[i] < -1.0):
                d = 1.0 if d > 0 else -1.0
                hp = self._parabolic(i, d)
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:
                    # Parabolic prediction left the bracket: linear.
                    j = i + 1 if d > 0 else i - 1
                    h[i] += d * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self._heights, self._pos
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i])
            / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1])
            / (n[i] - n[i - 1]))

    def value(self) -> Optional[float]:
        """Current quantile estimate; before five observations, the
        empirical quantile of what we have (None when empty)."""
        if self._heights is not None:
            return self._heights[2]
        if not self._init:
            return None
        s = sorted(self._init)
        idx = min(int(self.q * len(s)), len(s) - 1)
        return s[idx]


class TailDetector:
    """Per-engine streaming tail detector over TTFT and TPOT.

    ``observe`` compares the new sample against the estimate built
    from PRIOR samples (then folds it in), so a tail observation is
    "slower than the p-quantile of everything before it" — crossing
    requests get pinned, and the estimator keeps adapting. A warmup
    floor (``min_samples``) stops the first handful of requests from
    all counting as tails of a five-sample distribution."""

    METRICS = ("ttft", "tpot")

    def __init__(self, quantile: Optional[float] = None,
                 min_samples: Optional[int] = None):
        if quantile is None:
            try:
                quantile = float(
                    os.environ.get("SKYTPU_TAIL_QUANTILE", "") or 0.999)
            except ValueError:
                quantile = 0.999
        if min_samples is None:
            try:
                min_samples = int(
                    os.environ.get("SKYTPU_TAIL_MIN_SAMPLES", "") or 32)
            except ValueError:
                min_samples = 32
        self.quantile = quantile
        self.min_samples = max(int(min_samples), 5)
        self._est = {m: P2Quantile(quantile) for m in self.METRICS}

    def observe(self, metric: str, value: float
                ) -> Tuple[bool, Optional[float]]:
        """Fold one sample in; returns (crossed_tail, threshold)."""
        est = self._est[metric]
        threshold = est.value()
        crossed = (est.count >= self.min_samples
                   and threshold is not None and value >= threshold)
        est.observe(value)
        return crossed, threshold

    def snapshot(self) -> Dict[str, Any]:
        return {
            "quantile": self.quantile,
            "min_samples": self.min_samples,
            "estimates": {
                m: {"value": self._est[m].value(),
                    "count": self._est[m].count}
                for m in self.METRICS},
        }


class ExemplarStore:
    """Bounded store of pinned tail exemplars — full evidence (retire
    record, member flight records, ledger) for requests that crossed
    the tail quantile. A deque under a lock: the engine loop pins,
    HTTP threads and the incident capture read. Surviving flight-ring
    rollover is the point: the ring holds ~a minute of bursts, the
    store holds the last N INTERESTING requests regardless of age."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("SKYTPU_TAIL_EXEMPLARS", "") or 64)
            except ValueError:
                capacity = 64
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque(
            maxlen=self.capacity)

    def pin(self, exemplar: Dict[str, Any]) -> None:
        with self._lock:
            self._items.append(exemplar)

    def get(self, rid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            for ex in reversed(self._items):
                if ex.get("rid") == rid:
                    return ex
        return None

    def list(self) -> List[Dict[str, Any]]:
        """Newest-first summaries (the full records stay behind
        :meth:`get`/:meth:`snapshot` — a list is a dashboard row)."""
        with self._lock:
            items = list(self._items)
        out = []
        for ex in reversed(items):
            out.append({
                "rid": ex.get("rid"), "metric": ex.get("metric"),
                "value_ms": ex.get("value_ms"),
                "threshold_ms": ex.get("threshold_ms"),
                "ts_s": ex.get("ts_s"),
                "wall_ms": (ex.get("ledger") or {}).get("wall_ms"),
                "n_records": len(ex.get("records") or ()),
            })
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


# Process-default store (the flight RECORDER idiom): engines pin into
# it unless handed an injectable instance, and the SLO incident
# capture bundles whatever is pinned at breach time.
EXEMPLARS = ExemplarStore()


# ---------------------------------------------------------------------------
# SLO incident snapshots
# ---------------------------------------------------------------------------

_INCIDENTS_DIRNAME = "incidents"
_capture_lock = threading.Lock()
_last_capture_s = 0.0


def incidents_enabled() -> bool:
    return os.environ.get("SKYTPU_INCIDENTS", "1") != "0"


def incidents_dir(base_dir: Optional[str] = None) -> str:
    if base_dir is not None:
        return base_dir
    from skypilot_tpu.utils import paths
    return os.path.join(paths.home(), _INCIDENTS_DIRNAME)


def _keep() -> int:
    try:
        return max(int(
            os.environ.get("SKYTPU_INCIDENTS_KEEP", "") or 16), 1)
    except ValueError:
        return 16


def _min_interval_s() -> float:
    try:
        return float(
            os.environ.get("SKYTPU_INCIDENT_MIN_INTERVAL_S", "") or 60)
    except ValueError:
        return 60.0


def capture_incident(rule: str, attrs: Dict[str, Any],
                     recorder: Optional[Any] = None,
                     exemplars: Optional[ExemplarStore] = None,
                     health: Optional[Dict[str, Any]] = None,
                     base_dir: Optional[str] = None,
                     force: bool = False) -> Optional[str]:
    """Capture one incident snapshot bundle; returns its directory
    (or None when disabled / rate-limited / failed).

    Atomic by the tempdir+rename idiom the flush paths use: the bundle
    materialises under a ``.tmp`` name and renames into place, so a
    reader listing the incidents dir never sees a half-written bundle.
    Rate-limited (one bundle per ``SKYTPU_INCIDENT_MIN_INTERVAL_S``):
    a flapping rule must not turn the home dir into a disk-filling
    event loop — the FIRST transition of a storm carries the evidence.
    """
    global _last_capture_s
    if not incidents_enabled():
        return None
    now = time.time()
    with _capture_lock:
        if not force and now - _last_capture_s < _min_interval_s():
            return None
        _last_capture_s = now
    base = incidents_dir(base_dir)
    name = f"{int(now * 1e3)}-{rule}"
    final = os.path.join(base, name)
    tmp = final + ".tmp"
    try:
        os.makedirs(tmp, exist_ok=True)
        if recorder is None:
            from skypilot_tpu.observability import flight as flight_lib
            recorder = flight_lib.RECORDER
        store = exemplars if exemplars is not None else EXEMPLARS
        meta = {"rule": rule, "ts_s": now, "pid": os.getpid(),
                "attrs": dict(attrs)}
        _write_json(os.path.join(tmp, "meta.json"), meta)
        _write_json(os.path.join(tmp, "alert.json"), dict(attrs))
        _write_json(os.path.join(tmp, "health.json"), health or {})
        _write_json(os.path.join(tmp, "exemplars.json"),
                    store.snapshot())
        with open(os.path.join(tmp, "flight.jsonl"), "w",
                  encoding="utf-8") as f:
            tail = recorder.tail() if recorder is not None else []
            for rec in tail:
                f.write(json.dumps(rec) + "\n")
        with open(os.path.join(tmp, "events.jsonl"), "w",
                  encoding="utf-8") as f:
            for rec in tracing.buffered_records():
                f.write(json.dumps(rec) + "\n")
        with open(os.path.join(tmp, "metrics.prom"), "w",
                  encoding="utf-8") as f:
            f.write(metrics.REGISTRY.render())
        os.rename(tmp, final)
    except OSError:
        return None
    finally:
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    INCIDENTS_CAPTURED.inc()
    _gc_incidents(base)
    return final


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, default=str)


def _gc_incidents(base: str) -> None:
    try:
        names = sorted(n for n in os.listdir(base)
                       if not n.endswith(".tmp")
                       and os.path.isdir(os.path.join(base, n)))
    except OSError:
        return
    import shutil
    for n in names[:-_keep()]:
        shutil.rmtree(os.path.join(base, n), ignore_errors=True)


def list_incidents(base_dir: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    """Newest-first incident summaries from the incidents dir."""
    base = incidents_dir(base_dir)
    try:
        names = sorted((n for n in os.listdir(base)
                        if not n.endswith(".tmp")
                        and os.path.isdir(os.path.join(base, n))),
                       reverse=True)
    except OSError:
        return []
    out = []
    for n in names:
        meta: Dict[str, Any] = {}
        try:
            with open(os.path.join(base, n, "meta.json"),
                      encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        out.append({"name": n, "rule": meta.get("rule"),
                    "ts_s": meta.get("ts_s"),
                    "attrs": meta.get("attrs") or {}})
    return out


def load_incident(name: str, base_dir: Optional[str] = None
                  ) -> Optional[Dict[str, Any]]:
    """One incident's metadata plus its bundle file inventory."""
    base = incidents_dir(base_dir)
    path = os.path.join(base, name)
    if not os.path.isdir(path) or os.path.dirname(name):
        return None
    meta: Dict[str, Any] = {}
    try:
        with open(os.path.join(path, "meta.json"),
                  encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        pass
    files = []
    for fn in sorted(os.listdir(path)):
        fp = os.path.join(path, fn)
        if os.path.isfile(fp):
            try:
                lines = None
                if fn.endswith(".jsonl"):
                    with open(fp, encoding="utf-8") as f:
                        lines = sum(1 for _ in f)
                files.append({"file": fn,
                              "bytes": os.path.getsize(fp),
                              "lines": lines})
            except OSError:
                continue
    return {"name": name, "path": path, "meta": meta, "files": files}
