"""Dependency-free metrics core: Counter/Gauge/Histogram + Registry.

The live-serving counterpart to ``utils/timeline.py``'s post-hoc traces:
every hot path (engine prefill/decode, server waves, trainer steps, the
runtime daemons) records into a process-global :data:`REGISTRY`, and any
HTTP surface can render it as Prometheus text exposition (format 0.0.4,
what vLLM/JetStream-style serving stacks expose on ``GET /metrics``).

Design constraints, in order:
  * stdlib only — the runtime daemons run under ``python -S``;
  * cheap when unscraped — one dict lookup + float add under a lock per
    record (no allocation on the labeled fast path after first use);
  * safe under concurrency — handler threads, the engine loop thread and
    the skylet tick all record into one registry.

Metric names follow Prometheus conventions: ``skytpu_`` prefix, unit
suffix (``_seconds``, ``_total``). See docs/observability.md for the
catalog.
"""

from __future__ import annotations

import bisect
import contextlib
import math
import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Prometheus' classic latency ladder: 5 ms .. 10 s. TTFT on a cold
# bucket and a relayed chip can exceed 10 s, hence the 30/60 tail.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Serving-latency ladder for the user-visible histograms (TTFT, TPOT,
# HTTP request seconds): DEFAULT_BUCKETS puts exactly TWO boundaries
# between 25 ms and 250 ms — the region serving SLOs actually live in
# — so a p99 read off it can be interpolated across a 2.5x-wide
# bucket. This ladder is dense where decisions are made (1 ms .. 400
# ms) and still covers the cold-compile tail. Quantiles read from ANY
# histogram are linear interpolations within a bucket; see
# docs/observability.md ("quantile interpolation bias").
SERVING_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.0075, 0.01, 0.015, 0.02, 0.03, 0.045,
    0.065, 0.1, 0.15, 0.25, 0.4, 0.65, 1.0, 1.5, 2.5, 5.0, 10.0,
    30.0, 60.0)

_INF = float("inf")


def latency_buckets(default: Sequence[float] = SERVING_LATENCY_BUCKETS
                    ) -> Tuple[float, ...]:
    """Bucket ladder for the serving-latency histograms:
    ``SKYTPU_LATENCY_BUCKETS`` (comma-separated seconds) when set,
    else ``default``. The env var applies to every process that
    declares these histograms, so a fleet whose replicas share the
    environment stays merge-consistent — an override set on ONE
    replica is exactly the bucket-layout mismatch the fleet merge
    detects and refuses to sum. A malformed value falls back to the
    default (a typo must not take down metric declaration at import
    time)."""
    env = os.environ.get("SKYTPU_LATENCY_BUCKETS", "")
    if env:
        try:
            parsed = sorted(float(v) for v in env.split(",") if v.strip())
            if parsed and all(b > 0 for b in parsed):
                return tuple(parsed)
        except ValueError:
            pass
    return tuple(default)

_suppress_local = threading.local()


def _suppressed() -> bool:
    return getattr(_suppress_local, "depth", 0) > 0


def suppressed() -> bool:
    """Whether this thread is inside a :func:`suppress` block. Public
    so sibling recorders (the tracing event log) can honor the same
    discard window — warmup work skewing span summaries is the same
    bug as warmup work skewing histograms."""
    return _suppressed()


@contextlib.contextmanager
def suppress():
    """Discard every observation THIS thread records inside the block
    (``labels()`` lookups still resolve; values just don't change).
    For known-unrepresentative work driven through an instrumented
    path — e.g. the model server's warmup generation, whose XLA
    compile would permanently skew the serving histograms' sums."""
    _suppress_local.depth = getattr(_suppress_local, "depth", 0) + 1
    try:
        yield
    finally:
        _suppress_local.depth -= 1


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(names: Sequence[str], values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
    return "{" + inner + "}"


class _Child:
    """One (metric, label-values) time series. Thread-safe."""

    __slots__ = ("_lock", "_value", "_sum", "_counts", "_buckets")

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None):
        self._lock = threading.Lock()
        self._value = 0.0
        if buckets is not None:
            self._sum = 0.0
            self._buckets = buckets
            self._counts = [0] * (len(buckets) + 1)   # +1 for +Inf

    # counter / gauge ------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        if _suppressed():
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        if _suppressed():
            return
        with self._lock:
            self._value -= amount

    def set(self, value: float) -> None:
        if _suppressed():
            return
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    # histogram ------------------------------------------------------------
    def observe(self, value: float) -> None:
        if _suppressed():
            return
        value = float(value)
        # ``le`` is inclusive: a value exactly on a boundary lands in
        # that boundary's bucket (bisect_left gives the first bound
        # >= value).
        i = bisect.bisect_left(self._buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value

    def hist_state(self) -> Tuple[List[int], float]:
        with self._lock:
            return list(self._counts), self._sum

    # timing sugar ---------------------------------------------------------
    def time(self) -> "_Timer":
        return _Timer(self)


class _CounterChild(_Child):
    """A counter series is monotone: a negative increment would read as
    a counter reset to ``rate()``/``increase()``, so it is an error
    here, not data."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        super().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        raise TypeError("counters cannot decrease")

    def set(self, value: float) -> None:
        raise TypeError("counters cannot be set")


class _Timer:
    """``with HIST.labels(...).time(): ...`` observes the block wall."""

    def __init__(self, child: _Child):
        self._child = child

    def __enter__(self) -> "_Timer":
        import time
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        import time
        self._child.observe(time.monotonic() - self._t0)


class Metric:
    """A named metric family; label values select concrete children."""

    type: str = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}  # guarded-by: _lock
        if not self.labelnames:
            # Unlabeled metric: one implicit child so .inc()/.set()/
            # .observe() work directly on the family.
            self._default = self._labels(())
        else:
            self._default = None

    def _new_child(self) -> _Child:
        return _Child()

    def _labels(self, values: Tuple[str, ...]) -> _Child:
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    def labels(self, *args, **kwargs) -> _Child:
        if args and kwargs:
            raise ValueError(
                f"{self.name}: pass labels positionally or by name, "
                f"not both")
        if kwargs:
            if set(kwargs) != set(self.labelnames):
                raise ValueError(
                    f"{self.name}: expected labels "
                    f"{list(self.labelnames)}, got {sorted(kwargs)}")
            values = tuple(str(kwargs[n]) for n in self.labelnames)
        else:
            if len(args) != len(self.labelnames):
                raise ValueError(
                    f"{self.name}: expected {len(self.labelnames)} "
                    f"label values {list(self.labelnames)}, "
                    f"got {len(args)}")
            values = tuple(str(a) for a in args)
        return self._labels(values)

    def _require_default(self) -> _Child:
        if self._default is None:
            raise ValueError(
                f"{self.name} has labels {list(self.labelnames)}; "
                f"use .labels(...)")
        return self._default

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # rendering ------------------------------------------------------------
    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.type}"]
        for values, child in self.children():
            lines.append(
                f"{self.name}"
                f"{_render_labels(self.labelnames, values)} "
                f"{_format_value(child.value)}")
        return lines

    def snapshot(self) -> dict:
        return {
            "type": self.type,
            "help": self.help,
            "samples": [
                {"labels": dict(zip(self.labelnames, values)),
                 "value": child.value}
                for values, child in self.children()],
        }


class Counter(Metric):
    type = "counter"

    def _new_child(self) -> _Child:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        self._require_default().inc(amount)


class Gauge(Metric):
    type = "gauge"

    def set(self, value: float) -> None:
        self._require_default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._require_default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._require_default().dec(amount)


class Histogram(Metric):
    type = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: need at least one bucket bound")
        if len(set(bounds)) != len(bounds):
            raise ValueError(f"{name}: duplicate bucket bounds")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _new_child(self) -> _Child:
        return _Child(buckets=self.buckets)

    def observe(self, value: float) -> None:
        self._require_default().observe(value)

    def time(self) -> "_Timer":
        return self._require_default().time()

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.type}"]
        for values, child in self.children():
            counts, total = child.hist_state()
            cum = 0
            for bound, n in zip(self.buckets, counts):
                cum += n
                lines.append(
                    f"{self.name}_bucket"
                    f"{_render_labels(self.labelnames, values, ('le', _format_value(bound)))}"
                    f" {cum}")
            cum += counts[-1]
            lines.append(
                f"{self.name}_bucket"
                f"{_render_labels(self.labelnames, values, ('le', '+Inf'))}"
                f" {cum}")
            base = _render_labels(self.labelnames, values)
            lines.append(f"{self.name}_sum{base} {_format_value(total)}")
            lines.append(f"{self.name}_count{base} {cum}")
        return lines

    def snapshot(self) -> dict:
        samples = []
        for values, child in self.children():
            counts, total = child.hist_state()
            cum, by_le = 0, {}
            for bound, n in zip(self.buckets, counts):
                cum += n
                by_le[_format_value(bound)] = cum
            cum += counts[-1]
            by_le["+Inf"] = cum
            samples.append({"labels": dict(zip(self.labelnames, values)),
                            "count": cum, "sum": total,
                            "buckets": by_le})
        return {"type": self.type, "help": self.help, "samples": samples}


class Registry:
    """Process-wide metric store; rendering is the scrape surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}  # guarded-by: _lock

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                raise ValueError(f"metric {metric.name!r} already "
                                 f"registered as {existing.type}")
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (not isinstance(existing, cls)
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} re-declared with a different "
                        f"type or labels (have {existing.type}"
                        f"{list(existing.labelnames)})")
                if "buckets" in kwargs:
                    bounds = tuple(sorted(
                        float(b) for b in kwargs["buckets"]))
                    if existing.buckets != bounds:
                        raise ValueError(
                            f"metric {name!r} re-declared with "
                            f"different buckets (have "
                            f"{list(existing.buckets)}, got "
                            f"{list(bounds)})")
                return existing
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition (0.0.4) of every metric."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able dump (bench artifacts, tests)."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        return {m.name: m.snapshot() for m in metrics}

    def reset(self) -> None:
        """Drop every metric (tests only: module-level metric handles
        held by instrumented code keep recording into detached
        families, so production code must never call this)."""
        with self._lock:
            self._metrics.clear()


REGISTRY = Registry()

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# Module-level sugar: instrumentation sites declare their metric once at
# import with these (idempotent against double import).
def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: Sequence[str] = (),
              buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render() -> str:
    return REGISTRY.render()


def write_exposition(handler) -> None:
    """Serve ``GET /metrics`` on a ``BaseHTTPRequestHandler``: render
    the global registry with the 0.0.4 content type. Shared by the
    model server and the API server so exposition details live in ONE
    place."""
    body = render().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def write_exposition_file(path: str) -> None:
    """Atomically persist the global registry's exposition to ``path``
    (tempfile + ``os.replace``, the timeline/tracing pattern). This is
    how daemons WITHOUT an HTTP surface (skylet, serve controller)
    publish their registries: the federation tier and the rpc
    ``get_metrics`` method read the file, and its mtime doubles as a
    liveness signal."""
    import tempfile
    body = render()
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix=os.path.basename(path) + ".")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(body)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def parse_exposition(text: str) -> Dict[str, dict]:
    """Parse text exposition back into ``{family: {"type", "samples"}}``
    where samples is ``[(labels_dict, value)]`` keyed by the SAMPLE name
    (``_bucket``/``_sum``/``_count`` suffixes intact in the labels via
    ``__name__``). Round-trips :meth:`Registry.render`; the CLI metrics
    view and the exposition tests consume it."""
    families: Dict[str, dict] = {}
    ftype = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(None, 3)
            ftype[name] = typ
            families.setdefault(name, {"type": typ, "samples": []})
            continue
        if line.startswith("#"):
            continue
        if "{" in line:
            sample_name, rest = line.split("{", 1)
            labels_s, value_s = rest.rsplit("} ", 1)
            labels = {}
            for part in _split_label_pairs(labels_s):
                k, v = part.split("=", 1)
                labels[k] = _unescape_label(v[1:-1])
        else:
            sample_name, value_s = line.rsplit(" ", 1)
            labels = {}
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[:-len(suffix)] \
                if sample_name.endswith(suffix) else None
            if base and ftype.get(base) == "histogram":
                family = base
                labels["__name__"] = sample_name
                break
        families.setdefault(family, {"type": ftype.get(family, "untyped"),
                                     "samples": []})
        families[family]["samples"].append((labels, float(value_s)))
    return families


def _unescape_label(v: str) -> str:
    """Inverse of :func:`_escape_label`. A single left-to-right scan —
    ordered ``str.replace`` chains corrupt values like ``a\\nb``
    (literal backslash + n) by decoding the pair as a newline."""
    out, i = [], 0
    while i < len(v):
        ch = v[i]
        if ch == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _split_label_pairs(s: str) -> List[str]:
    """Split 'a="x",b="y,z"' on commas outside quoted values."""
    parts, buf, in_quote, escaped = [], [], False, False
    for ch in s:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quote = not in_quote
            buf.append(ch)
            continue
        if ch == "," and not in_quote:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts
