"""Training goodput forensics: the step-phase ledger, cumulative
goodput accounting, the loss/grad anomaly watchdog, and multi-host
straggler attribution for the train loop.

The serving side already answers "where did this request's wall time
go?" (forensics.py) and "what did the device actually do?"
(attribution.py). The train loop had neither: a slow run showed up as
a drifting ``skytpu_train_step_seconds`` histogram with no
decomposition, a NaN loss showed up as a diverging curve hours later,
and on a pod slice nobody could say WHICH host was dragging the
collective. This module closes those gaps with the same design
discipline as the serving stack — host-side bookkeeping only, bounded
state, typed events, and evidence that survives the process:

* :class:`GoodputRecorder` — the per-step flight record (reusing
  :class:`flight.FlightRecorder` with a ``train_step`` burst kind) and
  the cumulative goodput ledger. Each step's wall is decomposed into
  named phases (``data_wait``, ``h2d``, ``compute``, ``ckpt_save``,
  ``ckpt_wait``, ``eval``, ``anomaly_pause``) that sum to the measured
  step wall BY CONSTRUCTION: the remainder is ``host_other``, never
  silence. Across steps, a monotonic cursor attributes every second of
  run wall to exactly one goodput bucket (productive, warmup/compile,
  input-bound, checkpoint stall, restart replay, anomaly pause, eval,
  host other) — :meth:`GoodputRecorder.snapshot` sums to elapsed wall
  exactly, and the cumulative stamps persist in the checkpoint
  directory (``goodput.json``) so the ratio survives restarts instead
  of resetting to 100% after every preemption.

* :class:`AnomalyWatchdog` — streaming NaN/Inf guards (latched: one
  injected NaN batch produces exactly ONE typed ``train.anomaly``
  event, not one per logging interval) plus spike detection over
  loss/grad-norm deltas using the P² quantile estimator the serving
  tail detector already trusts. An anomaly emits the typed event,
  increments ``skytpu_train_anomalies_total{kind}``, and captures a
  :func:`forensics.capture_incident` bundle — the last N step records,
  buffered events, and a metrics snapshot, on disk before the loop
  crashes or the operator notices.

* Straggler attribution — each host publishes its own step wall as
  ``skytpu_train_host_step_seconds{host}``; the aggregate tier
  federates the per-host gauges, and ``skytpu top`` renders
  ``straggler host-K (+N ms)`` from the spread.

The recorder-off run (``SKYTPU_GOODPUT=0``) is the contract the bench
gates: bit-identical training (the recorder never touches batches or
state) within a 1.01x step-time overhead budget.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import tempfile
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu.observability import attribution, flight, forensics, \
    metrics, tracing

GOODPUT_RATIO = metrics.gauge(
    "skytpu_train_goodput_ratio",
    "Cumulative training goodput: productive (post-warmup compute) "
    "seconds / attributed elapsed wall, including stamps restored from "
    "the checkpoint directory — survives restarts instead of resetting "
    "to 100% after every preemption")
WALL_SECONDS = metrics.counter(
    "skytpu_train_wall_seconds_total",
    "Elapsed train-run wall seconds attributed to goodput buckets "
    "(the goodput denominator; equals the sum of the productive and "
    "unproductive counters by construction)")
PRODUCTIVE_SECONDS = metrics.counter(
    "skytpu_train_productive_seconds_total",
    "Wall seconds spent in post-warmup train-step compute — the "
    "goodput numerator")
UNPRODUCTIVE_SECONDS = metrics.counter(
    "skytpu_train_unproductive_seconds_total",
    "Wall seconds NOT spent in productive compute, by named bucket "
    "(warmup_compile, input_bound, ckpt_stall, restart_replay, "
    "anomaly_pause, eval, host_other)",
    labelnames=("bucket",))
ANOMALIES = metrics.counter(
    "skytpu_train_anomalies_total",
    "Training anomalies detected by the watchdog (non_finite = "
    "NaN/Inf loss or grad, latched per excursion; loss_spike / "
    "grad_spike = delta beyond the spike factor x streaming P2 "
    "quantile)",
    labelnames=("kind",))
HOST_STEP_SECONDS = metrics.gauge(
    "skytpu_train_host_step_seconds",
    "This host's most recent train-step wall seconds — federated "
    "across the slice by the aggregate tier, the spread is the "
    "straggler signal skytpu top renders",
    labelnames=("host",))

# Per-step phases, render order. ``host_other`` is the constructed
# remainder (step wall minus every named phase) — the partition is
# exact by definition, and a fat host_other is itself a finding.
PHASES = ("data_wait", "h2d", "compute", "ckpt_save", "ckpt_wait",
          "eval", "anomaly_pause", "host_other")

# Cumulative goodput buckets, render order. Everything the cursor
# attributes lands in exactly one of these; ``productive`` is the
# goodput numerator and the rest are the named badput decomposition.
BUCKETS = ("productive", "warmup_compile", "input_bound", "ckpt_stall",
           "restart_replay", "anomaly_pause", "eval", "host_other")

# Step phase -> goodput bucket. ``compute`` maps to ``productive``
# except on the warmup step, where the XLA compile dominates the call
# and the whole phase is warmup_compile.
_PHASE_BUCKET = {
    "data_wait": "input_bound",
    "h2d": "input_bound",
    "compute": "productive",
    "ckpt_save": "ckpt_stall",
    "ckpt_wait": "ckpt_stall",
    "eval": "eval",
    "anomaly_pause": "anomaly_pause",
    "host_other": "host_other",
}

STAMPS_FILE = "goodput.json"


def enabled() -> bool:
    """Goodput recording is on unless explicitly disabled
    (``SKYTPU_GOODPUT=0`` — the bench's parity/overhead baseline)."""
    return os.environ.get("SKYTPU_GOODPUT", "1") != "0"


def host_id() -> str:
    """This host's identity in the slice — the runtime env contract's
    host index (runtime/driver.py), '0' for single-host runs."""
    return os.environ.get("SKYTPU_HOST_ID", "0")


class GoodputRecorder:
    """Per-step phase ledger + cumulative goodput accounting.

    The train loop drives it::

        gp = GoodputRecorder(param_count=cfg.num_params())
        with gp.account("restart_replay"):
            state = mgr.restore(target)          # outside-step bucket
        for step in ...:
            gp.step_start(step)
            with gp.phase("data_wait"):
                batch = next(batches)
            with gp.phase("compute"):
                state, m = step_fn(state, batch)
            gp.step_end(tokens=..., loss=..., grad_norm=...)

    Single-writer by design (the train loop), with a lock guarding the
    cumulative state so the metrics endpoint and tests can snapshot
    concurrently. Two exactness invariants hold at all times:

    * per step: the record's phases sum to its measured wall — the
      remainder is stored as ``host_other``, never dropped;
    * cumulatively: bucket totals sum to attributed elapsed wall
      (``snapshot`` folds the not-yet-attributed residue into
      ``host_other`` on the fly, so the books always balance).
    """

    def __init__(self, recorder: Optional[flight.FlightRecorder] = None,
                 host: Optional[str] = None, param_count: int = 0,
                 watch: Optional[flight.CompileWatch] = None,
                 calibrator: Optional[Any] = None,
                 enable: Optional[bool] = None):
        self.enabled = enabled() if enable is None else bool(enable)
        self.recorder = recorder if recorder is not None \
            else flight.RECORDER
        self.param_count = int(param_count)
        self.watch = watch
        self.calibrator = calibrator
        self._host = host if host is not None else host_id()
        self._lock = threading.Lock()
        now = time.monotonic()
        self._t_start = now
        self._t_last = now                     # guarded-by: _lock
        self._buckets = {b: 0.0 for b in BUCKETS}  # guarded-by: _lock
        self._steps = 0                        # guarded-by: _lock
        self._tokens = 0                       # guarded-by: _lock
        self._prior = {"elapsed_s": 0.0,
                       "buckets": {b: 0.0 for b in BUCKETS},
                       "steps": 0, "tokens": 0}
        # Loop-thread state (the single writer): the open step.
        self._warm = False
        self._step: Optional[int] = None
        self._step_t0 = 0.0
        self._step_ts = 0.0
        self._phases: Dict[str, float] = {}

    # -- cursor attribution (call with _lock held) --------------------------

    def _credit_locked(self, bucket: str, dur: float) -> None:
        if dur <= 0.0:
            return
        self._buckets[bucket] += dur
        WALL_SECONDS.inc(dur)
        if bucket == "productive":
            PRODUCTIVE_SECONDS.inc(dur)
        else:
            UNPRODUCTIVE_SECONDS.labels(bucket=bucket).inc(dur)

    def _advance_locked(self, now: float, bucket: str) -> None:
        self._credit_locked(bucket, now - self._t_last)
        self._t_last = now

    # -- outside-step attribution -------------------------------------------

    @contextlib.contextmanager
    def account(self, bucket: str) -> Iterator[None]:
        """Attribute the body's wall to ``bucket`` (restore ->
        restart_replay, init -> warmup_compile, final save/wait ->
        ckpt_stall). The gap since the last attribution point goes to
        host_other so the cursor never skips time."""
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket: {bucket}")
        if not self.enabled:
            yield
            return
        t_enter = time.monotonic()
        with self._lock:
            self._advance_locked(t_enter, "host_other")
        try:
            yield
        finally:
            now = time.monotonic()
            with self._lock:
                self._advance_locked(now, bucket)

    # -- the per-step ledger -------------------------------------------------

    def step_start(self, step: int) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            # The inter-step gap (logging, loop bookkeeping the caller
            # didn't wrap) is host_other — named phases start inside.
            self._advance_locked(now, "host_other")
        self._step = step
        self._step_t0 = now
        self._step_ts = time.time()
        self._phases = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Time one named slice of the open step. Disabled or
        outside a step it is a bare yield — the loop body never
        branches on recorder state."""
        if name not in _PHASE_BUCKET:
            raise ValueError(f"unknown step phase: {name}")
        if not self.enabled or self._step is None:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            self._phases[name] = self._phases.get(name, 0.0) + dt

    def step_end(self, tokens: int = 0, loss: Optional[float] = None,
                 grad_norm: Optional[float] = None
                 ) -> Optional[Dict[str, Any]]:
        """Close the open step: build the exact phase partition,
        credit the goodput buckets, publish metrics, and append the
        ``train_step`` flight record. Returns the record (tests/bench
        introspection) or None when disabled."""
        if not self.enabled or self._step is None:
            return None
        now = time.monotonic()
        wall = now - self._step_t0
        phases = dict(self._phases)
        named = sum(phases.values())
        other = wall - named
        if other < 0.0:
            # Clock granularity can make disjoint sub-timers overshoot
            # the outer bracket by an epsilon; the partition stays
            # exact by definition: wall IS the sum.
            other = 0.0
            wall = named
        phases["host_other"] = other
        step, warm = self._step, self._warm
        self._step = None
        self._warm = True

        with self._lock:
            for name in PHASES:
                if name not in phases:
                    continue
                bucket = _PHASE_BUCKET[name]
                if bucket == "productive" and not warm:
                    bucket = "warmup_compile"
                self._credit_locked(bucket, phases[name])
            self._t_last = self._step_t0 + wall
            self._steps += 1
            self._tokens += tokens

        # Device-truth attribution: calibrated EWMA when the trainer's
        # calibrator has sampled this program, else nothing (the
        # warmup step's wall is compile, not execution — estimating
        # from it would poison the device-seconds counter).
        compiled = self.watch.drain_new() if self.watch is not None \
            else []
        dev_ms = None
        if (warm and self.calibrator is not None
                and self.watch is not None
                and self.watch.last_key is not None):
            est = self.calibrator.estimate(self.watch.last_key)
            if est is not None:
                dev_ms = est * 1e3
        if dev_ms is None and warm:
            # Donated-state back-pressure makes the post-warmup call
            # wall converge to device step time; the compute phase is
            # the honest fallback estimate.
            dev_ms = phases.get("compute", 0.0) * 1e3
        flops = 6 * self.param_count * tokens \
            if (self.param_count and tokens) else 0

        # Counters and the record carry the SAME values — the tier-1
        # counter-deltas-match-record-sums gate depends on it.
        if warm and flops:
            attribution.DEVICE_FLOPS.inc(flops)
        if warm and dev_ms:
            attribution.DEVICE_SECONDS.inc(dev_ms / 1e3)
        HOST_STEP_SECONDS.labels(host=self._host).set(wall)
        GOODPUT_RATIO.set(self.snapshot()["goodput_ratio"])

        rec_fields: Dict[str, Any] = {
            "ts_s": self._step_ts, "step": step,
            "dur_s": round(wall, 6),
            "phases": {k: round(v * 1e3, 4) for k, v in phases.items()},
            "toks": tokens, "host": self._host, "warm": warm,
        }
        if warm:
            rec_fields["flops"] = flops
            rec_fields["dev_ms_est"] = round(dev_ms, 4)
        if compiled:
            rec_fields["compiled"] = compiled
        if loss is not None:
            rec_fields["loss"] = loss
        if grad_norm is not None:
            rec_fields["grad_norm"] = grad_norm
        self.recorder.record("train_step", **rec_fields)
        return dict(rec_fields, burst="train_step")

    # -- cumulative accounting ----------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative goodput state including restored stamps. The
        buckets sum to ``elapsed_s`` exactly: the not-yet-attributed
        residue since the last cursor advance folds into host_other."""
        now = time.monotonic()
        with self._lock:
            buckets = dict(self._buckets)
            residue = now - self._t_last
            elapsed = now - self._t_start
            steps, tokens = self._steps, self._tokens
        buckets["host_other"] += max(residue, 0.0)
        prior = self._prior
        total = {b: buckets[b] + prior["buckets"].get(b, 0.0)
                 for b in BUCKETS}
        elapsed_total = elapsed + prior["elapsed_s"]
        ratio = (total["productive"] / elapsed_total
                 if elapsed_total > 0 else 0.0)
        return {
            "host": self._host,
            "elapsed_s": elapsed_total,
            "session_elapsed_s": elapsed,
            "buckets": total,
            "goodput_ratio": ratio,
            "steps": steps + prior["steps"],
            "tokens": tokens + prior["tokens"],
        }

    # -- restart-surviving stamps --------------------------------------------

    def stamps(self) -> Dict[str, Any]:
        snap = self.snapshot()
        return {"version": 1, "elapsed_s": snap["elapsed_s"],
                "buckets": snap["buckets"], "steps": snap["steps"],
                "tokens": snap["tokens"]}

    def persist(self, directory: str) -> bool:
        """Atomically write cumulative stamps next to the checkpoints.
        Best-effort: a bucket-mounted or gs:// dir that rejects posix
        writes must never fail a save."""
        if not self.enabled:
            return False
        data = self.stamps()
        tmp = None
        try:
            os.makedirs(directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=directory,
                                       prefix=STAMPS_FILE + ".")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, os.path.join(directory, STAMPS_FILE))
            return True
        except OSError:
            if tmp is not None:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            return False

    def load_stamps(self, directory: str) -> bool:
        """Fold a previous incarnation's stamps into the cumulative
        totals (call once, before the loop). Missing or corrupt stamps
        are a fresh start, never a crash."""
        try:
            with open(os.path.join(directory, STAMPS_FILE),
                      encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(data, dict):
            return False
        buckets = data.get("buckets") or {}
        self._prior = {
            "elapsed_s": float(data.get("elapsed_s", 0.0)),
            "buckets": {b: float(buckets.get(b, 0.0)) for b in BUCKETS},
            "steps": int(data.get("steps", 0)),
            "tokens": int(data.get("tokens", 0)),
        }
        return True


# ---------------------------------------------------------------------------
# The loss/grad anomaly watchdog.

class AnomalyWatchdog:
    """Streaming NaN/Inf guards + spike detection over the losses the
    train loop ALREADY fetched (its logging cadence) — the watchdog
    never forces a device sync of its own.

    Non-finite values latch: one NaN excursion emits exactly one typed
    ``train.anomaly`` event and one incident bundle, however many
    logging intervals it spans, and the latch re-arms when values turn
    finite again. Spikes compare each |delta| against ``spike_factor``
    x the streaming P² quantile of PRIOR deltas (compare-then-fold,
    the tail detector's discipline), with a warmup floor and a
    cooldown so a noisy warmup or one excursion cannot storm the event
    log. ``forensics.capture_incident`` applies its own global rate
    limit on top.
    """

    def __init__(self, quantile: float = 0.99,
                 spike_factor: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 cooldown_steps: Optional[int] = None,
                 recorder: Optional[flight.FlightRecorder] = None,
                 goodput: Optional[GoodputRecorder] = None):
        if spike_factor is None:
            try:
                spike_factor = float(os.environ.get(
                    "SKYTPU_ANOMALY_SPIKE_FACTOR", "") or 4.0)
            except ValueError:
                spike_factor = 4.0
        if min_samples is None:
            try:
                min_samples = int(os.environ.get(
                    "SKYTPU_ANOMALY_MIN_SAMPLES", "") or 16)
            except ValueError:
                min_samples = 16
        if cooldown_steps is None:
            try:
                cooldown_steps = int(os.environ.get(
                    "SKYTPU_ANOMALY_COOLDOWN_STEPS", "") or 50)
            except ValueError:
                cooldown_steps = 50
        self.spike_factor = spike_factor
        self.min_samples = max(int(min_samples), 5)
        self.cooldown_steps = max(int(cooldown_steps), 0)
        self.recorder = recorder
        self.goodput = goodput
        self._loss_deltas = forensics.P2Quantile(quantile)
        self._grad_deltas = forensics.P2Quantile(quantile)
        self._last_loss: Optional[float] = None
        self._last_grad: Optional[float] = None
        self._non_finite = False           # the latch
        self._last_anomaly_step: Optional[int] = None

    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None
                ) -> Optional[Dict[str, Any]]:
        """Fold one logged (loss, grad_norm) sample in; returns the
        anomaly info dict when one fired, else None."""
        kind = None
        detail: Dict[str, Any] = {}
        bad_loss = not math.isfinite(loss)
        bad_grad = grad_norm is not None and not math.isfinite(grad_norm)
        if bad_loss or bad_grad:
            if self._non_finite:
                return None        # latched: this excursion already fired
            self._non_finite = True
            kind = "non_finite"
            detail["signal"] = "loss" if bad_loss else "grad_norm"
            # NaN/Inf never feed the estimators or the last-value
            # state — a poisoned baseline would mute spike detection
            # for the rest of the run.
        else:
            self._non_finite = False
            in_cooldown = (
                self._last_anomaly_step is not None
                and step - self._last_anomaly_step < self.cooldown_steps)
            if self._last_loss is not None:
                d = abs(loss - self._last_loss)
                thr = self._loss_deltas.value()
                if (not in_cooldown and kind is None
                        and self._loss_deltas.count >= self.min_samples
                        and thr is not None
                        and d > self.spike_factor * max(thr, 1e-12)):
                    kind = "loss_spike"
                    detail.update(delta=round(d, 6),
                                  threshold=round(thr, 6))
                self._loss_deltas.observe(d)
            if grad_norm is not None and self._last_grad is not None:
                d = abs(grad_norm - self._last_grad)
                thr = self._grad_deltas.value()
                if (not in_cooldown and kind is None
                        and self._grad_deltas.count >= self.min_samples
                        and thr is not None
                        and d > self.spike_factor * max(thr, 1e-12)):
                    kind = "grad_spike"
                    detail.update(delta=round(d, 6),
                                  threshold=round(thr, 6))
                self._grad_deltas.observe(d)
            self._last_loss = loss
            if grad_norm is not None:
                self._last_grad = grad_norm
        if kind is None:
            return None
        self._last_anomaly_step = step
        info: Dict[str, Any] = {"kind": kind, "step": step,
                                "loss": loss}
        if grad_norm is not None:
            info["grad_norm"] = grad_norm
        info.update(detail)
        ANOMALIES.labels(kind=kind).inc()
        gp = self.goodput
        if gp is not None and gp.enabled and gp._step is not None:
            # The capture wall is badput with a name: anomaly_pause,
            # not a mystery host_other bump in this step's ledger.
            with gp.phase("anomaly_pause"):
                self._emit(kind, info)
        else:
            self._emit(kind, info)
        return info

    def _emit(self, kind: str, info: Dict[str, Any]) -> None:
        tracing.add_event("train.anomaly", dict(info), echo=True)
        bundle = forensics.capture_incident(
            f"train-anomaly-{kind}", dict(info), recorder=self.recorder)
        if bundle:
            info["incident"] = os.path.basename(bundle)


# ---------------------------------------------------------------------------
# Ledger building + rendering (skytpu train-why, tests).

def train_records(records: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """The ``train_step`` subset of a flight record set."""
    return [r for r in records if r.get("burst") == "train_step"]


def ledger_for_step(records: List[Dict[str, Any]],
                    step: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
    """Phase ledger for one recorded step (default: the newest).
    None when the step was never recorded."""
    recs = train_records(records)
    rec = None
    if step is None:
        rec = recs[-1] if recs else None
    else:
        for r in recs:
            if r.get("step") == step:
                rec = r
    if rec is None:
        return None
    wall_ms = float(rec.get("dur_s", 0.0)) * 1e3
    raw = rec.get("phases") or {}
    phases = []
    for name in PHASES:
        if name in raw:
            ms = float(raw[name])
            phases.append({
                "phase": name, "ms": ms,
                "pct": 100.0 * ms / wall_ms if wall_ms else 0.0})
    named_ms = sum(p["ms"] for p in phases
                   if p["phase"] != "host_other")
    return {
        "step": rec.get("step"), "host": rec.get("host"),
        "wall_ms": wall_ms, "phases": phases, "named_ms": named_ms,
        "toks": int(rec.get("toks", 0)), "loss": rec.get("loss"),
        "grad_norm": rec.get("grad_norm"),
        "dev_ms_est": rec.get("dev_ms_est"),
        "compiled": rec.get("compiled") or [],
        "warm": bool(rec.get("warm", True)),
    }


def summarize_steps(records: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Aggregate phase distribution across every recorded step —
    where the RUN's wall went, not just one step's."""
    recs = train_records(records)
    if not recs:
        return None
    totals = {name: 0.0 for name in PHASES}
    wall_ms = 0.0
    toks = 0
    for r in recs:
        wall_ms += float(r.get("dur_s", 0.0)) * 1e3
        toks += int(r.get("toks", 0))
        for name, ms in (r.get("phases") or {}).items():
            if name in totals:
                totals[name] += float(ms)
    phases = [{"phase": n, "ms": totals[n],
               "pct": 100.0 * totals[n] / wall_ms if wall_ms else 0.0}
              for n in PHASES if totals[n] > 0.0]
    return {"steps": len(recs), "wall_ms": wall_ms, "toks": toks,
            "phases": phases,
            "named_ms": sum(p["ms"] for p in phases
                            if p["phase"] != "host_other")}


def render_step_ledger(ledger: Dict[str, Any], width: int = 28) -> str:
    """Human phase table for one step (the forensics ledger's visual
    language: ms, %, a # bar, and the sum-equals-wall footer)."""
    lines = [f"train step {ledger['step']} on host "
             f"{ledger.get('host', '?')}: wall "
             f"{ledger['wall_ms']:.2f} ms"]
    bits = []
    if ledger.get("toks"):
        bits.append(f"toks {ledger['toks']}")
    if ledger.get("loss") is not None:
        bits.append(f"loss {ledger['loss']:.4f}")
    if ledger.get("grad_norm") is not None:
        bits.append(f"grad {ledger['grad_norm']:.4f}")
    if ledger.get("dev_ms_est") is not None:
        bits.append(f"dev {float(ledger['dev_ms_est']):.2f} ms")
    if not ledger.get("warm", True):
        bits.append("WARMUP (compile step)")
    if ledger.get("compiled"):
        bits.append(f"COMPILED x{len(ledger['compiled'])}")
    if bits:
        lines.append("  " + "  ".join(bits))
    lines.append(f"  {'phase':<{width}} {'ms':>10} {'%':>6}")
    for ph in ledger["phases"]:
        bar = "#" * max(int(round(ph["pct"] / 2.5)), 0)
        lines.append(f"  {ph['phase']:<{width}} {ph['ms']:>10.2f} "
                     f"{ph['pct']:>5.1f}% {bar}")
    named_pct = (100.0 * ledger["named_ms"] / ledger["wall_ms"]
                 if ledger["wall_ms"] else 0.0)
    lines.append(f"  {'sum (= wall)':<{width}} "
                 f"{sum(p['ms'] for p in ledger['phases']):>10.2f} "
                 f"{'':>6} named {named_pct:.1f}%")
    return "\n".join(lines)


def render_summary(summary: Dict[str, Any], width: int = 28) -> str:
    """Human phase table for the whole recorded run."""
    lines = [f"all {summary['steps']} recorded steps: total wall "
             f"{summary['wall_ms']:.2f} ms, toks {summary['toks']}"]
    lines.append(f"  {'phase':<{width}} {'ms':>10} {'%':>6}")
    for ph in summary["phases"]:
        bar = "#" * max(int(round(ph["pct"] / 2.5)), 0)
        lines.append(f"  {ph['phase']:<{width}} {ph['ms']:>10.2f} "
                     f"{ph['pct']:>5.1f}% {bar}")
    named_pct = (100.0 * summary["named_ms"] / summary["wall_ms"]
                 if summary["wall_ms"] else 0.0)
    lines.append(f"  {'named':<{width}} {summary['named_ms']:>10.2f} "
                 f"{named_pct:>5.1f}%")
    return "\n".join(lines)
