"""SLO watchdog: declarative rules over federated fleet snapshots.

A small rule engine in the Google SRE-workbook style: each rule is
evaluated over TWO windows (a short one for responsiveness, a long one
for confidence) and alerts only when BOTH breach — one slow request or
a single scrape blip must not page. Breaches and recoveries are emitted
as typed events (``slo.breach`` / ``slo.recovered``, ``echo=True``) so
they land in the structured event log AND the daemon's stderr, exactly
like the skylet's autostop events.

Rule kinds (all windowed deltas clamp counter resets to zero — see
``aggregate.delta``):

  * ``histogram_quantile`` — e.g. TTFT p95 over the window > threshold
    seconds;
  * ``ratio`` — numerator/denominator counter increase, e.g. HTTP 5xx
    ratio (label filters select the numerator; prefix matches support
    ``code=~"5"``-style classes via ``label_prefix``);
  * ``rate`` — counter increase per second, e.g. rpc transport-failure
    rate;
  * ``heartbeat_staleness`` — now minus a unix-timestamp gauge
    (instantaneous: both windows see the same truth);
  * ``train_step_regression`` — mean step time over the window vs the
    fleet's trailing-median gauge (the trainer exports
    ``skytpu_train_step_median_seconds``), thresholded as a ratio;
  * ``component_dead`` — any component the health model reports dead
    (instantaneous).

Rules are declarative data: the defaults below, overridable by a JSON
file at ``<home>/slo_rules.json`` (a list of rule dicts with the same
field names).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.observability import (aggregate, forensics, health,
                                        metrics, tracing)

SLO_BREACHES = metrics.counter(
    "skytpu_slo_breaches_total",
    "SLO watchdog breach events emitted, by rule", labelnames=("rule",))
SLO_ACTIVE = metrics.gauge(
    "skytpu_slo_alert_active",
    "1 while a rule's alert is firing (multi-window burn rate: both "
    "windows breached)", labelnames=("rule",))
SLO_EVALUATIONS = metrics.counter(
    "skytpu_slo_evaluations_total", "SLO watchdog evaluation passes")

RULES_FILENAME = "slo_rules.json"


@dataclasses.dataclass
class SloRule:
    """One declarative objective. ``threshold`` semantics depend on
    ``kind`` (seconds, ratio 0..1, events/s, staleness seconds, or a
    regression factor)."""

    name: str
    kind: str
    threshold: float
    metric: str = ""
    quantile: float = 0.95
    # Numerator label filters for `ratio` (exact and prefix matches).
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    label_prefix: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Series dropped from BOTH sides of a ratio (and from rate/ratio
    # numerators): label -> excluded values. The default 5xx rule
    # excludes monitoring routes — the watchdog's own /metrics scrapes
    # and /healthz probes would otherwise pad the denominator with
    # steady 200s and dilute the error ratio of low-traffic services.
    exclude_labels: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict)
    denominator: str = ""            # ratio: defaults to same metric
    baseline_metric: str = ""        # train_step_regression
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    # Ratio/rate rules ignore windows with fewer events than this: a
    # single failed request out of one request is a 100% error ratio
    # and exactly the page the burn-rate design exists to avoid.
    min_events: float = 1.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SloRule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown SLO rule fields {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


DEFAULT_RULES: List[SloRule] = [
    SloRule("ttft-p95", "histogram_quantile", threshold=10.0,
            metric="skytpu_ttft_seconds", quantile=0.95),
    SloRule("http-5xx-ratio", "ratio", threshold=0.05,
            metric="skytpu_http_requests_total",
            label_prefix={"code": "5"}, min_events=5.0,
            exclude_labels={"route": ["/metrics", "/healthz",
                                      "/health"]}),
    SloRule("rpc-transport-failures", "rate", threshold=0.2,
            metric="skytpu_rpc_failures_total",
            labels={"kind": "transport"}),
    SloRule("skylet-heartbeat", "heartbeat_staleness", threshold=120.0,
            metric="skytpu_skylet_last_tick_timestamp_seconds"),
    # The runtime retrace guard: an engine that compiled ANY program
    # after declaring warmup complete is stalling live requests on XLA
    # (tens of seconds on an 8B model) — threshold 0 means one
    # unexpected compile in both windows pages.
    SloRule("unexpected-compiles", "rate", threshold=0.0,
            metric="skytpu_unexpected_compiles_total"),
    # Sustained QoS load-shedding: sheds are the fleet protecting
    # itself (a hot tenant over its bucket, or an overloaded queue) —
    # working as designed in a burst, but a shed rate held across both
    # windows means capacity or quota is mis-sized and real traffic is
    # bouncing. The burn-rate autoscaler usually reacts first; this
    # rule pages when it can't (max_replicas hit, scaling frozen).
    SloRule("qos-shed-rate", "rate", threshold=1.0,
            metric="skytpu_qos_shed_total", min_events=5.0),
    SloRule("train-step-regression", "train_step_regression",
            threshold=1.5, metric="skytpu_train_step_seconds",
            baseline_metric="skytpu_train_step_median_seconds",
            min_events=3.0),
    # The training goodput floor, expressed as its complement: badput
    # (unproductive wall, named buckets) over attributed elapsed wall.
    # Breaching 0.5 in both windows means the run spent the majority
    # of the last minutes NOT in productive compute — an input-bound
    # pipeline, a checkpoint stall storm, or restart thrash — with the
    # named-bucket counters saying which. warmup_compile is excluded
    # from the numerator (cold-start compile is expected badput, not a
    # page), and min_events keeps windows with almost no attributed
    # wall (idle or just-started processes) from paging.
    SloRule("train-goodput-floor", "ratio", threshold=0.5,
            metric="skytpu_train_unproductive_seconds_total",
            denominator="skytpu_train_wall_seconds_total",
            exclude_labels={"bucket": ["warmup_compile"]},
            min_events=30.0),
    SloRule("component-alive", "component_dead", threshold=0.0),
    # Analytical HBM pressure from the engine's ledger: capacity
    # components (weights, pools, workspace) summed against the
    # published limit. Occupancy views (kv_used, prefix_pinned) are
    # excluded — they live INSIDE kv_pool/prefix_pool and would
    # double-count. Pages before the allocator does, while there is
    # still headroom to act (evict prefixes, shrink max_batch).
    SloRule("hbm-headroom", "hbm_headroom", threshold=0.92,
            metric="skytpu_hbm_bytes",
            baseline_metric="skytpu_hbm_limit_bytes",
            exclude_labels={"component": ["kv_used", "prefix_pinned"]}),
]


def load_rules(path: Optional[str] = None) -> List[SloRule]:
    """Rules from ``<home>/slo_rules.json`` when present, else the
    defaults. A broken file falls back loudly (typed event) rather
    than silently disabling the watchdog."""
    if path is None:
        from skypilot_tpu.utils import paths
        path = os.path.join(paths.home(), RULES_FILENAME)
    if not os.path.exists(path):
        return list(DEFAULT_RULES)
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        return [SloRule.from_dict(d) for d in raw]
    except (OSError, ValueError, TypeError) as e:
        tracing.add_event("slo.rules_invalid",
                          attrs={"path": path,
                                 "error_type": type(e).__name__,
                                 "message": str(e)[:500]},
                          echo=True)
        return list(DEFAULT_RULES)


# ---------------------------------------------------------------------------
# Evaluation.

Snapshot = Tuple[float, Dict[str, dict], List[Dict[str, Any]]]
#          (ts,    families,             components)


def _excluded_fn(rule: SloRule) -> Callable[[Dict[str, str]], bool]:
    def excluded(labels: Dict[str, str]) -> bool:
        return any(labels.get(k) in vals
                   for k, vals in rule.exclude_labels.items())
    return excluded


def _match_fn(rule: SloRule) -> Callable[[Dict[str, str]], bool]:
    excluded = _excluded_fn(rule)

    def ok(labels: Dict[str, str]) -> bool:
        if excluded(labels):
            return False
        for k, v in rule.labels.items():
            if labels.get(k) != v:
                return False
        for k, pfx in rule.label_prefix.items():
            if not str(labels.get(k, "")).startswith(pfx):
                return False
        return True
    return ok


def _window_start(history: List[Snapshot], now: float,
                  window_s: float) -> Optional[Snapshot]:
    """The newest snapshot at least ``window_s`` old (the window's
    left edge); None when history doesn't reach back that far."""
    best = None
    for snap in history:
        if now - snap[0] >= window_s:
            best = snap
        else:
            break
    return best


def _eval_window(rule: SloRule, start: Optional[Snapshot],
                 end: Snapshot) -> Optional[float]:
    """The rule's measured value over one window; None = not enough
    data (never a breach)."""
    ts, families, components = end
    prev = start[1] if start is not None else None
    span = ts - start[0] if start is not None else None
    if rule.kind == "component_dead":
        return float(sum(1 for c in components
                         if c["status"] == health.DEAD))
    if rule.kind == "heartbeat_staleness":
        # Worst instance, not freshest: agg="max" would let one fresh
        # skylet mask every wedged sibling forever.
        last = aggregate.sample_value(families, rule.metric, agg="min")
        if not last:
            return None
        return ts - last
    if rule.kind == "histogram_quantile":
        if prev is None:
            return None
        count = aggregate.delta(prev, families, rule.metric,
                                sample_name=f"{rule.metric}_count")
        if count is None or count < rule.min_events:
            return None
        return aggregate.histogram_quantile(prev, families, rule.metric,
                                            rule.quantile)
    if rule.kind == "ratio":
        if prev is None:
            return None
        num = aggregate.filtered_delta(prev, families, rule.metric,
                              _match_fn(rule))
        denom_metric = rule.denominator or rule.metric
        excluded = _excluded_fn(rule)
        denom = aggregate.filtered_delta(prev, families, denom_metric,
                                lambda labels: not excluded(labels))
        if num is None or not denom or denom < rule.min_events:
            return None
        return num / denom
    if rule.kind == "rate":
        if prev is None or not span:
            return None
        inc = aggregate.filtered_delta(prev, families, rule.metric,
                              _match_fn(rule))
        if inc is None:
            return None
        return inc / span
    if rule.kind == "train_step_regression":
        if prev is None:
            return None
        n = aggregate.delta(prev, families, rule.metric,
                            sample_name=f"{rule.metric}_count")
        s = aggregate.delta(prev, families, rule.metric,
                            sample_name=f"{rule.metric}_sum")
        baseline = aggregate.sample_value(families, rule.baseline_metric,
                                          agg="max")
        if not n or n < rule.min_events or s is None or not baseline:
            return None
        return (s / n) / baseline
    if rule.kind == "hbm_headroom":
        # Instantaneous gauge ratio: ledger components over the limit.
        # sample_value has no exclusion filter, so walk the family by
        # hand — exclude_labels drops the occupancy views that overlap
        # the capacity components. Each serving instance has its OWN
        # HBM: federated gauges arrive instance-labeled (never summed),
        # so group by instance and page on the worst ratio — summing
        # across replicas would breach on fleet size, not memory.
        fam = families.get(rule.metric)
        lim_fam = families.get(rule.baseline_metric)
        if fam is None or lim_fam is None:
            return None
        excluded = _excluded_fn(rule)
        inst_l = aggregate.INSTANCE_LABEL
        used: Dict[str, float] = {}
        for labels, value in fam["samples"]:
            if "__name__" in labels or excluded(labels):
                continue
            inst = labels.get(inst_l, "")
            used[inst] = used.get(inst, 0.0) + value
        limits: Dict[str, float] = {}
        for labels, value in lim_fam["samples"]:
            if "__name__" in labels:
                continue
            inst = labels.get(inst_l, "")
            limits[inst] = max(limits.get(inst, 0.0), value)
        fallback = max(limits.values(), default=0.0)
        ratios = [u / (limits.get(inst) or fallback)
                  for inst, u in used.items()
                  if limits.get(inst) or fallback]
        return max(ratios) if ratios else None
    return None


_INSTANT_KINDS = ("component_dead", "heartbeat_staleness",
                  "hbm_headroom")


def evaluate_rule(rule: SloRule, history: List[Snapshot]
                  ) -> Tuple[bool, Optional[float], Optional[float]]:
    """Multi-window verdict: ``(breached, short_value, long_value)``.
    Instantaneous kinds read the latest snapshot only; windowed kinds
    breach when BOTH windows exceed the threshold."""
    if not history:
        return False, None, None
    end = history[-1]
    now = end[0]
    if rule.kind in _INSTANT_KINDS:
        v = _eval_window(rule, None, end)
        return (v is not None and v > rule.threshold), v, v
    short = _eval_window(
        rule, _window_start(history, now, rule.short_window_s), end)
    long_ = _eval_window(
        rule, _window_start(history, now, rule.long_window_s), end)
    breached = (short is not None and short > rule.threshold
                and long_ is not None and long_ > rule.threshold)
    return breached, short, long_


class Watchdog:
    """Periodically snapshots the fleet, evaluates rules, and emits
    ``slo.breach``/``slo.recovered`` typed events on transitions.

    ``snapshot_fn`` returns ``(families, components)``; the default
    federates over :func:`aggregate.discover_endpoints` and runs the
    health model — the API server installs its own that includes its
    in-process registry.
    """

    def __init__(self, rules: Optional[List[SloRule]] = None,
                 interval_s: float = 15.0,
                 snapshot_fn: Optional[Callable[
                     [], Tuple[Dict[str, dict],
                               List[Dict[str, Any]]]]] = None,
                 history_s: float = 900.0):
        self.rules = list(rules) if rules is not None else load_rules()
        self.interval_s = interval_s
        self._snapshot_fn = snapshot_fn or self._default_snapshot
        self._history_s = history_s
        self._history: List[Snapshot] = []            # guarded-by: _lock
        self._active: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_snapshot():
        snap = aggregate.federate(aggregate.discover_endpoints())
        return snap.families, health.fleet_health()

    # -- evaluation --------------------------------------------------------
    def observe(self, families: Dict[str, dict],
                components: List[Dict[str, Any]],
                ts: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one snapshot and evaluate every rule. Returns the
        transition events emitted this pass (tests drive this
        directly; the thread loop calls it with fresh federation)."""
        ts = time.time() if ts is None else ts
        snap: Snapshot = (ts, families, components)
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self._history.append(snap)
            cutoff = ts - self._history_s
            while len(self._history) > 2 and self._history[0][0] < cutoff:
                self._history.pop(0)
            history = list(self._history)
        SLO_EVALUATIONS.inc()
        for rule in self.rules:
            breached, short, long_ = evaluate_rule(rule, history)
            attrs = {"rule": rule.name, "kind": rule.kind,
                     "threshold": rule.threshold,
                     "short_window_value": short,
                     "long_window_value": long_}
            if rule.kind == "component_dead":
                attrs["dead_components"] = [
                    f"{c['component']}/{c['instance']}"
                    for c in components
                    if c["status"] == health.DEAD][:10]
            with self._lock:
                was_active = rule.name in self._active
                if breached and not was_active:
                    self._active[rule.name] = {
                        "rule": rule.name, "since": ts, "attrs": attrs}
                elif not breached and was_active:
                    del self._active[rule.name]
            if breached and not was_active:
                SLO_BREACHES.labels(rule=rule.name).inc()
                SLO_ACTIVE.labels(rule=rule.name).set(1)
                # Incident snapshot (observability/forensics.py): the
                # breach TRANSITION is the one moment the evidence —
                # flight-ring tail, recent events, metrics, pinned
                # tail exemplars — is still in memory; capture it to a
                # GC'd bundle and link the dir from the breach event
                # (`skytpu incidents show <name>` reads it back).
                # Contained: a full disk must not kill the watchdog.
                try:
                    inc = forensics.capture_incident(
                        rule.name, attrs,
                        health={"components": components})
                except Exception:  # noqa: BLE001
                    inc = None
                if inc:
                    attrs["incident"] = os.path.basename(inc)
                tracing.add_event("slo.breach", attrs=attrs, echo=True)
                transitions.append({"event": "slo.breach", **attrs})
            elif not breached and was_active:
                SLO_ACTIVE.labels(rule=rule.name).set(0)
                tracing.add_event("slo.recovered", attrs=attrs,
                                  echo=True)
                transitions.append({"event": "slo.recovered", **attrs})
        return transitions

    def tick(self) -> List[Dict[str, Any]]:
        """One full pass: snapshot + evaluate. Snapshot failures are
        contained (a watchdog that dies of a scrape error watches
        nothing)."""
        try:
            families, components = self._snapshot_fn()
        except Exception as e:  # noqa: BLE001
            tracing.add_event("slo.snapshot_failed",
                              attrs={"error_type": type(e).__name__,
                                     "message": str(e)[:500]},
                              echo=True)
            return []
        return self.observe(families, components)

    def active_alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._active.values()]

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="slo-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()
            try:
                tracing.flush_periodic(min_new_records=64,
                                       max_age_s=self.interval_s * 2)
            except OSError:
                pass
